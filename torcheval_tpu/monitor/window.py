"""Tumbling / sliding bucket-of-epochs windows built on existing states.

:class:`SlidingWindow` wraps an array-state metric and keeps ``buckets``
copies of each sufficient statistic, stacked on a new leading axis.
Every ``update`` accumulates into bucket 0 *through the inner metric's
own kernel* — inside the same traced program, so the fused-collection
and engine-scan paths still run one dispatch.  Off the hot path,
:meth:`SlidingWindow.advance` rotates the buckets (host-side, e.g. once
per epoch or per wall-clock minute): bucket 0 becomes bucket 1, the
oldest bucket falls off, and a fresh zero bucket opens.

``compute()`` sums the buckets and evaluates the inner metric on the
sum — the reading always covers the last ``buckets`` epochs (a sliding
window with bucket granularity).  ``buckets=1`` is a tumbling window:
``advance()`` simply resets the statistics.

Unlike the per-sample ring buffers of the ``window/`` namespace
(:class:`~torcheval_tpu.metrics._buffer.RingWindowMixin`, whose
host-side cursors make them unfusable), the bucket states here are plain
fixed-shape arrays and the update is pure traced arithmetic — the
wrapper passes ``MetricCollection._check_fusable`` and is bit-identical
between the fused and unfused paths.

Requirements on the inner metric: all states are fixed-shape arrays and
*additive* — ``merge_state`` semantics are elementwise addition of
states (true of every counter/binned metric: accuracy, F1, confusion
matrix, histogram-binned AUROC/calibration, ...).
"""

from __future__ import annotations

from typing import Any, Iterable

import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.metric import (
    DeviceLike,
    Metric,
    _is_array,
)

__all__ = ["SlidingWindow"]


class SlidingWindow(Metric):
    """Sliding window of ``buckets`` epochs over ``metric``'s statistics.

    The window states are registered on the wrapper itself (same names
    as the inner metric, with a leading ``(buckets,)`` axis), so
    ``state_dict`` / checkpoint-resume round-trips the whole window; the
    epoch cursor rides along under the ``"window_epochs"`` extra key,
    mirroring the ring-window bookkeeping convention.
    """

    _EPOCH_META_KEY = "window_epochs"

    def __init__(
        self,
        metric: Metric,
        *,
        buckets: int,
        device: DeviceLike = None,
    ) -> None:
        if not isinstance(metric, Metric):
            raise TypeError(
                f"SlidingWindow wraps a Metric instance; got "
                f"{type(metric).__name__}."
            )
        if buckets < 1:
            raise ValueError(f"`buckets` must be >= 1; got {buckets}.")
        for name, default in metric._state_name_to_default.items():
            if not _is_array(default):
                raise TypeError(
                    "SlidingWindow requires fixed-shape array states; "
                    f"{type(metric).__name__}.{name} is a "
                    f"{type(default).__name__}."
                )
        super().__init__(device=device)
        self._inner = metric
        self.buckets = int(buckets)
        self._epochs = 0
        self._supports_mask = bool(type(metric)._supports_mask)
        for name, default in metric._state_name_to_default.items():
            default = jnp.asarray(default)
            self._add_state(
                name,
                jnp.zeros((self.buckets,) + default.shape, default.dtype),
            )

    def __getattr__(self, name: str) -> Any:
        # Config attributes (``num_classes`` for health label bounds,
        # ``average``, ...) read through to the inner metric; window
        # states live on the wrapper and never reach here.
        if name.startswith("__") or name == "_inner":
            raise AttributeError(name)
        inner = self.__dict__.get("_inner")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)

    # -------------------------------------------------------- lifecycle
    @property
    def inner(self) -> Metric:
        """The wrapped metric (used as compute/update scratch)."""
        return self._inner

    @property
    def epochs_advanced(self) -> int:
        """How many times :meth:`advance` has rotated the window."""
        return self._epochs

    def update(self, *args: Any, **kwargs: Any) -> "SlidingWindow":
        # Route the batch through the inner metric's own update kernel
        # with bucket 0 installed as its live state, then write the
        # result back into row 0 — pure traced array ops, one program.
        inner = self._inner
        names = list(self._state_name_to_default)
        for name in names:
            setattr(inner, name, getattr(self, name)[0])
        inner.update(*args, **kwargs)
        for name in names:
            setattr(
                self, name, getattr(self, name).at[0].set(getattr(inner, name))
            )
        return self

    def advance(self) -> "SlidingWindow":
        """Rotate the window one epoch: open a fresh bucket 0, drop the
        oldest.  Host-side — call between epochs, never on the hot path."""
        for name in self._state_name_to_default:
            st = getattr(self, name)
            setattr(
                self,
                name,
                jnp.concatenate([jnp.zeros_like(st[:1]), st[:-1]], axis=0),
            )
        self._epochs += 1
        return self

    def compute(self) -> Any:
        inner = self._inner
        for name in self._state_name_to_default:
            setattr(inner, name, getattr(self, name).sum(axis=0))
        return inner.compute()

    def merge_state(self, metrics: Iterable["SlidingWindow"]) -> "SlidingWindow":
        # Elementwise addition per bucket — the additive-state contract
        # that also underlies compute()'s bucket sum.
        metrics = list(metrics)
        for m in metrics:
            if not isinstance(m, SlidingWindow) or m.buckets != self.buckets:
                raise ValueError(
                    "merge_state requires SlidingWindow peers with "
                    f"buckets={self.buckets}; got {m!r}."
                )
        import jax

        for name in self._state_name_to_default:
            acc = getattr(self, name)
            for m in metrics:
                acc = acc + jax.device_put(getattr(m, name), self.device)
            setattr(self, name, acc)
        return self

    def reset(self) -> "SlidingWindow":
        super().reset()
        self._inner.reset()
        self._epochs = 0
        return self

    def to(self, device: DeviceLike, *args: Any, **kwargs: Any) -> "SlidingWindow":
        super().to(device, *args, **kwargs)
        self._inner.to(device, *args, **kwargs)
        return self

    # ------------------------------------------------------- checkpoint
    # The epoch cursor is host-side bookkeeping; it rides state_dict
    # under an extra key (the RingWindowMixin convention) so
    # checkpoint-resume restores the rotation count.
    def state_dict(self):
        out = super().state_dict()
        out[self._EPOCH_META_KEY] = np.asarray(
            [self.buckets, self._epochs], dtype=np.int64
        )
        return out

    def load_state_dict(self, state_dict, strict: bool = True) -> None:
        import jax

        state_dict = dict(state_dict)
        meta = state_dict.pop(self._EPOCH_META_KEY, None)
        if meta is not None:
            buckets, epochs = (int(v) for v in jax.device_get(meta))
            if buckets != self.buckets:
                raise RuntimeError(
                    f"Checkpoint was written with buckets={buckets}; this "
                    f"SlidingWindow has buckets={self.buckets}."
                )
            self._epochs = epochs
        super().load_state_dict(state_dict, strict=strict)
