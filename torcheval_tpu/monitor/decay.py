"""Exponential time-decay folded into existing counter/binned states.

:class:`Decayed` wraps any array-state metric and multiplies every state
by a constant factor ``decay`` *inside the same traced update* before the
inner metric's accumulation runs — the decay is one fused multiply on
state already resident in registers, NOT a ring buffer: the hot path
stays a single dispatch and the state footprint is unchanged.

The recurrence after ``n`` updates is

.. math::

    s_n = d \\cdot s_{n-1} + x_n = \\sum_{i=1}^{n} d^{\\,n-i} x_i

so a reading computed from the decayed sufficient statistics weights the
most recent batch at 1 and a batch ``k`` updates old at ``d^k`` — an
exponentially-weighted moving version of the same metric.  With
``half_life_updates=N`` the factor is ``0.5 ** (1/N)``: a batch's
contribution halves every ``N`` updates.

Fused/scan exactness: when an ``update`` carries a validity ``mask``
(the bucketing / engine-scan plumbing of ``metrics/_bucket.py``), the
decay factor is ``where(any_valid, d, 1.0)`` — a fully-masked step (an
engine pad step) multiplies by exactly ``1.0``, which is bit-exact, so
the scan path with pad steps stays bit-identical to the per-batch path
without them.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

import jax.numpy as jnp

from torcheval_tpu.metrics.metric import (
    DeviceLike,
    Metric,
    _is_array,
)

__all__ = ["Decayed"]


def _resolve_decay(
    decay: Optional[float], half_life_updates: Optional[float]
) -> float:
    if (decay is None) == (half_life_updates is None):
        raise ValueError(
            "Pass exactly one of `decay=` (per-update factor in (0, 1)) "
            "or `half_life_updates=` (updates until a batch's weight "
            f"halves); got decay={decay!r}, "
            f"half_life_updates={half_life_updates!r}."
        )
    if decay is not None:
        if not 0.0 < decay < 1.0:
            raise ValueError(
                f"`decay` must lie in (0, 1); got {decay!r}. decay=1 is a "
                "plain lifetime metric — drop the wrapper instead."
            )
        return float(decay)
    if half_life_updates <= 0:
        raise ValueError(
            f"`half_life_updates` must be positive; got {half_life_updates!r}."
        )
    return float(0.5 ** (1.0 / float(half_life_updates)))


class Decayed(Metric):
    """Exponentially time-decayed view of ``metric``.

    The wrapper owns no state of its own: it *shares* the inner metric's
    state registry, decays those states in the traced update, and
    delegates ``compute``/``merge_state``/checkpointing.  It therefore
    composes with every existing code path — ``MetricCollection`` fusion,
    the engine scan, ``state_dict`` round-trips — with zero extra HBM.

    Only metrics whose states are all plain arrays are supported (buffer
    metrics defer their math to ``compute`` where a decay multiply has
    nothing to fold into).  Integer counter states are cast to float32 at
    wrap time so the fractional decay is representable.
    """

    def __init__(
        self,
        metric: Metric,
        *,
        decay: Optional[float] = None,
        half_life_updates: Optional[float] = None,
        device: DeviceLike = None,
    ) -> None:
        if not isinstance(metric, Metric):
            raise TypeError(
                f"Decayed wraps a Metric instance; got {type(metric).__name__}."
            )
        for name, default in metric._state_name_to_default.items():
            if not _is_array(default):
                raise TypeError(
                    f"Decayed requires array states; {type(metric).__name__}"
                    f".{name} is a {type(default).__name__} (buffer-style "
                    "metrics have no accumulated statistic to decay)."
                )
        super().__init__(device=device)
        self._decay = _resolve_decay(decay, half_life_updates)
        self._inner = metric
        # Share the inner registry: the wrapper's Metric-inherited
        # state_dict/reset/load walk the same names, and attribute
        # forwarding (below) makes the inner's live arrays *be* the
        # wrapper's states.
        self._state_name_to_default = metric._state_name_to_default
        self._device = metric._device
        self._supports_mask = bool(type(metric)._supports_mask)
        # Fractional decay needs float state; patch integer counters
        # (live state AND the shared registry default) to float32.
        for name, default in list(metric._state_name_to_default.items()):
            if jnp.issubdtype(jnp.asarray(default).dtype, jnp.integer):
                metric._state_name_to_default[name] = jnp.asarray(
                    default, dtype=jnp.float32
                )
                setattr(
                    metric, name, getattr(metric, name).astype(jnp.float32)
                )

    # ------------------------------------------------------- forwarding
    # States live on the inner metric.  Writes to registered state names
    # land there (the fused collection installs traced states via
    # setattr); reads of anything the wrapper lacks (states,
    # ``num_classes`` for health label bounds, ...) fall through.
    def __setattr__(self, name: str, value: Any) -> None:
        inner = self.__dict__.get("_inner")
        if inner is not None and name in inner._state_name_to_default:
            setattr(inner, name, value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str) -> Any:
        if name.startswith("__") or name == "_inner":
            raise AttributeError(name)
        inner = self.__dict__.get("_inner")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)

    # -------------------------------------------------------- lifecycle
    @property
    def decay(self) -> float:
        """The per-update multiplicative factor."""
        return self._decay

    @property
    def inner(self) -> Metric:
        """The wrapped metric (shares its states with this wrapper)."""
        return self._inner

    def update(self, *args: Any, **kwargs: Any) -> "Decayed":
        inner = self._inner
        mask = kwargs.get("mask")
        if mask is None:
            factor: Any = self._decay
        else:
            # A fully-masked step (engine pad step) must be an exact
            # no-op: x * 1.0 is bit-identical to x, so the scan path
            # (which runs pad steps) matches the per-batch path (which
            # never sees them) bit for bit.
            factor = jnp.where(
                jnp.sum(mask) > 0,
                jnp.float32(self._decay),
                jnp.float32(1.0),
            )
        for name in inner._state_name_to_default:
            setattr(inner, name, getattr(inner, name) * factor)
        inner.update(*args, **kwargs)
        return self

    def compute(self) -> Any:
        return self._inner.compute()

    def merge_state(self, metrics: Iterable["Decayed"]) -> "Decayed":
        metrics = list(metrics)
        for m in metrics:
            if not isinstance(m, Decayed) or m._decay != self._decay:
                raise ValueError(
                    "merge_state requires Decayed peers with the same "
                    f"decay factor {self._decay!r}; got {m!r}."
                )
        self._inner.merge_state([m._inner for m in metrics])
        return self

    def to(self, device: DeviceLike, *args: Any, **kwargs: Any) -> "Decayed":
        self._inner.to(device, *args, **kwargs)
        object.__setattr__(self, "_device", self._inner._device)
        return self

    def __setstate__(self, state: Any) -> None:
        super().__setstate__(state)
        # Pickling snapshots the shared registry into two independent
        # dicts (one per object); re-establish sharing so post-restore
        # state_dict/reset on either object stay in lockstep.
        object.__setattr__(
            self, "_state_name_to_default", self._inner._state_name_to_default
        )
