"""Streaming distribution digest for the monitor family.

:class:`StreamDigest` tracks a latency / score / loss *distribution* —
not just a mean — in a fixed-size mergeable state: the dyadic compactor
ladder of :mod:`torcheval_tpu.ops.rank_sketch` (``levels`` levels of
``bins`` sub-bins, per-level bin width doubling, so 32 levels × 64 bins
= 8 KB of int32 counters span nine decades of latency at ≤ 1/64
relative value error).  One fused dispatch per batch (the same
:func:`~torcheval_tpu.metrics._fuse.accumulate` path as every counter
metric), integer-add merge (associative and bit-deterministic across
merge orders — fleet rollups of per-host latency digests are exact
arithmetic), and deterministic quantile reads (each quantile returns
its bin's left edge, never an interpolation, so every merge order
reports the identical p50/p90/p99).

It is a regular :class:`~torcheval_tpu.metrics.Metric`: it joins
collections, checkpoints bit-exactly, folds ``mask=`` (so it is
``bucket=``/``slices=`` eligible), and ships whole-state through
``fleet_merge`` at O(levels × bins) bytes.  See :doc:`/sketch` for the
ladder layout and error table.
"""

from typing import Iterable, Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.ops.rank_sketch import (
    ladder_counts,
    ladder_edges,
    ladder_fill,
    ladder_quantiles,
)

__all__ = ["StreamDigest"]


def _digest_kernel(values, edges, mask=None):
    # Module-level: its identity is part of the fused-dispatch cache key.
    return ladder_counts(values, edges, mask=mask)


class StreamDigest(Metric[jax.Array]):
    """Mergeable quantile digest over a non-negative value stream.

    ``base`` is the resolution floor (values below it land in level 0's
    uniform bins with absolute error ≤ ``base/bins``); above it the
    relative error is ≤ ``1/bins``.  ``compute()`` returns the
    configured ``quantiles`` (default p50/p90/p99) as one array, or the
    empty sentinel before any update."""

    _supports_mask = True

    def __init__(
        self,
        *,
        base: float = 1e-4,
        levels: int = 32,
        bins: int = 64,
        quantiles: Tuple[float, ...] = (0.5, 0.9, 0.99),
        device=None,
    ) -> None:
        super().__init__(device=device)
        self.base = float(base)
        self.levels = int(levels)
        self.bins = int(bins)
        self.quantiles = tuple(float(q) for q in quantiles)
        for q in self.quantiles:
            if not 0.0 < q <= 1.0:
                raise ValueError(f"quantiles must lie in (0, 1], got {q}")
        self._add_state("edges", ladder_edges(self.base, self.levels, self.bins))
        self._add_state(
            "counts", jnp.zeros(self.levels * self.bins, jnp.int32)
        )

    def update(self, values, *, mask=None) -> "StreamDigest":
        values = jnp.asarray(values)
        (self.counts,) = accumulate(
            _digest_kernel, (self.counts,), values, self.edges, mask=mask
        )
        return self

    def compute(self) -> jax.Array:
        """The configured quantile values; empty array before any
        update."""
        if int(self.counts.sum()) == 0:
            return jnp.zeros(0)
        return ladder_quantiles(self.counts, self.edges, self.quantiles)

    def quantile(self, q: float) -> jax.Array:
        """One ad-hoc quantile read (deterministic left-edge value)."""
        return ladder_quantiles(self.counts, self.edges, (float(q),))[0]

    def fill(self) -> jax.Array:
        """Per-level fill counters — how much mass each rung of the
        weight ladder holds (diagnostic for choosing ``base``/``levels``)."""
        return ladder_fill(self.counts, self.levels)

    def merge_state(self, metrics: Iterable["StreamDigest"]) -> "StreamDigest":
        metrics = list(metrics)
        for m in metrics:
            if (m.base, m.levels, m.bins) != (self.base, self.levels, self.bins):
                raise ValueError(
                    "digest merge requires identical ladder geometry: "
                    f"(base={m.base}, levels={m.levels}, bins={m.bins}) vs "
                    f"(base={self.base}, levels={self.levels}, bins={self.bins})"
                )
        merge_add(self, metrics, "counts")
        return self
