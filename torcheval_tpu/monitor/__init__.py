"""Live model-quality monitor: decayed / windowed metric variants,
slice-wise computation, and the streaming quality exporter.

The three pieces (see ``docs/source/monitor.rst`` for the cookbook):

* :class:`Decayed` — exponential time-decay folded into an existing
  metric's counter/binned states, inside the same fused update (no ring
  buffers on the hot path).
* :class:`SlidingWindow` — a tumbling/sliding bucket-of-epochs window
  over the same states; ``advance()`` rotates epochs off the hot path.
* ``slices=K`` on :class:`~torcheval_tpu.metrics.MetricCollection` —
  per-slice figures via masked segment reductions inside the one fused
  or engine-scan dispatch.
* :func:`~torcheval_tpu.monitor.quality.publish` — streams every figure
  into the telemetry ring as :class:`QualityEvent`s (Prometheus gauges,
  ``report()``, fleet rollups, quality SLOs).
* :class:`StreamDigest` — a fixed-size mergeable quantile digest
  (dyadic rank-sketch ladder, ``ops/rank_sketch.py``) for latency /
  score / loss *distributions*: p50/p90/p99 in 8 KB of add-mergeable
  counters, bit-deterministic across fleet merge orders.

All of it composes: a sliced collection of ``Decayed``/``SlidingWindow``
members still runs ONE dispatch per batch/block.
"""

from torcheval_tpu.monitor.decay import Decayed
from torcheval_tpu.monitor.digest import StreamDigest
from torcheval_tpu.monitor.window import SlidingWindow
from torcheval_tpu.monitor.quality import publish, window_kind

__all__ = ["Decayed", "SlidingWindow", "StreamDigest", "publish", "window_kind"]
