"""Streaming quality exporter: collection figures → telemetry events.

:func:`publish` turns one reading of a :class:`MetricCollection` —
global figures plus every slice of a sliced collection — into typed
:class:`~torcheval_tpu.telemetry.events.QualityEvent`s on the telemetry
ring, labeled with the member name, the slice label ("" for the global
figure), and the window kind (``lifetime`` / ``decayed`` / ``window``,
derived from the member's monitor wrapper).  Downstream they surface as
the ``torcheval_tpu_quality`` Prometheus gauge family, the ``quality``
section of :func:`telemetry.report`, the offline CLI, fleet rollups,
and the quality SLO extractors in perfscope.

Callers gate on ``telemetry.events.ENABLED`` (the one-branch
zero-cost-when-off contract — the engine's snapshot hook does exactly
that); ``publish`` itself assumes the bus is on.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from torcheval_tpu.metrics.collection import MetricCollection
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.monitor.decay import Decayed
from torcheval_tpu.monitor.window import SlidingWindow
from torcheval_tpu.telemetry import events as _telemetry

__all__ = ["publish", "window_kind"]


def window_kind(metric: Metric) -> str:
    """The ``window`` label a member's readings carry: ``"decayed"`` for
    :class:`~torcheval_tpu.monitor.Decayed`, ``"window"`` for
    :class:`~torcheval_tpu.monitor.SlidingWindow`, else ``"lifetime"``."""
    if isinstance(metric, Decayed):
        return "decayed"
    if isinstance(metric, SlidingWindow):
        return "window"
    return "lifetime"


def _as_scalar(value: Any) -> Optional[float]:
    """A finite-or-not float for size-1 results; ``None`` for anything
    an event/gauge can't carry (confusion matrices, per-class vectors,
    tuples)."""
    if isinstance(value, tuple):
        return None
    try:
        arr = np.asarray(value)
    except Exception:
        return None
    if arr.size != 1 or arr.dtype == object:
        return None
    return float(arr.reshape(()))


def publish(
    collection: MetricCollection,
    *,
    step: int = 0,
    values: Optional[Dict[str, Any]] = None,
) -> int:
    """Emit one :class:`QualityEvent` per scalar figure the collection
    currently holds — each member globally, and per slice for a sliced
    collection.  ``values`` short-circuits the global ``compute()`` when
    the caller already has it (the engine's snapshot path).  ``step`` is
    the publisher's progress cursor (engine blocks dispatched, or the
    caller's own counter).  Returns the number of events emitted;
    non-scalar members (confusion matrices, curves) are skipped."""
    emitted = 0
    if values is None:
        values = collection.compute()
    scalar_names = []
    for name, value in values.items():
        scalar = _as_scalar(value)
        if scalar is None:
            continue
        scalar_names.append(name)
        _telemetry.record_quality(
            name, "", window_kind(collection[name]), scalar, step
        )
        emitted += 1
    if collection.slices is not None and scalar_names:
        # Only the members whose global figure was scalar — a member
        # that publishes nothing (confusion matrix, curve) would have
        # its K slice computes dispatched and thrown away.
        for k, label in enumerate(collection.slice_labels):
            for name in scalar_names:
                scalar = _as_scalar(
                    collection._slice_members[f"{name}@{k}"].compute()
                )
                if scalar is None:
                    continue
                _telemetry.record_quality(
                    name, label, window_kind(collection[name]), scalar, step
                )
                emitted += 1
    return emitted
