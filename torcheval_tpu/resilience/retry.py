"""Retry/timeout/backoff for object collectives.

The distributed layer's collectives (``CollectiveGroup`` in
``torcheval_tpu/distributed.py``) fail two ways in a real fleet: a
transient RPC error (coordinator hiccup, preempted peer rejoining) that
a retry absorbs, and a genuine hang (a peer that is never coming back)
that must be cut at a deadline rather than stalling the whole eval.
:class:`RetryPolicy` names both budgets; :class:`ResilientGroup` applies
them to any group by composition::

    group = ResilientGroup(default_group(), RetryPolicy(max_attempts=3))
    telemetry.fleet_report(group=group)

Each failed attempt emits a ``retry`` telemetry event (when the bus is
on); exhausted retries raise :class:`CollectiveTimeoutError` — or, with
``degrade="local"``, fall back to the local single-host view the way
``telemetry.fleet_report`` already does for ``world_size <= 1``, with a
``degraded`` event and a warning so the fallback is never silent.

Attempts armed with a ``deadline`` run on a reaper thread and are
abandoned at the cutoff (``join(remaining)``) — a stuck RPC can leak its
daemon thread, but the caller *returns*; the eval never hangs past the
deadline.  Backoff jitter draws from a ``random.Random(policy.seed)``
stream so chaos tests replay byte-identically.
"""

from __future__ import annotations

import random
import threading
import time
import warnings
from dataclasses import dataclass
from typing import Any, Callable, List, Optional

from torcheval_tpu.distributed import CollectiveGroup
from torcheval_tpu.resilience import faults as _faults
from torcheval_tpu.telemetry import events as _telemetry
from torcheval_tpu.telemetry import trace as _trace


class CollectiveTimeoutError(RuntimeError):
    """A collective exhausted its retry budget or overran its deadline.

    Carries the operation name, the attempts spent, the deadline (when
    one was armed), and — when the underlying error identified it — the
    slowest/unresponsive peer rank."""

    def __init__(
        self,
        op: str,
        attempts: int,
        deadline: Optional[float] = None,
        peer: Optional[int] = None,
    ) -> None:
        self.op = op
        self.attempts = attempts
        self.deadline = deadline
        self.peer = peer
        msg = f"collective {op!r} failed after {attempts} attempt(s)"
        if deadline is not None:
            msg += f" (deadline {deadline:g}s)"
        if peer is not None:
            msg += f"; slowest peer: rank {peer}"
        super().__init__(msg)


@dataclass(frozen=True)
class RetryPolicy:
    """Budgets for one retried operation.

    ``max_attempts`` total tries; exponential backoff between them from
    ``base_delay`` doubling up to ``max_delay``, stretched by up to
    ``jitter`` fraction (seeded — deterministic per wrapper instance);
    ``deadline`` is the *total* wall-clock budget in seconds across all
    attempts and sleeps (None = no deadline: rely on the per-RPC budget,
    e.g. ``distributed.kv_timeout_ms``)."""

    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    jitter: float = 0.1
    deadline: Optional[float] = None
    seed: int = 0

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}"
            )
        if self.deadline is not None and self.deadline <= 0:
            raise ValueError(
                f"deadline must be positive seconds, got {self.deadline}"
            )

    def backoff(self, attempt: int, rng: random.Random) -> float:
        """Sleep before attempt ``attempt + 1`` (``attempt`` is the
        1-based attempt that just failed)."""
        delay = min(self.base_delay * (2.0 ** (attempt - 1)), self.max_delay)
        if self.jitter:
            delay *= 1.0 + self.jitter * rng.random()
        return delay


class _Exhausted(Exception):
    """Internal: retries exhausted; carries the peer when known."""

    def __init__(self, peer: Optional[int] = None) -> None:
        self.peer = peer
        super().__init__()


def run_with_retry(
    op: str,
    fn: Callable[[], Any],
    policy: RetryPolicy,
    *,
    rng: Optional[random.Random] = None,
    fault_site: Optional[str] = None,
) -> Any:
    """Run ``fn()`` under ``policy``.  Raises :class:`_Exhausted` (from
    the last error) when the budget runs out — callers translate that
    into :class:`CollectiveTimeoutError` or a degraded fallback.

    ``fault_site`` names the chaos hook fired at the top of each attempt
    (inside the try, so injected faults are retried like real ones).
    """
    rng = rng if rng is not None else random.Random(policy.seed)
    start = time.monotonic()

    def remaining() -> Optional[float]:
        if policy.deadline is None:
            return None
        return policy.deadline - (time.monotonic() - start)

    last_exc: Optional[BaseException] = None
    for attempt in range(1, policy.max_attempts + 1):
        budget = remaining()
        if budget is not None and budget <= 0:
            raise _Exhausted(_peer_of(last_exc)) from last_exc
        try:
            if fault_site is not None and _faults.ENABLED:
                _faults.fire(fault_site, op=op, attempt=attempt)
            if budget is None:
                return fn()
            return _call_with_deadline(op, fn, budget, attempt)
        except _Exhausted:
            raise
        except Exception as exc:  # noqa: BLE001 - retried / re-raised below
            last_exc = exc
            if attempt >= policy.max_attempts:
                raise _Exhausted(_peer_of(exc)) from exc
            delay = policy.backoff(attempt, rng)
            budget = remaining()
            if budget is not None:
                if budget <= 0:
                    raise _Exhausted(_peer_of(exc)) from exc
                delay = min(delay, budget)
            if _telemetry.ENABLED:
                if _trace.ENABLED:
                    # One child span per failed attempt: the trace tree
                    # shows a retry storm as distinct siblings under the
                    # operation that retried, not one flat node.
                    with _trace.span("retry_attempt"):
                        _telemetry.record_retry(op, attempt, delay, repr(exc))
                else:
                    _telemetry.record_retry(op, attempt, delay, repr(exc))
            time.sleep(delay)
    raise _Exhausted(_peer_of(last_exc)) from last_exc  # pragma: no cover


def retry_call(
    op: str,
    fn: Callable[[], Any],
    policy: RetryPolicy,
    *,
    rng: Optional[random.Random] = None,
    fault_site: Optional[str] = None,
) -> Any:
    """:func:`run_with_retry` with exhaustion translated into the public
    :class:`CollectiveTimeoutError` — the entry point for callers that
    want retry-or-raise without the degrade option (e.g.
    ``parallel.make_synced_update(retry=...)``)."""
    try:
        return run_with_retry(op, fn, policy, rng=rng, fault_site=fault_site)
    except _Exhausted as exhausted:
        raise CollectiveTimeoutError(
            op,
            attempts=policy.max_attempts,
            deadline=policy.deadline,
            peer=exhausted.peer,
        ) from exhausted.__cause__


def _peer_of(exc: Optional[BaseException]) -> Optional[int]:
    """Pull a peer rank out of an error when the backend attached one
    (``exc.peer``) — best effort; most timeouts don't know."""
    peer = getattr(exc, "peer", None)
    return peer if isinstance(peer, int) else None


def _call_with_deadline(
    op: str, fn: Callable[[], Any], budget: float, attempt: int
) -> Any:
    """Run ``fn`` on a reaper thread, abandoning it at ``budget``
    seconds.  On timeout the daemon thread may leak (a truly stuck RPC
    cannot be cancelled from Python) but the caller returns on time."""
    box: List[Any] = [None, None]  # [result, exception]
    done = threading.Event()
    # Explicit handoff: anything fn() emits on the reaper thread keeps
    # the caller's trace context (contextvars don't cross Thread()).
    ctx = _trace.capture() if _trace.ENABLED else None

    def target() -> None:
        if _trace.ENABLED:
            _trace.adopt(ctx)
        try:
            box[0] = fn()
        except BaseException as e:  # noqa: BLE001 - relayed to caller
            box[1] = e
        finally:
            done.set()

    t = threading.Thread(
        target=target, name=f"resilient-{op}-a{attempt}", daemon=True
    )
    t.start()
    if not done.wait(timeout=budget):
        raise _Exhausted() from TimeoutError(
            f"collective {op!r} attempt {attempt} still in flight after "
            f"{budget:g}s deadline budget"
        )
    if box[1] is not None:
        raise box[1]
    return box[0]


class ResilientGroup(CollectiveGroup):
    """Wrap any :class:`CollectiveGroup` with retry/deadline/degrade
    semantics on its object collectives.

    ``degrade=None`` (default): exhausted retries raise
    :class:`CollectiveTimeoutError`.  ``degrade="local"``: serve the
    local single-host view instead — ``[obj]`` for all-gather, ``obj``
    for broadcast, ``[obj]``/None for gather — mirroring what
    ``telemetry.fleet_report`` returns for ``world_size <= 1``, and emit
    a ``degraded`` telemetry event + ``UserWarning``.  The degraded
    event carries the surviving-rank set: the attached ``membership``
    view's live ranks when one was given (the fleet merge wires its
    :class:`~torcheval_tpu.resilience.membership.MembershipView` in per
    level), else this rank alone — so ``fleet_report`` can attribute
    which hosts were lost, not just that a fallback happened.

    Note a *retry* of a real collective is only coherent when every rank
    retries symmetrically (same policy, same failure) — exactly what a
    coordinator hiccup or a deterministic :class:`FaultPlan` produces.
    Point-to-point sends/receives (:meth:`send_object` /
    :meth:`recv_object`) have no such symmetry requirement and are
    retried independently per peer; they never degrade — exhaustion
    raises, and the merge layer above turns that into an excision.
    """

    _DEGRADE_MODES = (None, "local")

    def __init__(
        self,
        group: CollectiveGroup,
        policy: Optional[RetryPolicy] = None,
        *,
        degrade: Optional[str] = None,
        membership: Optional[Any] = None,
    ) -> None:
        if degrade not in self._DEGRADE_MODES:
            raise ValueError(
                f"degrade must be one of {self._DEGRADE_MODES}, got {degrade!r}"
            )
        self.inner = group
        self.policy = policy if policy is not None else RetryPolicy()
        self.degrade = degrade
        self.membership = membership
        self._rng = random.Random(self.policy.seed)

    @property
    def rank(self) -> int:
        return self.inner.rank

    @property
    def world_size(self) -> int:
        return self.inner.world_size

    def _call(self, op: str, fn: Callable[[], Any], local_view: Any) -> Any:
        try:
            return run_with_retry(
                op,
                fn,
                self.policy,
                rng=self._rng,
                fault_site="collective",
            )
        except _Exhausted as exhausted:
            cause = exhausted.__cause__
            if self.degrade == "local":
                reason = repr(cause) if cause is not None else "exhausted"
                if _telemetry.ENABLED:
                    survivors = (
                        self.membership.survivors_label()
                        if self.membership is not None
                        else str(self.rank)
                    )
                    _telemetry.record_degraded(
                        op, reason, "local", survivors=survivors
                    )
                warnings.warn(
                    f"collective {op!r} exhausted its retry budget "
                    f"({reason}); degrading to the local single-host view",
                    RuntimeWarning,
                    stacklevel=3,
                )
                return local_view
            raise CollectiveTimeoutError(
                op,
                attempts=self.policy.max_attempts,
                deadline=self.policy.deadline,
                peer=exhausted.peer,
            ) from cause

    def all_gather_object(self, obj: Any) -> List[Any]:
        return self._call(
            "all_gather_object",
            lambda: self.inner.all_gather_object(obj),
            [obj],
        )

    def broadcast_object(self, obj: Any, src: int) -> Any:
        return self._call(
            "broadcast_object",
            lambda: self.inner.broadcast_object(obj, src),
            obj,
        )

    def gather_object(self, obj: Any, dst: int = 0) -> Optional[List[Any]]:
        local = [obj] if self.inner.rank == dst else None
        return self._call(
            "gather_object",
            lambda: self.inner.gather_object(obj, dst),
            local,
        )

    # Point-to-point: retried per peer, never degraded — a peer that
    # stays silent past the budget raises CollectiveTimeoutError with
    # its rank attached, and the fleet merge turns that into an excision
    # rather than a run-wide fallback.
    @property
    def supports_p2p(self) -> bool:
        return self.inner.supports_p2p

    def send_object(self, obj: Any, dst: int, tag: str) -> None:
        retry_call(
            "send_object",
            lambda: self.inner.send_object(obj, dst, tag),
            self.policy,
            rng=self._rng,
            fault_site="collective",
        )

    def recv_object(
        self, src: int, tag: str, timeout: Optional[float] = None
    ) -> Any:
        return retry_call(
            "recv_object",
            lambda: self.inner.recv_object(src, tag, timeout=timeout),
            self.policy,
            rng=self._rng,
            fault_site="collective",
        )
