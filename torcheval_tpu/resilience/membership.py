"""Live-rank membership for the elastic fleet merge.

A :class:`MembershipView` is one rank's picture of which peers are still
alive.  There is no separate failure detector and no extra heartbeat
traffic: heartbeats ride the merge itself.  Every payload and ack the
hierarchical merge (:mod:`torcheval_tpu.parallel.fleet_merge`) ships
carries the sender's rank plus its dead-rank gossip; receiving one calls
:meth:`observe` (refreshing the sender) and :meth:`merge_gossip`
(folding in deaths the sender already discovered), and a hop that times
out past its retry budget calls :meth:`excise`.

Excision is how a host leaves mid-eval without killing the run: the
excised rank's contribution is dropped, the merge continues over the
survivors, and the final result is labelled partial with
``world_effective = world_size - len(dead)``.  Every excision emits a
``degraded`` telemetry event whose ``survivors`` field carries the
surviving-rank set (``"0,2,3"``), so ``telemetry.fleet_report`` can
attribute exactly which hosts were lost and as seen from where.

Views are deliberately local: two ranks may briefly disagree about a
slow peer (one excised it, the other got its payload).  The merge layer
resolves that with contributor-set bookkeeping, not with a consensus
round — see ``fleet_merge``'s module docstring for the guarantees.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterable, Optional, Set

from torcheval_tpu.telemetry import events as _telemetry
from torcheval_tpu.telemetry import flightrec as _flightrec


class MembershipView:
    """One rank's live/dead bookkeeping over a fixed initial world.

    Thread-safe: the engine's overlap hook runs the merge on a
    background thread while telemetry readers snapshot the view.
    """

    def __init__(self, world_size: int, rank: int) -> None:
        if world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {world_size}")
        if not 0 <= rank < world_size:
            raise ValueError(
                f"rank must be in [0, {world_size}), got {rank}"
            )
        self.world_size = int(world_size)
        self.rank = int(rank)
        self._lock = threading.Lock()
        self._dead: Set[int] = set()
        self._reasons: Dict[int, str] = {}
        # rank -> (merge level last heard at, monotonic time)
        self._last_seen: Dict[int, Any] = {
            rank: (-1, time.monotonic())
        }
        self.generation = 0

    # ----------------------------------------------------------- queries
    @property
    def alive(self) -> Set[int]:
        with self._lock:
            return set(range(self.world_size)) - self._dead

    @property
    def dead(self) -> Set[int]:
        with self._lock:
            return set(self._dead)

    @property
    def world_effective(self) -> int:
        """Live ranks remaining — the ``N - k`` a partial result is
        labelled with."""
        with self._lock:
            return self.world_size - len(self._dead)

    def is_alive(self, rank: int) -> bool:
        with self._lock:
            return rank not in self._dead

    def survivors_label(self) -> str:
        """The surviving-rank set as the compact ``"0,2,3"`` string the
        ``degraded`` telemetry event carries."""
        return ",".join(str(r) for r in sorted(self.alive))

    # ----------------------------------------------------------- updates
    def observe(self, rank: int, *, level: int = -1) -> None:
        """A heartbeat: ``rank`` was heard from (piggybacked on a merge
        payload or ack at ``level``).  A rank heard from again after an
        excision is NOT resurrected — its contribution was already
        dropped from the running merge; re-admission is the next merge
        round's job (each round starts from a fresh view)."""
        with self._lock:
            self._last_seen[rank] = (level, time.monotonic())

    def excise(self, rank: int, reason: str = "") -> bool:
        """Declare ``rank`` dead (retry budget exhausted).  Returns
        True the first time, False for an already-dead rank.  Emits the
        ``degraded`` telemetry event with the surviving-rank set."""
        with self._lock:
            if rank in self._dead or rank == self.rank:
                return False
            self._dead.add(rank)
            self._reasons[rank] = reason
            self.generation += 1
            survivors = ",".join(
                str(r)
                for r in sorted(set(range(self.world_size)) - self._dead)
            )
        if _telemetry.ENABLED:
            _telemetry.record_degraded(
                "membership",
                reason or f"rank {rank} unresponsive",
                fallback="excised",
                survivors=survivors,
            )
        if _flightrec.ENABLED:
            _flightrec.trigger(
                "excision",
                reason or f"rank {rank} unresponsive",
                extra={"membership": self.snapshot()},
            )
        return True

    def merge_gossip(self, dead: Iterable[int], reason: str = "gossip") -> None:
        """Fold a peer's dead-set (shipped on every merge payload/ack)
        into this view."""
        for rank in dead:
            self.excise(int(rank), reason=reason)

    # --------------------------------------------------------- reporting
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "world_size": self.world_size,
                "rank": self.rank,
                "world_effective": self.world_size - len(self._dead),
                "dead": sorted(self._dead),
                "reasons": dict(self._reasons),
                "generation": self.generation,
                "last_seen": {
                    r: {"level": lv, "age_s": time.monotonic() - t}
                    for r, (lv, t) in self._last_seen.items()
                },
            }

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"MembershipView(rank={self.rank}, "
            f"alive={sorted(self.alive)}, dead={sorted(self.dead)})"
        )


def resolve_membership(
    view: Optional[MembershipView], world_size: int, rank: int
) -> MembershipView:
    """The merge entry points accept an optional caller-held view (to
    carry knowledge across rounds); absent one, each round starts
    fresh."""
    if view is None:
        return MembershipView(world_size, rank)
    if view.world_size != world_size or view.rank != rank:
        raise ValueError(
            f"membership view is for rank {view.rank}/"
            f"{view.world_size}, group says {rank}/{world_size}."
        )
    return view
