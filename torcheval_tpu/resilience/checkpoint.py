"""Durable checkpoint/resume for streaming evals.

An eval that streams millions of batches through ``engine.Evaluator``
loses *all* accumulated metric state when the host is preempted.  This
module makes that state durable with the classic atomic-write recipe:

- payload = pickle of ``{"state": <flat orbax-style mapping>, "cursor":
  {"batches_seen", "blocks_dispatched"}}`` with every array forced to
  host numpy (``MetricCollection.state_dict`` already returns the flat
  ``"{member}/{state}"`` mapping of fresh buffers, so a checkpoint is
  RNG-free and donation-safe by construction);
- written to ``ckpt-<generation>.bin.tmp``, flushed, ``os.fsync``-ed,
  then ``os.rename``-d into place (atomic on POSIX);
- a sidecar manifest ``ckpt-<generation>.manifest.json`` (same
  tmp+fsync+rename dance, written *after* the data file) records the
  payload's SHA-256, byte length, and the cursor, so a reader can
  validate without unpickling.

``load_latest`` walks generations newest-first: a checkpoint whose
manifest is missing/unreadable or whose data hash/length mismatches is
*quarantined* (both files renamed with a ``.corrupt`` suffix, a
``checkpoint``/``quarantine`` telemetry event emitted) and the previous
generation is tried — a torn write never poisons resume, it just costs
one generation of progress.

The cursor is taken at block boundaries only (``Evaluator`` saves when
no partially-filled block is pending), so replaying the stream and
skipping ``batches_seen`` batches reproduces the exact block grouping —
that is what makes resume bit-identical to an uninterrupted run.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import shutil
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Optional

import numpy as np

from torcheval_tpu.resilience import faults as _faults
from torcheval_tpu.telemetry import events as _telemetry

_DATA_RE = re.compile(r"^ckpt-(\d{8})\.bin$")
_MANIFEST_VERSION = 1
_NAMESPACE_SAFE_RE = re.compile(r"[^A-Za-z0-9._-]")

# Sentinel returned by _load_one for a generation whose files vanished
# between the directory listing and the read — a concurrent _prune, not
# corruption.  Distinct from None (validation failure → quarantine).
_CONCURRENTLY_PRUNED = object()


@dataclass
class Checkpoint:
    """One loaded-and-validated checkpoint generation."""

    generation: int
    path: str
    state: Dict[str, np.ndarray]
    cursor: Dict[str, int]
    nbytes: int


@dataclass
class CheckpointBlob:
    """One generation as wire-ready bytes: the raw pickled payload plus
    its manifest (sha256/nbytes/cursor).  This is what the serve
    cluster streams p2p during a live migration — the manifest travels
    WITH the bytes so the importer can prove the transfer intact before
    any state is trusted, and the generation number fences stale
    owners (a replayed older blob can never shadow a newer one)."""

    generation: int
    manifest: Dict[str, Any]
    payload: bytes


def _fsync_write(path: str, data: bytes) -> None:
    """tmp-file + flush + fsync + atomic rename into ``path``."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.rename(tmp, path)


class CheckpointManager:
    """Generation-numbered atomic checkpoints in one directory.

    ``keep`` bounds disk use: after each successful save, valid
    generations beyond the newest ``keep`` are deleted (quarantined
    ``.corrupt`` files are left for post-mortems).
    """

    def __init__(self, directory: str, *, keep: int = 2) -> None:
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = str(directory)
        self.keep = keep
        os.makedirs(self.directory, exist_ok=True)

    # -- scoping ---------------------------------------------------------
    def namespace(self, name: str) -> "CheckpointManager":
        """A child manager over the subdirectory ``name`` (sanitized to
        filename-safe characters), inheriting ``keep``.  Namespaces are
        how the serve layer keys per-tenant spill state: each tenant's
        generations live in their own subtree, so one tenant's
        :meth:`delete_all` on close cannot touch a sibling's."""
        safe = _NAMESPACE_SAFE_RE.sub("_", name)
        if not safe:
            raise ValueError(f"namespace name sanitizes to empty: {name!r}")
        return CheckpointManager(
            os.path.join(self.directory, safe), keep=self.keep
        )

    def delete_all(self) -> None:
        """Remove this manager's directory tree — generations,
        quarantined ``.corrupt`` files, and child namespaces.  Siblings
        of this directory are never touched.  Idempotent; errors from
        concurrent cleanup are swallowed like :meth:`_prune`'s."""
        shutil.rmtree(self.directory, ignore_errors=True)

    # -- paths -----------------------------------------------------------
    def _data_path(self, generation: int) -> str:
        return os.path.join(self.directory, f"ckpt-{generation:08d}.bin")

    def _manifest_path(self, generation: int) -> str:
        return os.path.join(
            self.directory, f"ckpt-{generation:08d}.manifest.json"
        )

    def generations(self) -> List[int]:
        """Generation numbers with a data file present, ascending."""
        out = []
        for name in os.listdir(self.directory):
            m = _DATA_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # -- write -----------------------------------------------------------
    def save(
        self,
        state: Mapping[str, Any],
        cursor: Mapping[str, int],
    ) -> str:
        """Atomically persist one generation; returns the data path.

        ``state`` is the collection's flat ``state_dict()`` mapping;
        every leaf is forced to host numpy so the payload is
        device-free and bit-exact on reload.
        """
        t0 = time.monotonic()
        host_state = {k: np.asarray(v) for k, v in state.items()}
        payload = pickle.dumps(
            {"state": host_state, "cursor": dict(cursor)},
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        gens = self.generations()
        generation = (gens[-1] + 1) if gens else 0
        data_path = self._data_path(generation)

        if _faults.ENABLED:
            rule = _faults.fire(
                "checkpoint.write",
                generation=generation,
                nbytes=len(payload),
            )
            if rule is not None and rule.action == "tear":
                # Simulate a crash that left a torn data file on disk
                # (power loss after a non-atomic writer, fsync lost):
                # the manifest records the full payload's hash, so
                # load_latest must quarantine this generation.
                with open(data_path, "wb") as fh:
                    fh.write(payload[: rule.offset])
                self._write_manifest(generation, payload, cursor)
                raise _faults.InjectedFault(
                    "checkpoint.write",
                    f"torn checkpoint write at byte {rule.offset}",
                )

        _fsync_write(data_path, payload)
        self._write_manifest(generation, payload, cursor)
        self._prune()
        if _telemetry.ENABLED:
            _telemetry.record_checkpoint(
                "save",
                data_path,
                generation,
                len(payload),
                time.monotonic() - t0,
            )
        return data_path

    def _write_manifest(
        self, generation: int, payload: bytes, cursor: Mapping[str, int]
    ) -> None:
        manifest = {
            "version": _MANIFEST_VERSION,
            "generation": generation,
            "sha256": hashlib.sha256(payload).hexdigest(),
            "nbytes": len(payload),
            "cursor": dict(cursor),
        }
        _fsync_write(
            self._manifest_path(generation),
            json.dumps(manifest, sort_keys=True).encode("utf-8"),
        )

    def _prune(self) -> None:
        for generation in self.generations()[: -self.keep]:
            for path in (
                self._data_path(generation),
                self._manifest_path(generation),
            ):
                try:
                    os.remove(path)
                except OSError:  # pragma: no cover - concurrent cleanup
                    pass

    # -- read ------------------------------------------------------------
    def load_latest(self) -> Optional[Checkpoint]:
        """Newest checkpoint that validates; corrupt generations are
        quarantined and older ones tried.  None when nothing valid.

        Tolerates a concurrent writer pruning while this reader walks:
        a NON-newest generation whose files are gone by read time was
        concurrently pruned and is skipped without quarantine (only the
        newest generation can legitimately be torn — data is written
        before manifest, and _prune never touches the newest ``keep``).
        If every listed generation vanished mid-walk the stale listing
        is refreshed once before giving up."""
        for attempt in range(2):
            gens = self.generations()
            if not gens:
                return None
            pruned_under_us = 0
            for generation in reversed(gens):
                t0 = time.monotonic()
                loaded = self._load_one(
                    generation, newest=(generation == gens[-1])
                )
                if loaded is _CONCURRENTLY_PRUNED:
                    pruned_under_us += 1
                    continue
                if loaded is None:
                    self._quarantine(generation)
                    continue
                if _telemetry.ENABLED:
                    _telemetry.record_checkpoint(
                        "restore",
                        loaded.path,
                        generation,
                        loaded.nbytes,
                        time.monotonic() - t0,
                    )
                return loaded
            if pruned_under_us == 0 or attempt == 1:
                return None
        return None  # pragma: no cover - loop always returns

    def _load_one(self, generation: int, *, newest: bool = True):
        data_path = self._data_path(generation)
        try:
            with open(self._manifest_path(generation), "rb") as fh:
                manifest = json.loads(fh.read().decode("utf-8"))
            with open(data_path, "rb") as fh:
                payload = fh.read()
        except OSError:
            # Missing files on an older generation mean a concurrent
            # _prune won the race, not corruption; the newest generation
            # has no such excuse (save order is data-then-manifest).
            if not newest and not (
                os.path.exists(data_path)
                or os.path.exists(self._manifest_path(generation))
            ):
                return _CONCURRENTLY_PRUNED
            return None
        except (ValueError, UnicodeDecodeError):
            return None
        if (
            len(payload) != manifest.get("nbytes")
            or hashlib.sha256(payload).hexdigest() != manifest.get("sha256")
        ):
            return None
        try:
            record = pickle.loads(payload)
        except Exception:  # noqa: BLE001 - hash passed but unpicklable
            return None
        return Checkpoint(
            generation=generation,
            path=data_path,
            state=record["state"],
            cursor=dict(record["cursor"]),
            nbytes=len(payload),
        )

    # -- p2p streaming ---------------------------------------------------
    def export_latest(self) -> Optional[CheckpointBlob]:
        """The newest *valid* generation as a wire-ready
        :class:`CheckpointBlob` (payload bytes + manifest), for
        streaming over a p2p transport during a live migration.  Walks
        newest-first like :meth:`load_latest` but leaves quarantine
        policy to the readers; returns None when nothing validates."""
        for generation in reversed(self.generations()):
            try:
                with open(self._manifest_path(generation), "rb") as fh:
                    manifest = json.loads(fh.read().decode("utf-8"))
                with open(self._data_path(generation), "rb") as fh:
                    payload = fh.read()
            except (OSError, ValueError, UnicodeDecodeError):
                continue
            if (
                len(payload) != manifest.get("nbytes")
                or hashlib.sha256(payload).hexdigest()
                != manifest.get("sha256")
            ):
                continue
            return CheckpointBlob(
                generation=generation,
                manifest=manifest,
                payload=payload,
            )
        return None

    def import_blob(self, blob: CheckpointBlob) -> bool:
        """Install a streamed generation received over the wire.

        The payload is validated against the manifest it traveled with
        (sha256 + byte length) BEFORE anything durable is trusted:

        * valid → written with the same data-then-manifest fsync dance
          as :meth:`save`, so :meth:`load_latest` resumes from it.
          Idempotent: re-importing a generation whose local files
          already validate is a no-op (shared-store deployments see the
          owner's own save under the same name).
        * torn/corrupt transfer → the bytes are preserved under
          ``.corrupt`` paths for the post-mortem (never touching any
          resident generation's files), a ``checkpoint``/``quarantine``
          telemetry event fires, and False is returned — the importer
          must not resume from it.
        """
        manifest = dict(blob.manifest)
        generation = int(manifest.get("generation", blob.generation))
        payload = blob.payload
        valid = (
            generation >= 0
            and len(payload) == manifest.get("nbytes")
            and hashlib.sha256(payload).hexdigest()
            == manifest.get("sha256")
        )
        if not valid:
            quarantine_path = (
                self._data_path(max(generation, 0)) + ".corrupt"
            )
            try:
                with open(quarantine_path, "wb") as fh:
                    fh.write(payload)
            except OSError:  # pragma: no cover - disk gone mid-import
                pass
            if _telemetry.ENABLED:
                _telemetry.record_checkpoint(
                    "quarantine", quarantine_path, max(generation, 0), 0, 0.0
                )
            return False
        if self._load_one(generation, newest=False) not in (
            None,
            _CONCURRENTLY_PRUNED,
        ):
            return True
        t0 = time.monotonic()
        _fsync_write(self._data_path(generation), payload)
        _fsync_write(
            self._manifest_path(generation),
            json.dumps(manifest, sort_keys=True).encode("utf-8"),
        )
        self._prune()
        if _telemetry.ENABLED:
            _telemetry.record_checkpoint(
                "save",
                self._data_path(generation),
                generation,
                len(payload),
                time.monotonic() - t0,
            )
        return True

    def _quarantine(self, generation: int) -> None:
        data_path = self._data_path(generation)
        for path in (data_path, self._manifest_path(generation)):
            if os.path.exists(path):
                try:
                    os.rename(path, path + ".corrupt")
                except OSError:  # pragma: no cover - concurrent cleanup
                    pass
        if _telemetry.ENABLED:
            _telemetry.record_checkpoint(
                "quarantine", data_path + ".corrupt", generation, 0, 0.0
            )
