"""Deterministic fault injection for the chaos suite.

A :class:`FaultPlan` is a set of :class:`FaultRule` entries keyed by
*site* name.  Hook sites live inside the library at the exact places a
real fault would land — each is the same one-branch guard the telemetry
bus and data-health monitor use (``if _faults.ENABLED: _faults.fire``),
so with no plan installed (the default) the hot path pays one module
attribute read and one branch and never calls into this module.
``scripts/check_hot_path_overhead.py`` asserts that empirically, and
because every hook is host-side Python, the jitted programs are
byte-identical with the subsystem on or off.

Named sites (the cookbook in ``docs/source/resilience.rst`` shows each
in action):

``collective``
    Each attempt of an object collective inside
    :class:`~torcheval_tpu.resilience.retry.ResilientGroup` (context:
    ``op``, ``attempt``).  ``action="raise"`` drops the attempt,
    ``action="delay"`` stalls it.
``prefetch.produce``
    After the engine's prefetch producer stages an item (context:
    ``items`` staged so far).  ``after=K`` kills the producer after K
    items, exercising the consumer-side error relay.
``engine.scan``
    At the top of ``ScanRunner.dispatch``, before any state is read —
    a mid-stream abort between blocks (context: ``signature``).
``engine.batch``
    Per batch admitted by the ``Evaluator`` (context: ``batch``).
    ``action="corrupt"`` pokes a NaN into the first float argument so
    the data-health monitor has something to catch.
``checkpoint.write``
    Inside ``CheckpointManager.save`` (context: ``generation``,
    ``nbytes``).  ``action="tear"`` simulates a crash that left a torn
    data file of ``offset`` bytes behind, then raises.
``sync.dispatch``
    Per synced-update dispatch in ``parallel/sync.py`` (context:
    ``op``).
``serve.admit``
    Per ``EvalService.submit`` call in the multi-tenant serve layer
    (context: ``tenant``, ``queue_depth``).  ``action="raise"``
    propagates an :class:`InjectedFault` to the submitter (the service
    itself stays consistent — overload chaos drives bursts through a
    failing admission path); ``action="delay"`` stalls admission to
    manufacture queue pressure.
``merge.level``
    Each participation step of the hierarchical fleet merge
    (``parallel/fleet_merge.py``; context: ``rank``, ``level``,
    ``round``, ``topology``, ``role``).  ``action="drop_rank"`` makes
    the matched rank vanish mid-merge (it stops sending/acking from
    that level on, so peers must excise and re-parent around it);
    ``action="slow_rank"`` turns it into a ``delay_s`` straggler.
``serve.route``
    Per placement decision in the distributed serve plane
    (``serve/cluster.py``; context: ``tenant``, ``rank``, ``role`` —
    ``"submit"`` on the sender, ``"apply"`` on the owner applying a
    routed frame).  ``action="drop_rank"`` kills the host typed (the
    cluster catches it and goes silent — a mid-dispatch death with
    batches still in its inbox); ``action="raise"`` sheds the batch
    (sender side) or parks the frame for the retry sweep (owner side).
``serve.migrate``
    Per phase of a live tenant migration (``serve/cluster.py``;
    context: ``tenant``, ``phase`` ∈ ``spill``/``stream``/``resume``,
    ``rank``, ``target``).  ``action="drop_rank"`` at ``spill`` or
    ``stream`` kills the source mid-handoff; at ``resume`` it kills
    the target after the blob arrived — the source aborts and the
    tenant stays bit-exact where it last spilled.  ``action="raise"``
    aborts the handoff typed (``PlacementOutcome(action="aborted")``).

Reproducibility: probabilistic rules (``probability < 1``) draw from a
``numpy`` generator seeded by ``FaultPlan(seed=)``; draws are consumed
in site-hit order under a lock, so the same plan over the same workload
fires at the same hit indices every run.

Plans activate as context managers (``with FaultPlan([...]):``) or from
the environment: ``TORCHEVAL_TPU_FAULT_PLAN='[{"site": "collective",
"on_attempt": 1}]'`` installs a plan at import (one JSON object or a
list of them; keys mirror the :class:`FaultRule` fields).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from torcheval_tpu import _flags
from torcheval_tpu.telemetry import flightrec as _flightrec

# The one-branch guard flag.  True exactly while a plan is installed.
ENABLED: bool = False

_ACTIVE: Optional["FaultPlan"] = None
_lock = threading.Lock()

_ACTIONS = ("raise", "delay", "tear", "corrupt", "drop_rank", "slow_rank")


class InjectedFault(RuntimeError):
    """Raised by an ``action="raise"`` (or ``"tear"``) rule — typed so
    chaos tests can tell injected failures from real ones."""

    def __init__(self, site: str, message: str = "") -> None:
        self.site = site
        super().__init__(message or f"injected fault at site {site!r}")


class DroppedRank(InjectedFault):
    """Raised by an ``action="drop_rank"`` rule at a ``merge.level``
    site: the matched rank "vanishes" — the merge layer catches this at
    its top level and simply stops participating (no sends, no acks),
    which is what a preempted host looks like to its peers."""

    def __init__(self, site: str, rank: int, message: str = "") -> None:
        self.rank = rank
        super().__init__(
            site, message or f"rank {rank} dropped at site {site!r}"
        )


@dataclass
class FaultRule:
    """One injection rule.  A rule matches a :func:`fire` call when the
    site names are equal, every provided context filter (``on_attempt``,
    ``match``) agrees, the hit index at that site is past ``after``, the
    rule has fired fewer than ``count`` times, and the seeded coin
    (``probability``) lands."""

    site: str
    action: str = "raise"       # one of _ACTIONS
    after: int = 0              # skip the first `after` matching hits
    count: Optional[int] = 1    # max firings (None = unlimited)
    on_attempt: Optional[int] = None  # only when ctx["attempt"] == this
    match: Dict[str, Any] = field(default_factory=dict)  # ctx equality
    probability: float = 1.0    # seeded draw per eligible hit
    delay_s: float = 0.01       # action="delay"
    offset: int = 0             # action="tear": torn-write byte offset
    message: str = ""

    def __post_init__(self) -> None:
        if self.action not in _ACTIONS:
            raise ValueError(
                f"action must be one of {_ACTIONS}, got {self.action!r}"
            )
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError(
                f"probability must be in [0, 1], got {self.probability}"
            )


@dataclass
class FiredFault:
    """Journal entry: one rule firing (``FaultPlan.fired``)."""

    site: str
    action: str
    hit: int                    # per-site hit index (0-based)
    context: Dict[str, Any]


class FaultPlan:
    """A seeded set of rules, installable as a context manager.

    Only one plan can be active at a time (nesting would make the
    seeded schedule ambiguous).  The plan journals every firing in
    ``self.fired`` and counts site hits in ``self.hits`` so tests can
    assert exactly what chaos happened.
    """

    def __init__(
        self,
        rules: Sequence[Union[FaultRule, Dict[str, Any]]],
        *,
        seed: int = 0,
    ) -> None:
        self.rules: List[FaultRule] = [
            r if isinstance(r, FaultRule) else FaultRule(**r) for r in rules
        ]
        self.seed = seed
        self._rng = np.random.default_rng(seed)
        self.hits: Dict[str, int] = {}
        self.fired: List[FiredFault] = []
        self._fired_counts: Dict[int, int] = {}

    # -- installation ----------------------------------------------------
    def install(self) -> "FaultPlan":
        global ENABLED, _ACTIVE
        with _lock:
            if _ACTIVE is not None:
                raise RuntimeError(
                    "a FaultPlan is already active; plans do not nest"
                )
            _ACTIVE = self
            ENABLED = True
        return self

    def uninstall(self) -> None:
        global ENABLED, _ACTIVE
        with _lock:
            if _ACTIVE is self:
                _ACTIVE = None
                ENABLED = False

    def __enter__(self) -> "FaultPlan":
        return self.install()

    def __exit__(self, *exc_info: Any) -> None:
        self.uninstall()

    # -- matching --------------------------------------------------------
    def _match(
        self, site: str, ctx: Dict[str, Any]
    ) -> Optional[FaultRule]:
        """One site hit: bump the hit counter, return the firing rule
        (first match wins) or None.  Caller holds ``_lock``."""
        hit = self.hits.get(site, 0)
        self.hits[site] = hit + 1
        for idx, rule in enumerate(self.rules):
            if rule.site != site:
                continue
            if rule.on_attempt is not None and (
                ctx.get("attempt") != rule.on_attempt
            ):
                continue
            if any(ctx.get(k) != v for k, v in rule.match.items()):
                continue
            if hit < rule.after:
                continue
            if (
                rule.count is not None
                and self._fired_counts.get(idx, 0) >= rule.count
            ):
                continue
            if rule.probability < 1.0 and (
                self._rng.random() >= rule.probability
            ):
                continue
            self._fired_counts[idx] = self._fired_counts.get(idx, 0) + 1
            self.fired.append(
                FiredFault(
                    site=site, action=rule.action, hit=hit, context=dict(ctx)
                )
            )
            return rule
        return None


def active() -> Optional[FaultPlan]:
    # tpulint: disable=TPU006 -- lock-free hot-path read; rebinds are atomic
    return _ACTIVE


def fire(site: str, **ctx: Any) -> Optional[FaultRule]:
    """The hook-site entry point.  Callers MUST branch on :data:`ENABLED`
    first (the zero-cost contract); this function does not re-check.

    ``action="raise"`` raises :class:`InjectedFault`; ``"drop_rank"``
    raises :class:`DroppedRank` (carrying ``ctx["rank"]``); ``"delay"``
    and ``"slow_rank"`` sleep ``delay_s`` and return None;
    ``"tear"``/``"corrupt"`` return the matched rule so the site applies
    the data transformation itself.
    """
    # tpulint: disable=TPU006 -- hot-path snapshot; _match runs under _lock
    plan = _ACTIVE
    if plan is None:  # pragma: no cover - uninstall race
        return None
    with _lock:
        rule = plan._match(site, ctx)
    if rule is None:
        return None
    if _flightrec.ENABLED:
        # Snapshot BEFORE the action lands: for "raise"/"drop_rank" the
        # post-fault state is an unwound stack, so the bundle's value is
        # the ring tail leading up to the injection.
        _flightrec.trigger(
            "fault_fired",
            f"site={site} action={rule.action}",
            extra={"fault": {"site": site, "action": rule.action,
                             "context": {k: repr(v) for k, v in ctx.items()}}},
        )
    if rule.action == "raise":
        raise InjectedFault(site, rule.message)
    if rule.action == "drop_rank":
        raise DroppedRank(site, int(ctx.get("rank", -1)), rule.message)
    if rule.action in ("delay", "slow_rank"):
        import time

        time.sleep(rule.delay_s)
        return None
    return rule  # "tear" / "corrupt": the caller transforms its data


def corrupt_batch(args: Sequence[Any]) -> tuple:
    """Apply an ``action="corrupt"`` rule: return ``args`` with a NaN
    poked into element 0 of the first floating-point array (host-side
    numpy copy; the caller feeds it onward like any other batch)."""
    out = list(args)
    for i, a in enumerate(out):
        arr = np.asarray(a)
        if np.issubdtype(arr.dtype, np.floating) and arr.size:
            arr = np.array(arr)  # owned copy
            arr.reshape(-1)[0] = np.nan
            out[i] = arr
            break
    return tuple(out)


def install_from_env() -> Optional[FaultPlan]:
    """Install a plan from ``TORCHEVAL_TPU_FAULT_PLAN`` (JSON: one rule
    object or a list of them; an object may carry a ``seed`` key when
    wrapped as ``{"seed": n, "rules": [...]}``).  Returns the installed
    plan, or None when the variable is unset/empty."""
    spec = _flags.get("FAULT_PLAN")
    if spec is None:
        return None
    seed = 0
    if isinstance(spec, dict) and "rules" in spec:
        seed = int(spec.get("seed", 0))
        spec = spec["rules"]
    if isinstance(spec, dict):
        spec = [spec]
    return FaultPlan(spec, seed=seed).install()


# Env-driven activation at import so `TORCHEVAL_TPU_FAULT_PLAN=... python
# eval.py` needs no code change (mirrors TORCHEVAL_TPU_TELEMETRY).
install_from_env()
