"""Fault tolerance for streaming evals: durable checkpoint/resume,
collective retry/timeout/backoff, and deterministic fault injection.

The reference toolkit assumes every rank survives the whole eval
(``toolkit.sync_and_compute`` gathers once and merges); on a multi-host
TPU fleet that means one preempted host or one stalled coordinator RPC
kills the run and all accumulated state.  This package is the
fail-operational layer on top of PRs 2–4's observability:

- :mod:`~torcheval_tpu.resilience.checkpoint` —
  :class:`CheckpointManager`: atomic (tmp+fsync+rename, SHA-256
  manifest) generations of the collection's ``state_dict()`` plus the
  stream cursor; ``engine.Evaluator(checkpoint_dir=...)`` auto-resumes
  bit-identically.
- :mod:`~torcheval_tpu.resilience.retry` — :class:`RetryPolicy` /
  :class:`ResilientGroup`: backoff-retried object collectives with a
  hard deadline, typed :class:`CollectiveTimeoutError`, and optional
  ``degrade="local"`` single-host fallback.
- :mod:`~torcheval_tpu.resilience.faults` — :class:`FaultPlan`: seeded,
  site-named fault injection behind the same one-branch zero-cost-off
  guards as the telemetry bus (``scripts/check_hot_path_overhead.py``
  enforces it).
- :mod:`~torcheval_tpu.resilience.membership` —
  :class:`MembershipView`: live-rank tracking for the hierarchical
  fleet merge (``parallel/fleet_merge.py``), with heartbeats piggybacked
  on merge traffic, excision of unresponsive hosts, and dead-rank
  gossip; excisions surface as ``degraded`` telemetry events carrying
  the surviving-rank set.

See ``docs/source/resilience.rst`` for the checkpoint format, retry
policy guidance, and the fault-plan cookbook, and
``docs/source/fleet.rst`` for the host-loss runbook.
"""

from torcheval_tpu.resilience import checkpoint, faults, membership, retry
from torcheval_tpu.resilience.checkpoint import Checkpoint, CheckpointManager
from torcheval_tpu.resilience.faults import (
    DroppedRank,
    FaultPlan,
    FaultRule,
    InjectedFault,
)
from torcheval_tpu.resilience.membership import MembershipView
from torcheval_tpu.resilience.retry import (
    CollectiveTimeoutError,
    ResilientGroup,
    RetryPolicy,
)

__all__ = [
    "Checkpoint",
    "CheckpointManager",
    "CollectiveTimeoutError",
    "DroppedRank",
    "FaultPlan",
    "FaultRule",
    "InjectedFault",
    "MembershipView",
    "ResilientGroup",
    "RetryPolicy",
    "checkpoint",
    "faults",
    "membership",
    "retry",
]
