"""Route-downgrade diagnostics: loud fallbacks and ``explain_route``.

Several hot paths pick their formulation at CALL time from concrete
values (the ``_select_binned_route`` pattern): the sort-free rank-sum
AUROC/AUPRC route, the sharded ustat cap autotune, the confusion-matrix
three-way dispatch.  Under a caller's ``jax.jit`` those deciders see
tracers and silently keep the safe-but-slower formulation — the exact
failure mode that once made this repo's own headline clock measure the
189 ms sort path while eager users got the 33 ms routed kernel
(BASELINE.md round-3).  This module makes the downgrade loud (ONE
warning per user callsite) and gives users a way to ask which
formulation a call will take and why.
"""

from __future__ import annotations

import traceback
import warnings
from typing import Set, Tuple

_PKG_MARKER = "torcheval_tpu"
_warned_callsites: Set[Tuple[str, int, str]] = set()


class RouteDowngradeWarning(UserWarning):
    """A call-time fast-path decider fell back to a slower formulation
    for a reason the caller can fix (usually: pin the decision eagerly,
    e.g. ``ustat_cap=`` / ``max_class_count_per_shard=``)."""


def _user_callsite() -> Tuple[str, int]:
    """First stack frame outside this package (the user's call line)."""
    for frame in reversed(traceback.extract_stack(limit=40)[:-1]):
        if _PKG_MARKER not in (frame.filename or ""):
            return frame.filename, frame.lineno or 0
    return "<unknown>", 0


def warn_route_downgrade(kind: str, message: str) -> None:
    """Emit ``RouteDowngradeWarning`` once per (user callsite, kind).

    ``warn_explicit`` at the USER's file/line, with no Python warning
    registry: a plain ``warnings.warn`` from here would register every
    callsite under this module's fixed line, so under default filters
    only the FIRST user callsite would ever warn — and the warning would
    point at package internals instead of the user's jit call."""
    filename, lineno = _user_callsite()
    key = (filename, lineno, kind)
    if key in _warned_callsites:
        return
    _warned_callsites.add(key)
    warnings.warn_explicit(
        message, RouteDowngradeWarning, filename, lineno
    )


def reset_route_warnings() -> None:
    """Forget which callsites already warned (test hook)."""
    _warned_callsites.clear()


def explain_route(fn, *args, **kwargs) -> str:
    """Explain which formulation ``fn(*args, **kwargs)`` would run and
    why — a debugging aid for the call-time routed entry points.

    Supported ``fn`` (the ``torcheval_tpu.metrics.functional``
    callables): ``multiclass_auroc``, ``multiclass_auprc``,
    ``binary_auroc``, ``binary_auprc``, ``multiclass_confusion_matrix``,
    ``multiclass_f1_score``, ``multiclass_precision``,
    ``multiclass_recall``, and the binned family (every
    ``*_binned_auroc`` / ``*_binned_auprc`` /
    ``*_binned_precision_recall_curve`` variant).  Call it EAGERLY on
    representative data — inside a jit
    the deciders see tracers, which is exactly the downgrade this helper
    diagnoses.  Returns a one-paragraph human-readable explanation.
    """
    import jax

    import torcheval_tpu.metrics.functional as F
    from torcheval_tpu.metrics.functional._host_checks import all_concrete
    from torcheval_tpu.ops._flags import pallas_disabled, ustat_disabled

    name = getattr(fn, "__name__", str(fn))
    backend = jax.default_backend()

    def env_blockers() -> str:
        if pallas_disabled():
            return "TORCHEVAL_TPU_DISABLE_PALLAS is set"
        if backend != "tpu":
            return f"backend is {backend!r}, not TPU"
        return ""

    if fn in (F.multiclass_auroc, F.multiclass_auprc):
        from torcheval_tpu.ops.pallas_ustat import ustat_route_cap

        scores, target = args[0], args[1]
        num_classes = kwargs.get(
            "num_classes", scores.shape[1] if hasattr(scores, "shape") else None
        )
        cap = ustat_route_cap(
            jax.numpy.asarray(scores), jax.numpy.asarray(target), num_classes
        )
        if cap is not None:
            return (
                f"{name}: sort-free Pallas rank-sum route, table cap {cap}. "
                f"Under a caller's jit this decision sees tracers and falls "
                f"back to the sort path — pin it with ustat_cap={cap} (the "
                f"README 'pinning the rank-sum route under jit' recipe)."
            )
        sharding = getattr(scores, "sharding", None)
        reason = env_blockers() or (
            "inputs are tracers (decide eagerly, then pin ustat_cap)"
            if not all_concrete(scores, target)
            else "TORCHEVAL_TPU_DISABLE_USTAT is set"
            if ustat_disabled()
            else "inputs are mesh-sharded (a pallas_call under plain jit "
            "would replicate the full scores onto every device; the "
            "sharded_* wrappers in torcheval_tpu.parallel keep O(N/P) "
            "per-device economics instead)"
            if sharding is not None and len(sharding.device_set) > 1
            else "data outside the measured win region (small N, "
            "class-skewed counts, non-finite or subnormal scores)"
        )
        return f"{name}: XLA sort + scan path ({reason})."

    if fn in (F.binary_auroc, F.binary_auprc):
        from torcheval_tpu.ops.pallas_ustat import binary_ustat_route

        scores, target = jax.numpy.asarray(args[0]), jax.numpy.asarray(args[1])
        rows = scores[None] if scores.ndim == 1 else scores
        t_rows = target[None] if target.ndim == 1 else target
        route = binary_ustat_route(
            rows, t_rows, need_pos=fn is F.binary_auprc
        )
        if route is not None:
            side, cap = route
            return (
                f"{name}: sort-free rank-sum route against the packed "
                f"{side!r} side, cap {cap} (decided per call; jit callers "
                f"keep the sort path)."
            )
        blocked = env_blockers()
        tail = (
            "fused Pallas scan after a 1-D-layout sort"
            if not blocked
            else "pure-XLA sort + scan"
        )
        return f"{name}: {tail}" + (f" ({blocked})." if blocked else ".")

    _route_detail = {
        "pallas": "bucket-compaction Pallas kernel (ops/pallas_cm.py)",
        "matmul": "one dense one-hot MXU matmul",
        "scatter": "int32 scatter-add (reference formulation)",
    }
    if fn is F.multiclass_confusion_matrix:
        from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
            _cm_route,
        )

        inp = args[0]
        num_classes = kwargs.get("num_classes")
        if num_classes is None and len(args) > 2:
            num_classes = args[2]
        route = _cm_route(num_classes, inp.shape[0])
        return (
            f"{name}: confusion-matrix slab via {_route_detail[route]} — "
            f"decided from shapes/backend only, so it is identical under "
            f"a caller's jit."
        )

    if fn in (
        F.multiclass_f1_score,
        F.multiclass_precision,
        F.multiclass_recall,
    ):
        from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
            _counts_route,
        )

        inp = args[0]
        average = kwargs.get("average", "micro")
        num_classes = kwargs.get("num_classes")
        if average == "micro":
            return (
                f"{name}: micro average — scatter-free scalar counters "
                "(no per-class trio, no routing)."
            )
        route = _counts_route(inp, num_classes, average)
        return (
            f"{name}: per-class count trio via {_route_detail[route]} — "
            f"decided from shapes/backend only, so it is identical under "
            f"a caller's jit."
        )

    # (kind, default threshold count) per binned entry point — kinds fix
    # the (rows, samples) orientation _binned_counts_rows actually sees.
    _binned = {
        F.binary_binned_auroc: ("binary", 200),
        F.binary_binned_auprc: ("binary", 100),
        F.multiclass_binned_auroc: ("classes", 200),
        F.multiclass_binned_auprc: ("classes", 100),
        F.multilabel_binned_auprc: ("classes", 100),
        F.binary_binned_precision_recall_curve: ("binary", 100),
        F.multiclass_binned_precision_recall_curve: ("classes", 100),
        F.multilabel_binned_precision_recall_curve: ("classes", 100),
    }
    if fn in _binned:
        from torcheval_tpu.metrics.functional.classification.binned_auc import (
            _select_binned_route,
        )
        from torcheval_tpu.metrics.functional.classification.binned_precision_recall_curve import (
            _create_threshold_tensor,
        )

        inp = jax.numpy.asarray(args[0])
        kind, default_t = _binned[fn]
        if kind == "binary":
            # Multi-task binary: (R, N) rows; 1-D: one row of N samples.
            rows = inp.shape[0] if inp.ndim == 2 else 1
            n = inp.shape[-1]
        else:
            # Multiclass/multilabel: (N, C) → C rows of N samples.
            rows = inp.shape[1] if inp.ndim == 2 else 1
            n = inp.shape[0]
        th = _create_threshold_tensor(kwargs.get("threshold", default_t))
        route = _select_binned_route(rows, n, th)
        detail = {
            "broadcast": "fused VPU broadcast-compare (small work)",
            "pallas": "MXU one-hot histogram kernel (ops/pallas_binned.py)",
            "sort": "variadic sort + searchsorted (CPU / kill-switch / "
            "out-of-bounds fallback)",
        }[route]
        return (
            f"{name}: binned counts via {detail} — decided from static "
            f"shapes and flags only, identical under a caller's jit."
        )

    return (
        f"{name}: no call-time routing (single formulation, or not a "
        "routed entry point this helper knows)."
    )
