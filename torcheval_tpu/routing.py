"""Route-downgrade diagnostics: loud fallbacks and ``explain_route``.

Several hot paths pick their formulation at CALL time from concrete
values (the ``_select_binned_route`` pattern): the sort-free rank-sum
AUROC/AUPRC route, the sharded ustat cap autotune, the confusion-matrix
three-way dispatch.  Under a caller's ``jax.jit`` those deciders see
tracers and silently keep the safe-but-slower formulation — the exact
failure mode that once made this repo's own headline clock measure the
189 ms sort path while eager users got the 33 ms routed kernel
(BASELINE.md round-3).  This module makes the downgrade loud (ONE
warning per user callsite) and gives users a way to ask which
formulation a call will take and why.
"""

from __future__ import annotations

import os
import traceback
import warnings
from typing import Optional, Set, Tuple

from torcheval_tpu.telemetry import events as _telemetry

_PKG_MARKER = "torcheval_tpu"
_warned_callsites: Set[Tuple[str, int, str]] = set()
_SKIP_PREFIXES: Optional[Tuple[str, ...]] = None


class RouteDowngradeWarning(UserWarning):
    """A call-time fast-path decider fell back to a slower formulation
    for a reason the caller can fix (usually: pin the decision eagerly,
    e.g. ``ustat_cap=`` / ``max_class_count_per_shard=``)."""


def _skip_prefixes() -> Tuple[str, ...]:
    """Directory prefixes whose frames are never "the user's call line":
    the jax/jaxlib trees (trace-time hooks fire with jax's tracing
    machinery on the stack between the package and the user's jit call).
    Computed once; the package's own frames are matched by name."""
    global _SKIP_PREFIXES
    if _SKIP_PREFIXES is None:
        prefixes = []
        for mod_name in ("jax", "jaxlib"):
            try:
                mod = __import__(mod_name)
                prefixes.append(
                    os.path.dirname(os.path.abspath(mod.__file__)) + os.sep
                )
            except Exception:  # pragma: no cover - module absent/odd layout
                pass
        _SKIP_PREFIXES = tuple(prefixes)
    return _SKIP_PREFIXES


def _user_callsite() -> Tuple[str, int]:
    """First stack frame outside this package (and outside jax's tracing
    machinery) — the user's call line.  When the WHOLE captured stack is
    internal (e.g. ``aot.warmup`` driving updates from inside the
    package), fall back to the outermost frame instead of ``<unknown>``
    so downgrade warnings and telemetry events are never unattributed."""
    stack = traceback.extract_stack(limit=40)[:-1]
    for frame in reversed(stack):
        filename = frame.filename or ""
        if _PKG_MARKER in filename:
            continue
        if any(filename.startswith(p) for p in _skip_prefixes()):
            continue
        return filename, frame.lineno or 0
    if stack:
        outer = stack[0]
        return outer.filename or "<unknown>", outer.lineno or 0
    return "<unknown>", 0


def warn_route_downgrade(kind: str, message: str) -> None:
    """Emit ``RouteDowngradeWarning`` once per (user callsite, kind).

    ``warn_explicit`` at the USER's file/line, with no Python warning
    registry: a plain ``warnings.warn`` from here would register every
    callsite under this module's fixed line, so under default filters
    only the FIRST user callsite would ever warn — and the warning would
    point at package internals instead of the user's jit call."""
    filename, lineno = _user_callsite()
    if _telemetry.ENABLED:
        # Every occurrence is an event (the warning dedupes; the counter
        # must not — "how often does this downgrade fire" is the question
        # an operator asks).
        _telemetry.record_route_downgrade(
            kind, message, callsite=f"{filename}:{lineno}"
        )
    key = (filename, lineno, kind)
    if key in _warned_callsites:
        return
    _warned_callsites.add(key)
    warnings.warn_explicit(
        message, RouteDowngradeWarning, filename, lineno
    )


def reset_route_warnings() -> None:
    """Forget which callsites already warned (test hook)."""
    _warned_callsites.clear()


def _measured_note(decision: str, sig_args=None) -> str:
    """The ``measured`` verdict sentence for :func:`explain_route`: names
    the cost-store numbers that decided ``decision`` (empty when the
    measured-cost layer is off).  ``sig_args`` are the positional batch
    args for shape-keyed decisions; ``None`` for shape-less ones."""
    from torcheval_tpu import routing_autotune as _autotune

    if not _autotune.ENABLED:
        return ""
    signature = (
        "*" if sig_args is None else _autotune.batch_signature(sig_args)
    )
    pref = _autotune.preference(decision, signature)
    if pref is None:
        return (
            "  Measured verdict: no binding cost-store rows for this "
            "shape/device yet — the static heuristic above decided "
            "(aot.warmup(autotune=True) races the candidates)."
        )
    return (
        f"  Measured verdict: {pref['choice']} at "
        f"{pref['seconds'] * 1e3:.3f} ms vs {pref['alt_choice']} at "
        f"{pref['alt_seconds'] * 1e3:.3f} ms ({pref['kind']}, "
        f"{pref['site']} site) — these numbers decided the route."
    )


def hot_path_stats() -> dict:
    """Process-wide update hot-path instrumentation in one dict:

    * ``"trace_counts"`` — how many distinct update programs were BUILT,
      by kind (``"accumulate"`` / ``"windowed"`` / ``"fused_collection"``;
      see :mod:`torcheval_tpu._stats`).  In a steady-state eval loop this
      must stop growing; each +1 is a retrace — through a remote TPU
      compiler, ~15 s of wall clock (bucket the stream or
      :func:`torcheval_tpu.aot.warmup` it).
    * ``"spmd_cache"`` — hits/misses/currsize of the shared sharded-
      program memoizer (``parallel/_compile_cache.py``); climbing misses
      mean program churn (e.g. a fresh mesh per step keys a new entry).

    Compatibility view over :func:`torcheval_tpu.telemetry.report` —
    these two sections read live counters and work with the bus disabled;
    the full report adds callsite attribution, padding waste, collective
    timing, and spans when telemetry is enabled.
    """
    from torcheval_tpu import telemetry

    rep = telemetry.report()
    cache = dict(rep["spmd_cache"])
    cache.pop("hit_rate", None)
    cache.pop("evictions", None)  # legacy view predates bounded caches
    return {"trace_counts": rep["trace_counts"], "spmd_cache": cache}


def _spmd_cache_line() -> str:
    from torcheval_tpu.parallel._compile_cache import spmd_cache_info

    info = spmd_cache_info()
    return (
        f"Sharded-program cache this process: {info.hits} hits / "
        f"{info.misses} misses, {info.currsize} live programs "
        "(see hot_path_stats())."
    )


def explain_route(fn, *args, **kwargs) -> str:
    """Explain which formulation ``fn(*args, **kwargs)`` would run and
    why — a debugging aid for the call-time routed entry points.

    Supported ``fn`` (the ``torcheval_tpu.metrics.functional``
    callables): ``multiclass_auroc``, ``multiclass_auprc``,
    ``binary_auroc``, ``binary_auprc``, ``multiclass_confusion_matrix``,
    ``multiclass_f1_score``, ``multiclass_precision``,
    ``multiclass_recall``, and the binned family (every
    ``*_binned_auroc`` / ``*_binned_auprc`` /
    ``*_binned_precision_recall_curve`` variant).  Call it EAGERLY on
    representative data — inside a jit
    the deciders see tracers, which is exactly the downgrade this helper
    diagnoses.  Returns a one-paragraph human-readable explanation.
    """
    import jax

    import torcheval_tpu.metrics.functional as F
    from torcheval_tpu.metrics.functional._host_checks import all_concrete
    from torcheval_tpu.ops._flags import pallas_disabled, ustat_disabled

    name = getattr(fn, "__name__", str(fn))
    backend = jax.default_backend()

    def env_blockers() -> str:
        if pallas_disabled():
            return "TORCHEVAL_TPU_DISABLE_PALLAS is set"
        if backend != "tpu":
            return f"backend is {backend!r}, not TPU"
        return ""

    if fn in (F.multiclass_auroc, F.multiclass_auprc):
        from torcheval_tpu.ops.pallas_ustat import ustat_route_cap

        scores, target = args[0], args[1]
        num_classes = kwargs.get(
            "num_classes", scores.shape[1] if hasattr(scores, "shape") else None
        )
        cap = ustat_route_cap(
            jax.numpy.asarray(scores), jax.numpy.asarray(target), num_classes
        )
        if cap is not None:
            return (
                f"{name}: sort-free Pallas rank-sum route, table cap {cap}. "
                f"Under a caller's jit this decision sees tracers and falls "
                f"back to the sort path — pin it with ustat_cap={cap} (the "
                f"README 'pinning the rank-sum route under jit' recipe)."
            )
        sharding = getattr(scores, "sharding", None)
        reason = env_blockers() or (
            "inputs are tracers (decide eagerly, then pin ustat_cap)"
            if not all_concrete(scores, target)
            else "TORCHEVAL_TPU_DISABLE_USTAT is set"
            if ustat_disabled()
            else "inputs are mesh-sharded (a pallas_call under plain jit "
            "would replicate the full scores onto every device; the "
            "sharded_* wrappers in torcheval_tpu.parallel keep O(N/P) "
            "per-device economics instead)"
            if sharding is not None and len(sharding.device_set) > 1
            else "data outside the measured win region (small N, "
            "class-skewed counts, non-finite or subnormal scores)"
        )
        return f"{name}: XLA sort + scan path ({reason})."

    if fn in (F.binary_auroc, F.binary_auprc):
        from torcheval_tpu.ops.pallas_ustat import binary_ustat_route

        scores, target = jax.numpy.asarray(args[0]), jax.numpy.asarray(args[1])
        rows = scores[None] if scores.ndim == 1 else scores
        t_rows = target[None] if target.ndim == 1 else target
        route = binary_ustat_route(
            rows, t_rows, need_pos=fn is F.binary_auprc
        )
        if route is not None:
            side, cap = route
            return (
                f"{name}: sort-free rank-sum route against the packed "
                f"{side!r} side, cap {cap} (decided per call; jit callers "
                f"keep the sort path)."
            )
        blocked = env_blockers()
        tail = (
            "fused Pallas scan after a 1-D-layout sort"
            if not blocked
            else "pure-XLA sort + scan"
        )
        return f"{name}: {tail}" + (f" ({blocked})." if blocked else ".")

    _route_detail = {
        "pallas": "bucket-compaction Pallas kernel (ops/pallas_cm.py)",
        "matmul": "one dense one-hot MXU matmul",
        "scatter": "int32 scatter-add (reference formulation)",
    }
    if fn is F.multiclass_confusion_matrix:
        from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
            _cm_route,
        )

        inp = args[0]
        num_classes = kwargs.get("num_classes")
        if num_classes is None and len(args) > 2:
            num_classes = args[2]
        if not isinstance(num_classes, int):
            return (
                f"{name}: not routable — the call itself would fail "
                f"(num_classes is required, got {num_classes!r})."
            )
        route = _cm_route(num_classes, inp.shape[0])
        from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
            _cm_row_chunk,
        )

        crossover = (
            f" One-hot tiles are capped at {_cm_row_chunk()} rows, so the "
            f"matmul's 2·C re-read multiplier applies to a bounded "
            f"working set, not the whole batch; past C=512 (n·C² MACs "
            f"overtaking the ~7 ms flat scatter, measured C=1000 at "
            f"0.64x) the route crosses back to the scatter."
        )
        return (
            f"{name}: confusion-matrix slab via {_route_detail[route]} — "
            f"decided from shapes/backend only, so it is identical under "
            f"a caller's jit." + crossover + _measured_note("cm_row_chunk")
        )

    if fn in (
        F.multiclass_f1_score,
        F.multiclass_precision,
        F.multiclass_recall,
    ):
        from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
            _counts_route,
        )

        inp = args[0]
        average = kwargs.get("average", "micro")
        num_classes = kwargs.get("num_classes")
        if average == "micro":
            return (
                f"{name}: micro average — scatter-free scalar counters "
                "(no per-class trio, no routing)."
            )
        # Mirror the entry point's validation so the debugging aid never
        # crashes on inputs the real call would reject with a clear error
        # (e.g. average=None with num_classes=None).
        if average not in ("macro", "weighted", None):
            return (
                f"{name}: not routable — the call itself would fail "
                f"(average={average!r} is not an allowed value)."
            )
        if not isinstance(num_classes, int) or num_classes <= 0:
            return (
                f"{name}: not routable — the call itself would fail "
                f"(num_classes must be a positive int when "
                f"average={average!r}, got {num_classes!r})."
            )
        route = _counts_route(inp, num_classes, average)
        return (
            f"{name}: per-class count trio via {_route_detail[route]} — "
            f"decided from shapes/backend only, so it is identical under "
            f"a caller's jit."
        )

    # (kind, default threshold count) per binned entry point — kinds fix
    # the (rows, samples) orientation _binned_counts_rows actually sees.
    _binned = {
        F.binary_binned_auroc: ("binary", 200),
        F.binary_binned_auprc: ("binary", 100),
        F.multiclass_binned_auroc: ("classes", 200),
        F.multiclass_binned_auprc: ("classes", 100),
        F.multilabel_binned_auprc: ("classes", 100),
        F.binary_binned_precision_recall_curve: ("binary", 100),
        F.multiclass_binned_precision_recall_curve: ("classes", 100),
        F.multilabel_binned_precision_recall_curve: ("classes", 100),
    }
    if fn in _binned:
        from torcheval_tpu.metrics.functional.classification.binned_auc import (
            _select_binned_route,
        )
        from torcheval_tpu.metrics.functional.classification.binned_precision_recall_curve import (
            _create_threshold_tensor,
        )

        inp = jax.numpy.asarray(args[0])
        kind, default_t = _binned[fn]
        if kind == "binary":
            # Multi-task binary: (R, N) rows; 1-D: one row of N samples.
            rows = inp.shape[0] if inp.ndim == 2 else 1
            n = inp.shape[-1]
        else:
            # Multiclass/multilabel: (N, C) → C rows of N samples.
            rows = inp.shape[1] if inp.ndim == 2 else 1
            n = inp.shape[0]
        th = _create_threshold_tensor(kwargs.get("threshold", default_t))
        route = _select_binned_route(rows, n, th)
        detail = {
            "broadcast": "fused VPU broadcast-compare (small work)",
            "pallas": "MXU one-hot histogram kernel (ops/pallas_binned.py)",
            "sort": "variadic sort + searchsorted (CPU / kill-switch / "
            "out-of-bounds fallback)",
        }[route]
        return (
            f"{name}: binned counts via {detail} — decided from static "
            f"shapes and flags only, identical under a caller's jit."
        )

    # --- text wavefront family ------------------------------------------
    from torcheval_tpu.ops.pallas_wavefront import (
        edit_distance_tokens as _edt,
    )

    if fn in (
        F.word_error_rate,
        F.word_information_preserved,
        F.word_information_lost,
        _edt,
    ):
        from torcheval_tpu.metrics.functional.text.word_error_rate import (
            _is_tokens,
        )
        from torcheval_tpu.ops import _flags as _oflags
        from torcheval_tpu.ops.pallas_wavefront import (
            has_pallas,
            wavefront_plan,
            wavefront_route,
        )

        if fn is not _edt and args and not _is_tokens(args[0]):
            return (
                f"{name}: host string path — per-batch word→id interning "
                "feeds the native C++ batched DP (ctypes, pure-Python "
                "fallback).  Tokenize with metrics/text/_tokens."
                "tokenize_pairs to ride the device wavefront routes."
            )
        mode = _oflags.wavefront_mode()
        # The metric/functional kernels are jitted, so the eager-only
        # native DP is a candidate only for a concrete
        # edit_distance_tokens call.
        concrete = fn is _edt and all_concrete(
            *[a for a in args if a is not None]
        )
        route = wavefront_route(concrete)
        if route != "pallas":
            reason = (
                "the TORCHEVAL_TPU_DISABLE_PALLAS kill-switch outranks "
                "even a forced-on TORCHEVAL_TPU_WAVEFRONT"
                if pallas_disabled()
                else "TORCHEVAL_TPU_WAVEFRONT is falsy"
                if mode is False
                else f"auto mode engages only on TPU (backend is "
                f"{backend!r}); TORCHEVAL_TPU_WAVEFRONT=1 forces the "
                "interpreter elsewhere"
            )
            detail = (
                "the native C++ batch DP (eager concrete call)"
                if route == "native"
                else "the lax.scan anti-diagonal sweep (same integer "
                "arithmetic, any backend)"
            )
            return (
                f"{name}: wavefront Pallas route OFF ({reason}); edit "
                f"distances come from {detail} — integer-exact against "
                "the kernel." + _measured_note("wavefront")
            )
        flagged = (
            "FORCED ON (TORCHEVAL_TPU_WAVEFRONT truthy; the interpreter "
            "emulates off-TPU)"
            if mode
            else "AUTO on this TPU backend"
        )
        shapes = [getattr(a, "shape", None) for a in args[:2]]
        if all(s is not None and len(s) >= 2 for s in shapes):
            n = shapes[0][0]
            len_a = shapes[0][1] if len(shapes[0]) == 2 else shapes[1][1]
            len_b = shapes[1][1]
            plan = wavefront_plan(int(n), int(len_a), int(len_b))
            geometry = (
                f"  Engaged bucket: ({plan['pairs']}, {plan['lanes']}) "
                f"int32 block, one grid sweep of {plan['grid']} "
                f"anti-diagonals, ~{plan['vmem_bytes'] // 1024} KiB VMEM "
                "high water (three rolling diagonal buffers — O(max_len) "
                "memory, never the O(len²) DP matrix)."
            )
        else:
            geometry = (
                "  Pass sample (n, len) id arrays for the engaged bucket "
                "geometry."
            )
        return (
            f"{name}: wavefront Pallas route {flagged} — each DP "
            "anti-diagonal is data-parallel across the whole pair bucket "
            f"(ops/pallas_wavefront.py).{geometry}"
            + _measured_note("wavefront")
        )

    parallel_answer = _explain_parallel_route(fn, name, args, kwargs)
    if parallel_answer is not None:
        # Sharded entry points share one jit(shard_map) memoizer; its
        # counters tell the caller whether this call re-compiles.
        if getattr(fn, "__self__", None) is None:
            parallel_answer += "  " + _spmd_cache_line()
        return parallel_answer

    return (
        f"{name}: no call-time routing (single formulation, or not a "
        "routed entry point this helper knows)."
    )


def _explain_parallel_route(fn, name, args, kwargs):
    """The ``torcheval_tpu.parallel`` sharded entry points and
    ``MetricCollection.fused_update`` — the pod paths, where a silent
    downgrade costs the most wire/compute (round-4 VERDICT weak item 6).
    Returns ``None`` when ``fn`` is none of them."""
    import jax

    import torcheval_tpu.parallel as P
    from torcheval_tpu.metrics.collection import MetricCollection
    from torcheval_tpu.metrics.functional._host_checks import all_concrete
    from torcheval_tpu.parallel.exact import _resolve_multi_axis_comm
    from torcheval_tpu.parallel.mesh import _axis_size

    # --- windowed pair-update metrics (bound .update) --------------------
    from torcheval_tpu.metrics._buffer import WindowedLifetimeMixin

    owner = getattr(fn, "__self__", None)
    if isinstance(owner, WindowedLifetimeMixin) and name == "update":
        from torcheval_tpu._stats import trace_count
        from torcheval_tpu.ops._flags import donation_enabled

        donation = (
            "window/lifetime buffers are donated to XLA (in-place column "
            "writes)"
            if donation_enabled()
            else "window/lifetime buffers are copied each step (donation "
            "off; set TORCHEVAL_TPU_DONATE=1)"
        )
        lifetime = (
            "lifetime sums ride the same dispatch"
            if owner.enable_lifetime
            else "lifetime tracking is off (zero-size placeholders)"
        )
        return (
            f"{name}: fused windowed pair update — the two-statistic "
            "kernel and both ring-window column writes run in ONE jitted "
            f"dispatch (metrics/_buffer.py); {lifetime}, and {donation}.  "
            "The ring cursor is host-side state, so this metric cannot "
            "join MetricCollection.fused_update; the program re-traces "
            "only per batch SHAPE — this process has built "
            f"{trace_count('windowed')} windowed program(s) so far "
            "(hot_path_stats() for the full counters)."
        )

    def _megakernel_verdict(owner, args, kwargs) -> str:
        from torcheval_tpu.ops import _flags as _oflags
        from torcheval_tpu.ops import _mega_plan

        mode = _oflags.megakernel_mode()
        if mode is False:
            return (
                "Megakernel route OFF (TORCHEVAL_TPU_MEGAKERNEL is "
                "falsy); every member runs its own fused update."
            )
        if _oflags.pallas_disabled():
            return (
                "Megakernel route OFF — the TORCHEVAL_TPU_DISABLE_PALLAS "
                "kill-switch outranks even a forced-on flag."
            )
        if len(args) < 2:
            flagged = (
                "FORCED ON (TORCHEVAL_TPU_MEGAKERNEL truthy)"
                if mode
                else "AUTO (engages on TPU with >=2 supported members)"
            )
            return (
                f"Megakernel route {flagged}; pass sample (input, target) "
                "args for the per-shape verdict."
            )
        plan = _mega_plan.plan_for(
            owner._metrics, tuple(args), dict(kwargs), owner._slices
        )
        if plan is not None:
            sup = ", ".join(mp.name for mp in plan.members)
            un = (
                f"; unsupported member(s) "
                f"{', '.join(plan.unsupported)} keep the per-member "
                f"path inside the same program"
                if plan.unsupported
                else ""
            )
            return (
                f"Megakernel route ENGAGED: one Pallas HBM pass (lane "
                f"tile {plan.tile}) scatters into {len(plan.members)} "
                f"member state group(s) [{sup}]{un}."
                + _measured_note("megakernel", tuple(args))
            )
        if mode is None and jax.default_backend() != "tpu":
            return (
                "Megakernel route off: auto mode engages only on TPU "
                "backends (TORCHEVAL_TPU_MEGAKERNEL=1 forces the "
                "interpret path elsewhere)."
                + _measured_note("megakernel", tuple(args))
            )
        return (
            "Megakernel route off for this call: unsupported call shape "
            "or not enough supported members (auto needs >=2, forced "
            "needs >=1; ops/_mega_plan.py lists the supported "
            "accumulation shapes)."
            + _measured_note("megakernel", tuple(args))
        )

    def _rank_sketch_verdict(owner) -> str:
        from torcheval_tpu.metrics._rank_state import predicted_epsilon
        from torcheval_tpu.ops import _flags as _oflags

        engaged, declined = [], []
        for mname, m in owner._metrics.items():
            if getattr(m, "_sketch_mode", False):
                engaged.append((mname, m))
            elif type(m).__name__ in (
                "BinaryAUROC", "BinaryAUPRC", "MulticlassAUROC"
            ):
                declined.append(mname)
        if not engaged and not declined:
            return ""
        parts = []
        if engaged:
            detail = ", ".join(
                f"{mname} ({m._sketch_bins} bins, "
                f"eps<={predicted_epsilon(m):.2e})"
                for mname, m in engaged
            )
            parts.append(
                f"Rank-sketch tier ENGAGED for {len(engaged)} member(s) "
                f"[{detail}]: single-pass sort-free updates on fixed "
                "O(bins) count states, add-mergeable payloads "
                "(ops/rank_sketch.py; see docs/source/sketch.rst for the "
                "sketch-vs-sort crossover)."
            )
        if declined:
            hint = (
                "TORCHEVAL_TPU_RANK_SKETCH is truthy but these members "
                "predate the flip — the state layout is fixed at "
                "construction"
                if _oflags.rank_sketch_enabled()
                else "construct with sketch=True or set "
                "TORCHEVAL_TPU_RANK_SKETCH=1 to trade exact sorting for "
                "a bounded-error single pass"
            )
            parts.append(
                f"Exact sample-buffer member(s) [{', '.join(declined)}] "
                f"keep the sort-per-compute path ({hint})."
            )
        return "  ".join(parts)

    # --- MetricCollection.fused_update (bound method) --------------------
    if isinstance(owner, MetricCollection) and name == "fused_update":
        try:
            owner._check_fusable()
        except ValueError as exc:
            sketch_verdict = _rank_sketch_verdict(owner)
            return (
                f"fused_update: not fusable — the call itself would "
                f"raise ({exc})"
                + (f"  {sketch_verdict}" if sketch_verdict else "")
            )
        from torcheval_tpu._stats import trace_count

        if owner._bucket:
            ragged = (
                f"Ragged batches are padded to power-of-two buckets "
                f"(min {owner._min_bucket}) with a validity mask, so M "
                "batch sizes compile O(log max_batch) programs."
            )
        else:
            ragged = (
                "Bucketing is OFF (bucket=False): every distinct batch "
                "size traces + compiles its own program."
            )
        sketch_verdict = _rank_sketch_verdict(owner)
        donated = owner._fused_apply_donated
        donation = (
            "state buffers are donated to XLA (in-place accumulate)"
            if donated
            else "state buffers are copied each step (donation off; set "
            "TORCHEVAL_TPU_DONATE=1 or donate=True)"
            if donated is not None
            else "donation resolves from TORCHEVAL_TPU_DONATE at first call"
        )
        return (
            "fused_update: all member updates trace into ONE jitted "
            "program.  Inside that trace every member's call-time route "
            "decider sees tracers, so tracer-dependent fast paths (the "
            "rank-sum ustat route) downgrade to their sort formulations "
            "unless pinned via the member's static kwargs (e.g. "
            "ustat_cap); shape-static routes (confusion slab, binned "
            f"counts) are unaffected.  {ragged}  This process has built "
            f"{trace_count('fused_collection')} fused + "
            f"{trace_count('mega_collection')} megakernel program(s) so "
            f"far (hot_path_stats() for the full counters), and "
            f"{donation}.  {_megakernel_verdict(owner, args, kwargs)}"
            + (f"  {sketch_verdict}" if sketch_verdict else "")
        )

    def call_arg(pos, kw, default=None):
        if kw in kwargs:
            return kwargs[kw]
        return args[pos] if len(args) > pos else default

    def mesh_and_axis():
        return call_arg(2, "mesh"), call_arg(3, "axis", "dp")

    # --- binary ustat pair: the cap decides the wire cost ----------------
    _binary_ustat = {
        P.sharded_binary_auroc_ustat: "max_minority_count_per_shard",
        P.sharded_binary_auprc_ustat: "max_positive_count_per_shard",
    }
    if fn in _binary_ustat:
        param = _binary_ustat[fn]
        scores = jax.numpy.asarray(args[0])
        mesh, axis = mesh_and_axis()
        size = _axis_size(mesh, axis)
        n_local = scores.shape[0] // size
        cap = kwargs.get(param)
        comm = kwargs.get("comm", "auto")
        if comm not in ("auto", "gather", "ring"):
            return (
                f"{name}: not routable — the call itself would fail "
                f"(comm should be 'auto', 'gather' or 'ring', got "
                f"{comm!r})."
            )
        try:
            comm = _resolve_multi_axis_comm(comm, axis)
        except ValueError as exc:
            return (
                f"{name}: not routable — the call itself would fail "
                f"({exc})"
            )
        if comm == "auto":
            from torcheval_tpu.parallel.exact import _choose_ustat_comm

            comm = _choose_ustat_comm(
                1, min(cap, n_local) if cap is not None else n_local, size
            )
            auto_note = " (resolved from comm='auto' by pack size)"
        else:
            auto_note = ""
        schedule = (
            "one all-gather of the packed runs"
            if comm == "gather"
            else "ppermute ring over the packed runs (O(cap) peak "
            "memory, counting overlapped per step)"
        ) + auto_note
        if cap is not None:
            return (
                f"{name}: packed-run formulation via {schedule}, cap "
                f"{min(cap, n_local)} per shard — O(P·cap) = "
                f"O({size}·{min(cap, n_local)}) total wire (a host check "
                f"validates the cap unless skip_value_checks)."
            )
        return (
            f"{name}: {param} is None, so each shard packs its FULL "
            f"{n_local}-sample run via {schedule} — O(N) wire like the "
            f"gather-exact path.  Measure the per-shard "
            f"minority/positive maximum eagerly and pass {param}= to "
            f"get O(P·cap) wire."
        )

    # --- multiclass ustat: cap autotune + local-count kernel gate --------
    if fn is P.sharded_multiclass_auroc_ustat:
        from torcheval_tpu.metrics.functional._host_checks import (
            value_checks_enabled,
        )
        from torcheval_tpu.parallel.exact import _eager_ustat_decision

        scores, targets = args[0], args[1]
        mesh, axis = mesh_and_axis()
        num_classes = kwargs.get("num_classes")
        if not isinstance(num_classes, int):
            return (
                f"{name}: not routable — the call itself would fail "
                f"(num_classes is required, got {num_classes!r})."
            )
        comm = kwargs.get("comm", "auto")
        if comm not in ("auto", "gather", "ring"):
            return (
                f"{name}: not routable — the call itself would fail "
                f"(comm should be 'auto', 'gather' or 'ring', got "
                f"{comm!r})."
            )
        try:
            comm = _resolve_multi_axis_comm(comm, axis)
        except ValueError as exc:
            return (
                f"{name}: not routable — the call itself would fail "
                f"({exc})"
            )
        size = _axis_size(mesh, axis)
        n_local = scores.shape[0] // size
        cap = kwargs.get("max_class_count_per_shard")
        if not all_concrete(scores, targets):
            return (
                f"{name}: inputs are tracers — the cap autotune cannot "
                f"run, so the pack widens to the full shard ({n_local} "
                f"rows, O(N·C) wire) and a RouteDowngradeWarning fires.  "
                f"Pin max_class_count_per_shard (see "
                f"parallel.exact.eager_ustat_pin)."
            )
        known_stats = None
        if cap is None:
            if value_checks_enabled() and scores.size:
                cap, known_stats = _eager_ustat_decision(
                    jax.numpy.asarray(scores),
                    jax.numpy.asarray(targets),
                    num_classes,
                    size,
                )
                cap_src = f"autotuned to {cap}"
            else:
                cap, cap_src = n_local, f"full shard ({n_local})"
        else:
            cap = min(cap, n_local)
            cap_src = f"pinned at {cap}"
        # THE wrapper's own gate/resolution helpers — one definition,
        # three surfaces (wrapper, eager_ustat_pin, this explainer).
        from torcheval_tpu.parallel.exact import (
            _choose_ustat_comm,
            _mc_kernel_ok_for_schedule,
            _ring_buys_envelope,
        )

        auto_note = ""
        if comm == "auto":
            comm = _choose_ustat_comm(
                num_classes, cap, size,
                ring_buys_kernel=_ring_buys_envelope(
                    cap, size, n_local * size
                ),
            )
            auto_note = " (resolved from comm='auto')"
        use_kernel = _mc_kernel_ok_for_schedule(
            scores, n_local * size, cap, size, known_stats, comm
        )
        local = (
            "Pallas rank-sum kernel (sort-free)"
            if use_kernel
            else "vmapped variadic-searchsorted (the kernel's "
            "backend/int32/score-domain gate declined)"
        )
        schedule = (
            "one all-gather of the packed runs (O(C·cap·P) wire and "
            "peak memory)"
            if comm == "gather"
            else "ppermute ring over the packed chunks (O(C·cap·P) "
            "total wire, O(C·cap) peak memory, counting overlapped "
            "per step)"
        ) + auto_note
        return (
            f"{name}: packed per-class runs, cap {cap_src}; {schedule}; "
            f"local counting via {local}.  Under a caller's jit the "
            f"autotune and kernel gate see tracers — pin "
            f"max_class_count_per_shard (eager_ustat_pin, with matching "
            f"comm=) to keep the wire bound."
        )

    # --- histogram family: 0/1-target gate + binned-counts dispatch ------
    _hist_detail = {
        "broadcast": "fused VPU broadcast-compare (small work)",
        "pallas": "MXU one-hot histogram kernel (ops/pallas_binned.py)",
        "sort": "variadic sort + searchsorted (CPU / kill-switch / "
        "out-of-bounds fallback)",
    }
    def weighted_verdict(name, weights, num_rows, n_local, num_bins):
        """Mirror ``sync._weighted_kernel_route`` (without its warning):
        kernel vs scatter for a weighted histogram call."""
        from torcheval_tpu.parallel.sync import _hist_route

        if _hist_route(num_rows, n_local, num_bins) != "pallas":
            return (
                f"{name}: weighted — per-device scatter histogram (the "
                f"binned-counts dispatch picks a non-Pallas formulation "
                f"at this work shape/backend, and only the Pallas route "
                f"has a weighted payload kernel)."
            )
        safe = kwargs.get("assume_split_safe_weights")
        if safe is None:
            if not all_concrete(weights):
                return (
                    f"{name}: weighted — weights are tracers, so the "
                    f"weights-domain gate cannot read values: scatter "
                    f"path (and a RouteDowngradeWarning fires).  Pass "
                    f"assume_split_safe_weights=True to keep the Pallas "
                    f"payload kernel reachable under jit."
                )
            from torcheval_tpu.ops.pallas_binned import split_safe_weights

            safe = split_safe_weights(weights)
        if not safe:
            return (
                f"{name}: weighted — per-device scatter histogram (the "
                f"weights fail the exact-bf16-split domain gate: a "
                f"nonzero |weight| below 2^-100, or non-finite)."
            )
        return (
            f"{name}: weighted — Pallas payload kernel "
            f"(ops/pallas_binned._binned_wcount_kernel), one psum of the "
            f"merged statistics; ~1e-6 summation-order contract vs the "
            f"scatter."
        )

    if fn in (P.sharded_auroc_histogram, P.sharded_auprc_histogram):
        from torcheval_tpu.parallel.sync import _binary_hist_gate, _hist_route

        scores, targets = args[0], args[1]
        mesh, axis = mesh_and_axis()
        num_bins = call_arg(4, "num_bins", 8192)
        weights = call_arg(5, "weights")
        assume = kwargs.get("assume_01_targets")
        n_local = scores.shape[0] // _axis_size(mesh, axis)
        if assume is None:
            if not all_concrete(scores, targets):
                return (
                    f"{name}: inputs are tracers, so the 0/1-target gate "
                    f"cannot read values — scatter path.  Pass "
                    f"assume_01_targets=True to keep the binned-counts "
                    f"dispatch reachable under jit."
                )
            assume = _binary_hist_gate(
                jax.numpy.asarray(scores), jax.numpy.asarray(targets)
            )
        if not assume:
            return (
                f"{name}: targets are not verifiably 0/1 — per-device "
                f"scatter histogram (soft-target semantics), one psum of "
                f"2×{num_bins} bins."
            )
        if weights is not None:
            return weighted_verdict(name, weights, 1, n_local, num_bins)
        route = _hist_route(1, n_local, num_bins)
        return (
            f"{name}: unweighted 0/1 targets — per-device binned counts "
            f"via {_hist_detail[route]}, one psum of 2×{num_bins} bins."
        )

    if fn is P.sharded_multiclass_auroc_histogram:
        from torcheval_tpu.parallel.sync import _hist_route

        scores = args[0]
        mesh, axis = mesh_and_axis()
        num_bins = call_arg(4, "num_bins", 2048)
        weights = call_arg(6, "weights")
        num_classes = scores.shape[1]
        n_local = scores.shape[0] // _axis_size(mesh, axis)
        if weights is not None:
            return weighted_verdict(
                name, weights, num_classes, n_local, num_bins
            )
        route = _hist_route(num_classes, n_local, num_bins)
        return (
            f"{name}: per-device ({num_classes}, n_local) binned counts "
            f"via {_hist_detail[route]}, one psum of "
            f"{num_classes}×2×{num_bins} statistics — decided from "
            f"static shapes and flags only, identical under a caller's "
            f"jit."
        )

    # --- gather-exact family: single formulation, wire note --------------
    _gather_exact = (
        P.sharded_binary_auroc_exact,
        P.sharded_binary_auprc_exact,
        P.sharded_multiclass_auroc_exact,
        P.sharded_multitask_auroc_exact,
        P.sharded_multitask_auprc_exact,
    )
    if fn in _gather_exact:
        return (
            f"{name}: single formulation — one tiled all-gather of the "
            f"full sharded batch (O(N) wire), then the single-device "
            f"exact kernel on every device.  No call-time routing; the "
            f"ustat variants trade this wire cost for packed runs."
        )

    return None
