"""Shape bucketing for ragged update streams.

``jax.jit`` caches by input shape, so a stream of M distinct batch sizes
retraces — and, through a remote compiler, recompiles — every metric
program M times.  Padding each batch up to the next power-of-two bucket
caps the distinct shapes at O(log max_batch), and a validity mask keeps
the padded rows out of every count: weighted kernels take the mask as a
zero weight for free, and the unweighted counter kernels (accuracy,
confusion-matrix slab, binned counters, F1/precision/recall trio) have a
mask-aware path that multiplies each row's contribution by its mask bit.

Padded rows EDGE-REPLICATE the last valid row rather than zero-fill, so
class indices stay in range for the host-side validation the update
paths run before dispatch (a zero-filled score row would also be fine,
but a replicated row is valid by construction for every input flavor).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.telemetry import events as _telemetry

# Floor for bucket sizes: batches below this all share one shape, so a
# stream of tiny ragged batches costs ONE compile, not log2(spread).
DEFAULT_MIN_BUCKET = 128


def bucket_size(
    n: int, *, min_bucket: int = DEFAULT_MIN_BUCKET, multiple_of: int = 1
) -> int:
    """The padded batch size for a raw batch of ``n`` rows: the next
    power of two, floored at ``min_bucket``, then rounded up to a
    multiple of ``multiple_of`` (for sharding over a mesh axis whose
    size is not a power of two)."""
    if n < 0:
        raise ValueError(f"batch size must be non-negative, got {n}")
    b = max(int(min_bucket), 1)
    while b < n:
        b *= 2
    if multiple_of > 1:
        b += (-b) % multiple_of
    return b


def bucket_sizes(
    max_batch: int, *, min_bucket: int = DEFAULT_MIN_BUCKET, multiple_of: int = 1
) -> Tuple[int, ...]:
    """Every bucket a stream with batches in ``[0, max_batch]`` can land
    in — the shapes ``aot.warmup`` pre-compiles.  Length is
    O(log2(max_batch / min_bucket) + 1)."""
    sizes = []
    b = bucket_size(0, min_bucket=min_bucket, multiple_of=multiple_of)
    top = bucket_size(max_batch, min_bucket=min_bucket, multiple_of=multiple_of)
    while True:
        sizes.append(b)
        if b >= top:
            return tuple(sizes)
        b = bucket_size(b + 1, min_bucket=min_bucket, multiple_of=multiple_of)


def pad_to_bucket(
    *arrays,
    mask: Optional[jax.Array] = None,
    min_bucket: int = DEFAULT_MIN_BUCKET,
    multiple_of: int = 1,
) -> Tuple[Tuple[jax.Array, ...], jax.Array]:
    """Pad every array's leading (batch) dim up to its power-of-two
    bucket; return ``(padded_arrays, mask)`` where ``mask`` is int32
    ``(bucket,)`` with 1 for real rows and 0 for padding.

    Padded rows edge-replicate the last valid row (see module
    docstring).  An incoming ``mask`` (already-masked data being
    re-bucketed) is padded with zeros and combined.  All arrays must
    share the same leading dim.  Empty batches pad against zeros.
    """
    if not arrays:
        raise ValueError("pad_to_bucket needs at least one array")
    arrays = tuple(jnp.asarray(a) for a in arrays)
    n = arrays[0].shape[0]
    for a in arrays[1:]:
        if a.shape[0] != n:
            raise ValueError(
                "pad_to_bucket requires a shared leading dim, got "
                f"{[a.shape for a in arrays]}."
            )
    if mask is not None:
        mask = jnp.asarray(mask)
        if mask.shape != (n,):
            raise ValueError(
                f"mask must have shape ({n},), got {mask.shape}."
            )
    bucket = bucket_size(n, min_bucket=min_bucket, multiple_of=multiple_of)
    pad = bucket - n
    if _telemetry.ENABLED:
        # rows_padded/rows_valid waste accounting — emitted on the
        # pad == 0 path too, so the per-bucket waste ratio has the full
        # denominator.
        _telemetry.record_bucket_pad(bucket, n, pad)
    if pad == 0:
        out_mask = (
            mask.astype(jnp.int32)
            if mask is not None
            else jnp.ones(n, jnp.int32)
        )
        return arrays, out_mask
    padded = []
    for a in arrays:
        if n == 0:
            fill = jnp.zeros((pad,) + a.shape[1:], a.dtype)
            padded.append(fill)
            continue
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        padded.append(jnp.pad(a, widths, mode="edge"))
    valid = (
        mask.astype(jnp.int32) if mask is not None else jnp.ones(n, jnp.int32)
    )
    out_mask = jnp.concatenate([valid, jnp.zeros(pad, jnp.int32)])
    return tuple(padded), out_mask
