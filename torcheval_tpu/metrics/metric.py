"""Metric core runtime — the state-machine base class every metric builds on.

Capability parity with the reference ``torcheval/metrics/metric.py`` (300 LoC):
state registry (``_add_state``), the update/compute/merge_state lifecycle,
``reset``/``state_dict``/``load_state_dict``/``to``/``device``, the
``_prepare_for_merge_state`` pre-sync hook, and state-type validation
(reference ``metric.py:18-20,52-68,278-300``).

TPU-first design notes
----------------------
* State leaves are immutable ``jax.Array``s — "mutation" is re-binding the
  attribute to a new array produced by a jit-compiled pure kernel.  This is
  the JAX analog of the reference's in-place ``@torch.inference_mode()``
  tensor mutation: no autograd tracking, no version counters, and every
  sufficient-statistic transition is a compiled XLA program.
* The four legal state container types mirror the reference ``TState``
  (Tensor / List / Dict / Deque of Tensors → Array / list / dict / deque of
  Arrays) so buffer-style metrics (AUROC, Cat) and dict-style counters keep
  the same shapes of statefulness.
* ``to(device)`` maps to ``jax.device_put``; under SPMD/pjit the state can
  additionally carry a ``NamedSharding`` and the same code runs sharded.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from collections import defaultdict, deque
from typing import (
    Any,
    Deque,
    Dict,
    Generic,
    Iterable,
    List,
    TypeVar,
    Union,
)

import functools
import logging

import jax
import jax.numpy as jnp

from torcheval_tpu.telemetry import events as _telemetry

_usage_log = logging.getLogger("torcheval_tpu.telemetry")

TComputeReturn = TypeVar("TComputeReturn")

TState = Union[
    jax.Array,
    List[jax.Array],
    Dict[Any, jax.Array],
    Deque[jax.Array],
]

TSelf = TypeVar("TSelf", bound="Metric")

DeviceLike = Union[str, jax.Device, jax.sharding.Sharding, None]

# Where a metric's states live: a single device, or a Sharding placement over
# a mesh under SPMD.
Placement = Union[jax.Device, jax.sharding.Sharding]


def canonicalize_device(device: DeviceLike) -> Placement:
    """Resolve ``None`` / ``"cpu"`` / ``"tpu:0"`` / ``jax.Device`` to a Device.

    A ``jax.sharding.Sharding`` passes through unchanged: under SPMD a
    metric's "device" is a placement over the mesh (usually
    ``NamedSharding(mesh, PartitionSpec())`` so counter states are replicated
    and arithmetic with mesh-sharded update outputs stays on-mesh).  This is
    the TPU generalization of the reference's single-device ``.to()``
    (reference ``metric.py:221-266``).
    """
    if device is None:
        # local_devices, not devices: under multi-host SPMD jax.devices()[0]
        # is process 0's device, non-addressable from other ranks.
        return jax.local_devices()[0]
    if isinstance(device, (jax.Device, jax.sharding.Sharding)):
        return device
    if isinstance(device, str):
        if ":" in device:
            platform, _, idx = device.partition(":")
            local = jax.local_devices(backend=platform)
            i = int(idx)
            # "tpu:5" names a global device id (what __getstate__ records);
            # match it among this process's devices first, falling back to a
            # local positional index (they coincide on a single host).
            for d in local:
                if d.id == i:
                    return d
            return local[i]
        return jax.local_devices(backend=device)[0]
    raise ValueError(f"Invalid device {device!r}.")


def _is_array(value: Any) -> bool:
    return isinstance(value, (jax.Array, jnp.ndarray))


def _check_state_variable_type(name: str, value: Any) -> None:
    """Enforce the four legal state types (reference ``metric.py:278-300``)."""
    if _is_array(value):
        return
    if isinstance(value, list) and all(_is_array(v) for v in value):
        return
    if isinstance(value, deque) and all(_is_array(v) for v in value):
        return
    if isinstance(value, dict) and all(_is_array(v) for v in value.values()):
        return
    raise TypeError(
        "The value of state variable must be an Array, a list of Arrays, "
        f"a dict with Array values, or a deque of Arrays. Got {name}={value!r} instead."
    )


def _zero_scalar() -> jax.Array:
    """Picklable default factory for dict states (reference resets dict
    states to a defaultdict of scalar zeros, ``metric.py:142-148``)."""
    return jnp.asarray(0.0)


def _fresh_array(value: jax.Array, device: "Placement") -> jax.Array:
    """A NEW buffer holding ``value`` on ``device``.

    ``jax.device_put`` onto the array's current device ALIASES the input
    buffer — so a donated update (``donate_argnums`` on the hot paths,
    ``ops/_flags.donation_enabled``) would delete the caller's array too:
    the registry default behind ``reset()``, a checkpoint snapshot, a
    user-held reference.  The explicit copy decouples the live state's
    lifetime from every other holder's.
    """
    return jax.device_put(jnp.array(value, copy=True), device)


def _move_state(value: TState, device: "Placement", fresh: bool = False) -> TState:
    """Copy a state value onto ``device`` (containers are shallow-copied;
    defaultdict-ness is preserved).  ``fresh=True`` forces array leaves
    into NEW buffers (donation safety — see :func:`_fresh_array`);
    container states are never donated, so their leaves keep the cheap
    aliasing ``device_put``."""
    if _is_array(value):
        return _fresh_array(value, device) if fresh else jax.device_put(value, device)
    if isinstance(value, list):
        return [jax.device_put(v, device) for v in value]
    if isinstance(value, deque):
        return deque((jax.device_put(v, device) for v in value), maxlen=value.maxlen)
    if isinstance(value, defaultdict):
        moved = defaultdict(value.default_factory)
        for k, v in value.items():
            moved[k] = jax.device_put(v, device)
        return moved
    if isinstance(value, dict):
        return {k: jax.device_put(v, device) for k, v in value.items()}
    raise TypeError(f"Unsupported state type: {type(value)}")


def _wrap_phase(fn, phase: str):
    """Wrap a subclass's ``update``/``compute`` as a telemetry span hook.

    Disabled (the default), the wrapper is one module-flag branch plus a
    passthrough call; enabled, the phase is timed, its state-memory
    footprint recorded, and (under ``enable(annotate=True)``) the call
    runs inside a ``jax.profiler.TraceAnnotation``.  Inside a fused
    collection trace the member's wrapped update only runs at trace time,
    so steady-state fused dispatch stays span-free.
    """

    @functools.wraps(fn)
    def wrapped(self, *args, **kwargs):
        if not _telemetry.ENABLED:
            return fn(self, *args, **kwargs)
        return _telemetry.timed_phase(self, phase, fn, args, kwargs)

    wrapped.__torcheval_tpu_phase__ = phase
    return wrapped


class Metric(Generic[TComputeReturn], ABC):
    """Base class for all metrics: a registry of array states plus the
    update/compute/merge lifecycle (reference ``Metric``, ``metric.py:23``)."""

    # Capability marker: True on metrics whose ``update`` accepts a
    # ``mask=`` validity array (the ragged-batch bucketing path,
    # ``metrics/_bucket.py``).  ``MetricCollection(bucket=True)`` requires
    # it of every member.
    _supports_mask: bool = False

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        # Telemetry span hooks: every concrete update/compute a subclass
        # defines is wrapped ONCE (inherited already-wrapped methods are
        # left alone), so enabling the bus times each phase with no
        # per-metric opt-in.
        for phase in ("update", "compute"):
            fn = cls.__dict__.get(phase)
            if (
                callable(fn)
                and not getattr(fn, "__isabstractmethod__", False)
                and getattr(fn, "__torcheval_tpu_phase__", None) is None
            ):
                setattr(cls, phase, _wrap_phase(fn, phase))

    def __init__(self: TSelf, *, device: DeviceLike = None) -> None:
        # Usage telemetry analog of the reference's
        # ``torch._C._log_api_usage_once`` (reference ``metric.py:44``):
        # one debug record per construction on a dedicated logger, for
        # deployments that want adoption counts without a torch runtime.
        _usage_log.debug("torcheval_tpu.metrics.%s", type(self).__name__)
        self._device: Placement = canonicalize_device(device)
        self._state_name_to_default: Dict[str, TState] = {}

    # ------------------------------------------------------------------ state
    def _add_state(self, name: str, default: TState) -> None:
        """Register a named state with its default value
        (reference ``metric.py:52-68``).

        The default is copied so later mutation of the caller's object (or of
        the live state, for container types) can never corrupt ``reset()``.
        Arrays are immutable in JAX, so only containers need copying.
        """
        _check_state_variable_type(name, default)
        if _is_array(default):
            stored: TState = default
        elif isinstance(default, list):
            stored = list(default)
        elif isinstance(default, deque):
            stored = deque(default, maxlen=default.maxlen)
        else:
            # Registry keeps a plain-dict copy (picklable); the *live* state
            # preserves the caller's defaultdict-ness via _move_state.
            stored = dict(default)
        self._state_name_to_default[name] = stored
        # fresh=True: the live state must not share a buffer with the
        # registry default, or a donated update would delete the default
        # and break every later reset() (see _fresh_array).
        setattr(self, name, _move_state(default, self._device, fresh=True))

    # ------------------------------------------------------------- lifecycle
    @abstractmethod
    def update(self: TSelf, *_: Any, **__: Any) -> TSelf:
        """Absorb a batch into the sufficient statistics. Returns ``self``
        (chainable, reference ``metric.py:70-78``)."""

    @abstractmethod
    def compute(self) -> TComputeReturn:
        """Turn the sufficient statistics into the final value.  Must be
        idempotent and safe to call before any update
        (reference ``metric.py:80-89``)."""

    @abstractmethod
    def merge_state(self: TSelf, metrics: Iterable[TSelf]) -> TSelf:
        """Merge the state of ``metrics`` into ``self`` — the building block
        for distributed sync (reference ``metric.py:91-110``).  Implementations
        must not modify the input metrics."""

    def _prepare_for_merge_state(self) -> None:
        """Optional pre-sync hook: canonicalize list-states to a single array
        so cross-process gather ships one buffer (reference ``metric.py:112-121``)."""

    # ---------------------------------------------------------------- sketch
    def sketch_state(self, kind: str = "exact", **options: Any) -> Any:
        """Compress this metric's state into a mergeable sketch for the
        hierarchical fleet merge (:mod:`torcheval_tpu.metrics._sketch`).

        The base class supports only ``kind="exact"`` — the whole
        prepared metric, lossless, payload O(samples).  Buffer metrics
        with compressible state (BinaryAUROC, BinaryAUPRC) override this
        to also offer ``"reservoir"`` / ``"histogram"`` / ``"count"``
        with documented error bounds; curve metrics constructed with
        ``sketch=True`` additionally offer ``"rank"`` — their state is
        already a mergeable rank sketch, payload O(compactors); see the
        ``_sketch`` module docstring for the bounds,
        ``docs/source/sketch.rst`` for the rank tier, and
        ``docs/source/fleet.rst`` for selection guidance.
        """
        from torcheval_tpu.metrics._sketch import ExactSketch

        if kind != "exact":
            raise ValueError(
                f"{type(self).__name__} supports only kind='exact' "
                f"sketches, got {kind!r}"
            )
        return ExactSketch.from_metric(self)

    def merge_sketch(self: TSelf, sketch: Any) -> TSelf:
        """Absorb a (merged) sketch back into this metric so a following
        ``compute()`` reflects the fleet.  Sample-domain sketches (exact,
        reservoir) restore; bin-domain sketches (histogram, count, rank)
        are terminal and raise — read their value from
        ``sketch.compute()``.
        """
        sketch.merge_into(self)
        return self

    def reset(self: TSelf) -> TSelf:
        """Re-initialize every state from its default on the current device
        (reference ``metric.py:123-156``)."""
        device = self._device
        for name, default in self._state_name_to_default.items():
            if isinstance(default, dict):
                # Dict states reset to a defaultdict of scalar zeros
                # (reference ``metric.py:142-148``).
                fresh: TState = defaultdict(
                    lambda: jax.device_put(jnp.asarray(0.0), device)
                )
                for k, v in default.items():
                    fresh[k] = jax.device_put(v, device)
                setattr(self, name, fresh)
            else:
                setattr(self, name, _move_state(default, device, fresh=True))
        return self

    # ---------------------------------------------------------- checkpointing
    def state_dict(self) -> Dict[str, TState]:
        """Snapshot of all states (reference ``metric.py:158-186``).

        Array states are snapshotted into FRESH buffers: arrays are
        immutable, but under donated updates (``ops/_flags
        .donation_enabled``) the live buffer is deleted by the next
        ``update()`` — an aliased snapshot would dangle.  Containers are
        shallow-copied (never donated).  The result is a pytree of
        arrays — directly orbax-checkpointable.
        """
        out: Dict[str, TState] = {}
        for name in self._state_name_to_default:
            value = getattr(self, name)
            if _is_array(value):
                out[name] = _fresh_array(value, self._device)
            elif isinstance(value, list):
                out[name] = list(value)
            elif isinstance(value, deque):
                out[name] = list(value)
            else:
                out[name] = dict(value)
        return out

    def load_state_dict(
        self, state_dict: Dict[str, TState], strict: bool = True
    ) -> None:
        """Restore states from a snapshot (reference ``metric.py:188-219``)."""
        state_dict = dict(state_dict)
        metric_state_names = set(self._state_name_to_default.keys())
        provided_keys = set(state_dict.keys())
        for name in metric_state_names:
            if name in state_dict:
                value = state_dict.pop(name)
                default = self._state_name_to_default[name]
                if isinstance(default, deque) and isinstance(value, list):
                    value = deque(value, maxlen=default.maxlen)
                _check_state_variable_type(name, value)
                # fresh=True: the caller keeps its checkpoint arrays; a
                # donated update must not delete them out from under it.
                setattr(self, name, _move_state(value, self._device, fresh=True))
        if strict:
            unexpected_keys = set(state_dict.keys())
            missing_keys = metric_state_names - provided_keys
            if missing_keys or unexpected_keys:
                raise RuntimeError(
                    "Error(s) in loading state_dict for "
                    f"{self.__class__.__name__}. "
                    f"Encountered missing keys: {missing_keys} and unexpected "
                    f"keys: {unexpected_keys}."
                )

    # --------------------------------------------------------------- devices
    def to(self: TSelf, device: DeviceLike, *args: Any, **kwargs: Any) -> TSelf:
        """Move every state onto ``device`` (reference ``metric.py:221-266``).
        Extra args are accepted for reference-signature parity and ignored
        (they configured torch transfer semantics, e.g. ``non_blocking``)."""
        device = canonicalize_device(device)
        for name in self._state_name_to_default:
            value = getattr(self, name)
            if isinstance(value, defaultdict):
                moved: TState = defaultdict(
                    lambda: jax.device_put(jnp.asarray(0.0), device)
                )
                for k, v in value.items():
                    moved[k] = jax.device_put(v, device)
                setattr(self, name, moved)
            else:
                setattr(self, name, _move_state(value, device))
        self._device = device
        return self

    @property
    def device(self) -> "Placement":
        """The device all state currently lives on (reference ``metric.py:268-274``)."""
        return self._device

    # ---------------------------------------------------------------- pickle
    def __getstate__(self) -> Dict[str, Any]:
        state = self.__dict__.copy()
        # jax.Device objects are not picklable; record platform:index instead.
        # A mesh Sharding placement degrades to its first device: the
        # receiving process (object-sync path) has its own mesh and must
        # re-place with ``.to(sharding)`` if it wants SPMD state.
        device = state.pop("_device")
        if isinstance(device, jax.sharding.Sharding):
            device = min(device.device_set, key=lambda d: d.id)
        state["_device_str"] = f"{device.platform}:{device.id}"
        return {k: _to_numpy_tree(v) for k, v in state.items()}

    def __setstate__(self, state: Dict[str, Any]) -> None:
        device_str = state.pop("_device_str", None)
        try:
            device = canonicalize_device(device_str)
        except (RuntimeError, IndexError, ValueError):
            # E.g. a metric pickled on another host recorded a device id this
            # process cannot address; land on the local default instead.
            device = jax.local_devices()[0]
        self.__dict__.update(
            {k: _from_numpy_tree(v, device) for k, v in state.items()}
        )
        self._device = device
        # Dict states come back as plain dicts (user default factories are
        # not picklable in general); restore defaultdict-ness with the
        # standard scalar-zero factory.
        for name, default in self._state_name_to_default.items():
            value = getattr(self, name, None)
            if isinstance(default, dict) and isinstance(value, dict):
                restored = defaultdict(_zero_scalar)
                restored.update(value)
                setattr(self, name, restored)


def _to_numpy_tree(value: Any) -> Any:
    """Convert arrays (possibly nested in state containers) to numpy for pickling."""
    import numpy as np

    if _is_array(value):
        return np.asarray(value)
    if isinstance(value, list):
        return [_to_numpy_tree(v) for v in value]
    if isinstance(value, deque):
        return deque((_to_numpy_tree(v) for v in value), maxlen=value.maxlen)
    if isinstance(value, dict):
        return {k: _to_numpy_tree(v) for k, v in value.items()}
    return value


def _from_numpy_tree(value: Any, device: jax.Device) -> Any:
    import numpy as np

    if isinstance(value, np.ndarray) or isinstance(value, np.generic):
        return jax.device_put(jnp.asarray(value), device)
    if isinstance(value, list):
        return [_from_numpy_tree(v, device) for v in value]
    if isinstance(value, deque):
        return deque((_from_numpy_tree(v, device) for v in value), maxlen=value.maxlen)
    if isinstance(value, dict):
        return {k: _from_numpy_tree(v, device) for k, v in value.items()}
    return value
