"""Mean squared error — parity with reference
``torcheval/metrics/functional/regression/mean_squared_error.py`` (142 LoC).

Sufficient statistics: weighted streaming sums of squared error and weight —
a single fused reduction per batch on TPU (jit kernels mirror the reference's
``@torch.jit.script`` sites at ``mean_squared_error.py:81-110``)."""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def mean_squared_error(
    input,
    target,
    *,
    sample_weight=None,
    multioutput: str = "uniform_average",
) -> jax.Array:
    """Weighted MSE with ``uniform_average`` / ``raw_values`` multioutput
    (reference ``mean_squared_error.py:7-66``)."""
    _mean_squared_error_param_check(multioutput)
    input, target = jnp.asarray(input), jnp.asarray(target)
    if sample_weight is not None:
        sample_weight = jnp.asarray(sample_weight)
    sum_squared_error, sum_weight = _mean_squared_error_update(
        input, target, sample_weight
    )
    return _mean_squared_error_compute(sum_squared_error, multioutput, sum_weight)


def _mean_squared_error_update(
    input: jax.Array,
    target: jax.Array,
    sample_weight: Optional[jax.Array],
) -> Tuple[jax.Array, jax.Array]:
    _mean_squared_error_update_input_check(input, target, sample_weight)
    if sample_weight is None:
        return _update_unweighted(input, target)
    return _update_weighted(input, target, sample_weight)


@jax.jit
def _update_unweighted(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    squared_error = jnp.square(target - input)
    return squared_error.sum(axis=0), jnp.asarray(target.shape[0])


@jax.jit
def _update_weighted(
    input: jax.Array, target: jax.Array, sample_weight: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    squared_error = jnp.square(target - input)
    if squared_error.ndim == 2:
        sample_weight_b = sample_weight[:, None]
    else:
        sample_weight_b = sample_weight
    sum_squared_error = (squared_error * sample_weight_b).sum(axis=0)
    sum_weight = jnp.squeeze(sample_weight_b.sum(axis=0))
    return sum_squared_error, sum_weight


@jax.jit
def _mse_raw(sum_squared_error: jax.Array, sum_weight: jax.Array) -> jax.Array:
    return sum_squared_error / sum_weight


@jax.jit
def _mse_mean(sum_squared_error: jax.Array, sum_weight: jax.Array) -> jax.Array:
    return (sum_squared_error / sum_weight).mean()


def _mean_squared_error_compute(
    sum_squared_error: jax.Array,
    multioutput: str,
    sum_weight: jax.Array,
) -> jax.Array:
    if multioutput == "raw_values":
        return _mse_raw(sum_squared_error, sum_weight)
    return _mse_mean(sum_squared_error, sum_weight)


def _mean_squared_error_update_input_check(
    input: jax.Array,
    target: jax.Array,
    sample_weight: Optional[jax.Array],
) -> None:
    if input.ndim >= 3 or target.ndim >= 3:
        raise ValueError(
            "The dimension `input` and `target` should be 1D or 2D, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same size, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if sample_weight is not None and target.shape[0] != sample_weight.shape[0]:
        raise ValueError(
            "The first dimension of `input`, `target` and `sample_weight` "
            f"should be the same size, got shapes {input.shape}, "
            f"{target.shape} and {sample_weight.shape}."
        )


def _mean_squared_error_param_check(multioutput: str) -> None:
    if multioutput not in ("raw_values", "uniform_average"):
        raise ValueError(
            "The `multioutput` must be either `raw_values` or `uniform_average`, "
            f"got multioutput={multioutput}."
        )
