"""R² score — parity with reference
``torcheval/metrics/functional/regression/r2_score.py`` (188 LoC).

Streaming sufficient statistics (mergeable by addition):
``tss = Σy² − (Σy)²/n``, ``r² = 1 − rss/tss``; ``raw_values`` /
``uniform_average`` / ``variance_weighted`` multioutput and adjusted-R² via
``num_regressors`` (reference ``r2_score.py:97-156``).  Compute-time
guards (n ≥ 2, num_regressors < n−1) stay on host (reference
``r2_score.py:117-125``; SURVEY §7 hard part 5)."""

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional._host_checks import all_concrete


def r2_score(
    input,
    target,
    *,
    multioutput: str = "uniform_average",
    num_regressors: int = 0,
) -> jax.Array:
    """R² (coefficient of determination), optionally adjusted
    (reference ``r2_score.py:~20-80``)."""
    _r2_score_param_check(multioutput, num_regressors)
    input, target = jnp.asarray(input), jnp.asarray(target)
    _r2_score_update_input_check(input, target)
    # One-shot path: the sample count is static shape info, so the
    # data-size guards raise at trace time too (the compute-side guard
    # only covers the class path, whose num_obs is accumulated state).
    # Runs after the shape checks so mismatched inputs get the real error.
    _r2_score_size_check(target.shape[0] if target.ndim else 0, num_regressors)
    sum_squared_obs, sum_obs, sum_squared_residual, num_obs = _r2_score_update(
        input, target
    )
    return _r2_score_compute(
        sum_squared_obs,
        sum_obs,
        sum_squared_residual,
        num_obs,
        multioutput,
        num_regressors,
    )


def _r2_score_update(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    _r2_score_update_input_check(input, target)
    return _update(input, target)


@jax.jit
def _update(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    sum_squared_obs = jnp.sum(jnp.square(target), axis=0)
    sum_obs = jnp.sum(target, axis=0)
    sum_squared_residual = jnp.sum(jnp.square(target - input), axis=0)
    num_obs = jnp.asarray(target.shape[0])
    return sum_squared_obs, sum_obs, sum_squared_residual, num_obs


def _r2_score_compute(
    sum_squared_obs: jax.Array,
    sum_obs: jax.Array,
    rss: jax.Array,
    num_obs: jax.Array,
    multioutput: str,
    num_regressors: int,
) -> jax.Array:
    # The class streaming path accumulates num_obs as device state; its
    # guards run only on concrete values (under tracing they cannot be
    # evaluated).  The functional one-shot path checks statically in
    # ``r2_score`` before this point.
    if all_concrete(num_obs):
        _r2_score_size_check(int(num_obs), num_regressors)
    return _compute(sum_squared_obs, sum_obs, rss, num_obs, multioutput, num_regressors)


def _r2_score_size_check(num_obs: int, num_regressors: int) -> None:
    if num_obs < 2:
        raise ValueError(
            "Not enough data to compute: the R2 score needs at least two "
            "samples."
        )
    if num_regressors >= num_obs - 1:
        raise ValueError(
            "The `num_regressors` must be smaller than n_samples - 1, "
            f"got num_regressors={num_regressors}, n_samples={num_obs}.",
        )


@partial(jax.jit, static_argnames=("multioutput", "num_regressors"))
def _compute(
    sum_squared_obs: jax.Array,
    sum_obs: jax.Array,
    rss: jax.Array,
    num_obs: jax.Array,
    multioutput: str,
    num_regressors: int,
) -> jax.Array:
    tss = sum_squared_obs - jnp.square(sum_obs) / num_obs
    r_squared = 1 - (rss / tss)
    if multioutput == "uniform_average":
        r_squared = jnp.mean(r_squared)
    elif multioutput == "variance_weighted":
        r_squared = jnp.sum(r_squared * tss / jnp.sum(tss))
    if num_regressors != 0:
        r_squared = 1 - (1 - r_squared) * (num_obs - 1) / (
            num_obs - num_regressors - 1
        )
    return r_squared


def _r2_score_param_check(multioutput: str, num_regressors: int) -> None:
    if multioutput not in ("raw_values", "uniform_average", "variance_weighted"):
        raise ValueError(
            "The `multioutput` must be either `raw_values` or "
            "`uniform_average` or `variance_weighted`, "
            f"got multioutput={multioutput}."
        )
    if not isinstance(num_regressors, int) or num_regressors < 0:
        raise ValueError(
            "The `num_regressors` must an integer larger or equal to zero, "
            f"got num_regressors={num_regressors}."
        )


def _r2_score_update_input_check(input: jax.Array, target: jax.Array) -> None:
    if input.ndim >= 3 or target.ndim >= 3:
        raise ValueError(
            "The dimension `input` and `target` should be 1D or 2D, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same size, "
            f"got shapes {input.shape} and {target.shape}."
        )
