"""Weighted sum — parity with reference
``torcheval/metrics/functional/aggregation/sum.py`` (56 LoC)."""

from typing import Union

import jax
import jax.numpy as jnp
import numpy as np


def sum(input, weight: Union[float, int, "jax.Array"] = 1.0) -> jax.Array:  # noqa: A001
    """Weighted sum of ``input``; scalar or same-size array ``weight``
    (reference ``sum.py:43-56``)."""
    return _sum_update(jnp.asarray(input), weight)


def _sum_validate(input: jax.Array, weight) -> None:
    if isinstance(weight, (float, int)) or (
        isinstance(weight, (jax.Array, jnp.ndarray, np.ndarray))
        and input.shape == jnp.shape(weight)
    ):
        return
    raise ValueError(
        "Weight must be either a float value or an int value or a tensor "
        f"that matches the input tensor size. Got {weight} instead."
    )


def _sum_update(input: jax.Array, weight) -> jax.Array:
    _sum_validate(input, weight)
    return _weighted_sum(input, weight)


@jax.jit
def _weighted_sum(input: jax.Array, weight) -> jax.Array:
    return (input * weight).sum()
