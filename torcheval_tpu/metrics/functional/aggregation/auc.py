"""AUC — trapezoidal area under an arbitrary sampled (x, y) curve.

Beyond the v0.0.4 snapshot (upstream torcheval added the aggregation
``auc`` later).  One fused sort (when ``reorder``) + trapezoid kernel;
multi-task via a leading dim like the other aggregation metrics."""

from functools import partial

import jax
import jax.numpy as jnp


def auc(x, y, *, reorder: bool = True, num_tasks: int = 1) -> jax.Array:
    """Area under the piecewise-linear curve through the ``(x, y)`` points;
    ``reorder`` sorts the points by x first (needed whenever the x samples
    are not already monotonic)."""
    x, y = jnp.asarray(x), jnp.asarray(y)
    _auc_input_check(x, y, num_tasks)
    return _auc_compute_kernel(x, y, reorder)


@partial(jax.jit, static_argnames=("reorder",))
def _auc_compute_kernel(x: jax.Array, y: jax.Array, reorder: bool) -> jax.Array:
    squeeze = x.ndim == 1
    if squeeze:
        x, y = x[None], y[None]
    if reorder:
        order = jnp.argsort(x, axis=-1)
        x = jnp.take_along_axis(x, order, axis=-1)
        y = jnp.take_along_axis(y, order, axis=-1)
    area = jnp.trapezoid(y, x, axis=-1)
    return area[0] if squeeze else area


def _auc_input_check(x: jax.Array, y: jax.Array, num_tasks: int) -> None:
    if x.shape != y.shape:
        raise ValueError(
            f"`x` and `y` should have the same shape, got {x.shape} and "
            f"{y.shape}."
        )
    if num_tasks == 1:
        if x.ndim != 1:
            raise ValueError(
                "`x` should be a one-dimensional tensor for num_tasks = 1, "
                f"got shape {x.shape}."
            )
    elif x.ndim != 2 or x.shape[0] != num_tasks:
        raise ValueError(
            f"`x` should have shape ({num_tasks}, num_samples) for "
            f"num_tasks = {num_tasks}, got shape {x.shape}."
        )
