"""Click-through rate — weighted click fraction Σw·click / Σw per task.

Beyond the v0.0.4 snapshot (upstream torcheval added
``click_through_rate`` later).  Same per-task sufficient-statistic shape
as weighted calibration: two add-mergeable sums."""

from typing import Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.functional._host_checks import (
    all_concrete,
    value_checks_enabled,
)


def click_through_rate(
    input,
    weights: Union[float, int, "jax.Array"] = 1.0,
    *,
    num_tasks: int = 1,
) -> jax.Array:
    """CTR per task over 0/1 click events; ``weights`` is a scalar or a
    per-event array of impression weights."""
    input = jnp.asarray(input)
    kernel, args = _ctr_select_kernel(input, weights, num_tasks=num_tasks)
    click_total, weight_total = kernel(*args)
    return click_total / weight_total


@jax.jit
def _ctr_scalar_kernel(
    input: jax.Array, weights
) -> Tuple[jax.Array, jax.Array]:
    n = input.shape[-1]
    return weights * jnp.sum(input, axis=-1), weights * jnp.full(
        input.shape[:-1], n, dtype=input.dtype
    )


@jax.jit
def _ctr_array_kernel(
    input: jax.Array, weights: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    return jnp.sum(weights * input, axis=-1), jnp.sum(
        jnp.broadcast_to(weights, input.shape), axis=-1
    )


def _ctr_select_kernel(
    input: jax.Array,
    weights: Union[float, int, "jax.Array"],
    *,
    num_tasks: int,
):
    """Validate and pick the matching jitted kernel; returns
    ``(kernel, args)`` so callers can dispatch it directly or fused."""
    _ctr_input_check(input, weights, num_tasks=num_tasks)
    if isinstance(weights, (float, int)):
        return _ctr_scalar_kernel, (input, float(weights))
    weights = jnp.asarray(weights)
    if weights.ndim == 0:  # scalar array: same path as a Python float
        return _ctr_scalar_kernel, (input, weights)
    return _ctr_array_kernel, (input, weights)


def _ctr_input_check(
    input: jax.Array,
    weights: Union[float, int, "jax.Array"],
    *,
    num_tasks: int,
) -> None:
    if num_tasks == 1:
        if input.ndim != 1:
            raise ValueError(
                "`input` should be a one-dimensional tensor for num_tasks = 1, "
                f"got shape {input.shape}."
            )
    elif input.ndim != 2 or input.shape[0] != num_tasks:
        raise ValueError(
            f"`input` should have shape ({num_tasks}, num_samples) for "
            f"num_tasks = {num_tasks}, got shape {input.shape}."
        )
    if not isinstance(weights, (float, int)):
        wshape = jnp.shape(weights)
        if wshape not in ((), input.shape, input.shape[-1:]):
            raise ValueError(
                "`weights` must be a float, or a tensor broadcastable to the "
                f"input shape {input.shape}, got shape {wshape}."
            )
    # Click events must be 0/1 — a data-dependent check, skipped under
    # tracing like every host-side value check (_host_checks.py).
    if input.size and all_concrete(input) and value_checks_enabled():
        vals = np.asarray(jax.device_get(_ctr_binary_probe(input)))
        if not bool(vals):
            raise ValueError(
                "`input` should be a binary tensor of 0/1 click events."
            )


@jax.jit
def _ctr_binary_probe(input: jax.Array) -> jax.Array:
    return jnp.all((input == 0) | (input == 1))
