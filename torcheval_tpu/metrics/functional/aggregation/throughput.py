"""Throughput — parity with reference
``torcheval/metrics/functional/aggregation/throughput.py`` (47 LoC).

Host-time semantics: inputs are Python numbers, not arrays — elapsed time is
wall-clock measured outside the device (reference ``throughput.py:24-47``;
SURVEY §7 hard part 6)."""

import jax
import jax.numpy as jnp


def throughput(num_processed: int = 0, elapsed_time_sec: float = 0.0) -> jax.Array:
    """Items processed per second (reference ``throughput.py:24-47``)."""
    return _throughput_compute(num_processed, elapsed_time_sec)


def _throughput_compute(num_processed: int, elapsed_time_sec: float) -> jax.Array:
    if num_processed < 0:
        raise ValueError(
            "Expected num_processed to be a non-negative number, but "
            f"received {num_processed}."
        )
    if elapsed_time_sec <= 0:
        raise ValueError(
            "Expected elapsed_time_sec to be a positive number, but "
            f"received {elapsed_time_sec}."
        )
    return jnp.asarray(num_processed / elapsed_time_sec)
