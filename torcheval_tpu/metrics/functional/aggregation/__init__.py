from torcheval_tpu.metrics.functional.aggregation.mean import mean
from torcheval_tpu.metrics.functional.aggregation.sum import sum  # noqa: A004
from torcheval_tpu.metrics.functional.aggregation.throughput import throughput

__all__ = ["mean", "sum", "throughput"]
