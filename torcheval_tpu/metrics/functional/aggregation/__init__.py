from torcheval_tpu.metrics.functional.aggregation.auc import auc
from torcheval_tpu.metrics.functional.aggregation.click_through_rate import (
    click_through_rate,
)
from torcheval_tpu.metrics.functional.aggregation.mean import mean
from torcheval_tpu.metrics.functional.aggregation.sum import sum  # noqa: A004
from torcheval_tpu.metrics.functional.aggregation.throughput import throughput

__all__ = ["auc", "click_through_rate", "mean", "sum", "throughput"]
