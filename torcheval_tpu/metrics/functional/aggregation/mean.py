"""Weighted mean — parity with reference
``torcheval/metrics/functional/aggregation/mean.py`` (65 LoC)."""

from typing import Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np


def mean(input, weight: Union[float, int, "jax.Array"] = 1.0) -> jax.Array:
    """``sum(weight * input) / sum(weight)`` (reference ``mean.py:44-58``)."""
    weighted_sum, weights = _mean_update(jnp.asarray(input), weight)
    return weighted_sum / weights


def _mean_select_kernel(input: jax.Array, weight):
    """Validate ``weight`` and pick the matching jitted kernel; returns
    ``(kernel, args)`` so callers can dispatch it directly or fused."""
    if isinstance(weight, (float, int)):
        return _scalar_weighted, (input, float(weight))
    if isinstance(weight, (jax.Array, jnp.ndarray, np.ndarray)) and input.shape == jnp.shape(
        weight
    ):
        return _array_weighted, (input, weight)
    raise ValueError(
        "Weight must be either a float value or a tensor that matches the "
        f"input tensor size. Got {weight} instead."
    )


def _mean_update(input: jax.Array, weight) -> Tuple[jax.Array, jax.Array]:
    kernel, args = _mean_select_kernel(input, weight)
    return kernel(*args)


@jax.jit
def _scalar_weighted(input: jax.Array, weight: float) -> Tuple[jax.Array, jax.Array]:
    return weight * jnp.sum(input), jnp.asarray(weight * input.size)


@jax.jit
def _array_weighted(input: jax.Array, weight: jax.Array) -> Tuple[jax.Array, jax.Array]:
    return jnp.sum(weight * input), jnp.sum(weight)
