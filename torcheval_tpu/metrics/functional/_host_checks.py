"""Single-round-trip host-side value checks for update-path validation.

Several update paths must raise on data-dependent conditions (out-of-range
class indices, probabilities outside [0, 1]) because XLA scatters/gathers
silently drop or clamp out-of-bounds indices where torch ``scatter_`` /
``gather`` raise (reference e.g.
``torcheval/metrics/functional/classification/confusion_matrix.py:245-280``).

Checking on host forces a device→host sync, and a sync costs a full round
trip — ~10µs locally but tens of milliseconds through a tunneled backend.
The helpers here fuse *all* of a validation's reductions into one jitted
kernel returning one small packed array, so every ``update()`` pays exactly
one round trip for validation instead of one per bound (the previous
``int(jnp.min(x))``/``int(jnp.max(x))`` pattern cost 4 syncs per
1000-class confusion-matrix update and dominated the benchmark end-to-end).
"""

from contextlib import contextmanager
from contextvars import ContextVar
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu import _flags

# Even one fused validation round trip costs a full host sync — ~10 µs on
# a PCIe host, tens of ms through a tunneled backend, where it can
# dominate µs-scale update kernels.  Both switches put the update path in
# the same skip-value-checks mode it already runs in under jit tracing.
_SKIP_CHECKS: ContextVar = ContextVar("torcheval_tpu_skip_value_checks", default=False)


@contextmanager
def skip_value_checks():
    """Disable data-dependent (value) validation of update inputs inside
    the block.

    Shape and parameter validation still applies; out-of-range indices
    are then dropped by XLA's scatter semantics instead of raising —
    exactly the documented behavior when composing the functional metrics
    into a user jit program.  Use for throughput-critical update loops on
    pre-validated data (or set ``TORCHEVAL_TPU_SKIP_VALUE_CHECKS=1`` to
    disable process-wide)."""
    token = _SKIP_CHECKS.set(True)
    try:
        yield
    finally:
        _SKIP_CHECKS.reset(token)


def value_checks_enabled() -> bool:
    """False inside :func:`skip_value_checks` or when the
    ``TORCHEVAL_TPU_SKIP_VALUE_CHECKS`` env var is truthy (read at call
    time, so harnesses may set it after import).  Gates only the
    update-path *data* validations; parameter checks and compute-time
    guards key on :func:`all_concrete` alone."""
    if _SKIP_CHECKS.get():
        return False
    return not _flags.get("SKIP_VALUE_CHECKS")


def all_concrete(*arrays) -> bool:
    """False when any input is a JAX tracer (inside ``jit``/``vmap``/
    ``grad``).  Data-dependent host checks cannot be evaluated at trace
    time, so callers skip them under tracing — this is what makes the
    functional API composable into larger jitted programs (shape and
    static-argument validation still applies; out-of-range indices are
    then dropped by XLA's scatter semantics instead of raising, as
    documented)."""
    return not any(isinstance(a, jax.core.Tracer) for a in arrays)


@jax.jit
def _bounds_kernel(arrays):
    # One stacked (2n,) result: a single dispatch and a single tiny fetch.
    # The common dtype follows JAX promotion from the inputs (at least
    # float32), so float64 probability checks under jax_enable_x64 keep
    # full precision instead of being narrowed to float32.
    dtype = jnp.result_type(*arrays, jnp.float32)
    return jnp.stack(
        [f(a).astype(dtype) for a in arrays for f in (jnp.min, jnp.max)]
    )


def bounds(*arrays: jax.Array) -> np.ndarray:
    """Fused ``[min, max]`` per array, one device round trip for all of them.

    Returns a flat numpy float array ``[min0, max0, min1, max1, ...]`` in
    the promoted dtype of the inputs (float32 minimum, float64 when an
    x64 input is present).  Exact for integer class indices below 2^24
    (any real ``num_classes``).  Callers must skip empty arrays themselves
    (``jnp.min`` of empty raises) and tracers (``all_concrete``).
    """
    out = _bounds_kernel(tuple(arrays))
    if isinstance(out, jax.core.Tracer):
        # Inside someone else's trace every jax op is staged — even on
        # concrete inputs — so the fused kernel yields a tracer.  Pure
        # numpy on the (concrete) host values stays outside the trace
        # (rare path: validating a concrete closure array inside a user's
        # jit; the device→host copy is the unavoidable cost).
        host = [np.asarray(a) for a in arrays]
        return np.asarray(
            [f(h) for h in host for f in (np.min, np.max)], dtype=np.float64
        )
    return np.asarray(out)


def check_index_ranges(
    pairs: Sequence[Tuple[jax.Array, str]], upper: Optional[int]
) -> None:
    """Range-check several class-index arrays with ALL bounds fused into one
    dispatch — a validation costs one device round trip regardless of how
    many arrays it covers.  Raises for the first violating array in order
    (OOB indices must raise: XLA scatters/gathers silently drop or clamp
    them where torch ``scatter_``/``gather`` error)."""
    if upper is None or not value_checks_enabled():
        return
    # Skip only the arrays that are tracers — a concrete array alongside a
    # traced one still gets its eager raise-on-OOB behavior.
    pairs = [(v, n) for v, n in pairs if v.size and all_concrete(v)]
    if not pairs:
        return
    vals = bounds(*(v for v, _ in pairs))
    for i, (_, name) in enumerate(pairs):
        lo, hi = vals[2 * i], vals[2 * i + 1]
        if lo < 0 or hi >= upper:
            raise ValueError(
                f"{name} values should be in [0, {upper}), got min "
                f"{int(lo)} max {int(hi)}."
            )
