"""Stateless functional metrics (reference
``torcheval/metrics/functional/__init__.py:38-68`` — 28 public functions)."""

from torcheval_tpu.metrics.functional.aggregation import (  # noqa: A004
    mean,
    sum,
    throughput,
)
from torcheval_tpu.metrics.functional.classification import (
    binary_accuracy,
    binary_auroc,
    binary_precision_recall_curve,
    multiclass_auroc,
    multiclass_precision_recall_curve,
    binary_binned_precision_recall_curve,
    binary_confusion_matrix,
    binary_f1_score,
    binary_normalized_entropy,
    binary_precision,
    binary_recall,
    multiclass_accuracy,
    multiclass_binned_precision_recall_curve,
    multiclass_confusion_matrix,
    multiclass_f1_score,
    multiclass_precision,
    multiclass_recall,
    multilabel_accuracy,
    topk_multilabel_accuracy,
)
from torcheval_tpu.metrics.functional.ranking import (
    frequency_at_k,
    hit_rate,
    num_collisions,
    reciprocal_rank,
    weighted_calibration,
)
from torcheval_tpu.metrics.functional.regression import (
    mean_squared_error,
    r2_score,
)

__all__ = [
    "binary_accuracy",
    "binary_auroc",
    "binary_precision_recall_curve",
    "frequency_at_k",
    "hit_rate",
    "multiclass_auroc",
    "multiclass_precision_recall_curve",
    "num_collisions",
    "reciprocal_rank",
    "binary_binned_precision_recall_curve",
    "binary_confusion_matrix",
    "binary_f1_score",
    "binary_normalized_entropy",
    "binary_precision",
    "binary_recall",
    "mean",
    "mean_squared_error",
    "multiclass_accuracy",
    "multiclass_binned_precision_recall_curve",
    "multiclass_confusion_matrix",
    "multiclass_f1_score",
    "multiclass_precision",
    "multiclass_recall",
    "multilabel_accuracy",
    "r2_score",
    "sum",
    "throughput",
    "topk_multilabel_accuracy",
    "weighted_calibration",
]
