"""Stateless functional metrics (reference
``torcheval/metrics/functional/__init__.py:38-68`` — 28 public functions)."""

from torcheval_tpu.metrics.functional.classification import (
    binary_accuracy,
    multiclass_accuracy,
    multilabel_accuracy,
    topk_multilabel_accuracy,
)

__all__ = [
    "binary_accuracy",
    "multiclass_accuracy",
    "multilabel_accuracy",
    "topk_multilabel_accuracy",
]
