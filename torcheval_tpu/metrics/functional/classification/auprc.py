"""AUPRC (average precision) — area under the precision-recall curve.

Not in the reference snapshot (torcheval v0.0.4 has only the PR *curve*;
upstream torcheval added ``binary_auprc``/``multiclass_auprc`` later), but
the BASELINE AUPRC workload and the shared sort+tie-scan core
(``_sort_scan.py``) make it a natural member of the threshold-curve family
here.  Semantics follow the standard step-sum average precision
(``sklearn.metrics.average_precision_score``):

    AP = Σ_groups (R_g − R_{g−1}) · P_g

evaluated at tie-group ends of the descending score sort — shape-stable,
jit-composable, multi-task via a leading dim like ``binary_auroc``.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification._sort_scan import (
    class_hits,
    sorted_tie_cumsums,
)
from torcheval_tpu.metrics.functional.classification.auroc import (
    _binary_auroc_update_input_check,
    _group_end_values,
    _multiclass_auroc_update_input_check,
)
from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _multilabel_precision_recall_curve_update_input_check as _multilabel_auprc_update_input_check,  # noqa: E501  (same shape contract)
)


def binary_auprc(
    input,
    target,
    *,
    num_tasks: int = 1,
) -> jax.Array:
    """Average precision for binary classification; multi-task via a
    ``(num_tasks, n)`` leading dim.  Rows with no positive labels (or no
    samples) yield 0 — sklearn returns NaN with a warning there."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    _binary_auroc_update_input_check(input, target, num_tasks)
    if input.shape[-1] == 0:
        return jnp.zeros(input.shape[:-1])
    return _binary_auprc_compute(input, target)


def _binary_auprc_compute(
    input: jax.Array, target: jax.Array, ustat_route="auto"
) -> jax.Array:
    # Rare-positive fast path: step-sum AP against the packed positive
    # table instead of a row sort (ops/pallas_ustat.py; AP is
    # positive-anchored, so only that side packs).  ustat_route as in
    # _binary_auroc_compute: "auto" decides here, None forces the sort
    # path, a (side, cap) pair reuses a decision made on the same data.
    if input.shape[-1] < 2**24:
        from torcheval_tpu.ops.pallas_ustat import (
            binary_auprc_ustat,
            binary_ustat_route,
        )

        squeeze = input.ndim == 1
        rows = input[None] if squeeze else input
        t_rows = target[None] if squeeze else target
        if ustat_route == "auto":
            ustat_route = binary_ustat_route(rows, t_rows, need_pos=True)
        if ustat_route is not None:
            _, cap = ustat_route
            ap = binary_auprc_ustat(rows, t_rows.astype(jnp.int32), cap=cap)
            return ap[0] if squeeze else ap
    return _binary_auprc_compute_kernel(input, target)


def multiclass_auprc(
    input,
    target,
    *,
    num_classes: int,
    average: Optional[str] = "macro",
    ustat_cap: Optional[int] = None,
) -> jax.Array:
    """One-vs-rest average precision with macro/None averaging.

    Classes absent from ``target`` contribute 0 to the macro mean —
    sklearn yields NaN with a warning for such classes.

    ``ustat_cap`` pins the sort-free rank-histogram formulation's static
    table capacity for composition under a caller's ``jax.jit`` — the
    same contract as ``multiclass_auroc``'s ``ustat_cap`` (see its
    docstring), plus this kernel's ``N < 2^24`` bound."""
    _multiclass_auprc_param_check(num_classes, average)
    input, target = jnp.asarray(input), jnp.asarray(target)
    _multiclass_auroc_update_input_check(input, target, num_classes)
    if input.shape[0] == 0:
        return jnp.zeros(()) if average == "macro" else jnp.zeros(num_classes)
    if ustat_cap is not None:
        from torcheval_tpu.metrics.functional.classification.auroc import (
            _ustat_cap_check,
        )

        if input.shape[0] >= 2**24:
            raise ValueError(
                "the rank-histogram formulation requires N < 2^24; leave "
                "ustat_cap=None for this shape."
            )
        _ustat_cap_check(input, target, num_classes, ustat_cap)
    return _multiclass_auprc_compute(
        input, target, num_classes, average, ustat_cap=ustat_cap
    )


def _multiclass_auprc_compute(
    input: jax.Array,
    target: jax.Array,
    num_classes: int,
    average: Optional[str],
    ustat_cap: Optional[int] = None,
    _interpret: bool = False,
) -> jax.Array:
    # Sort-free rank-histogram fast path (ops/pallas_ustat.py): sparse
    # one-vs-rest positives make step-sum AP a per-entry count against a
    # tiny packed table instead of a (C, N) variadic sort.  Same call-time
    # route as the AUROC fast path, plus the kernel's N < 2^24 bound.
    # A pinned cap (the jit-composition recipe) asserts the data
    # preconditions only; environment guards are re-checked here so
    # pinned code still runs — on the sort path — off-TPU.
    if input.shape[0] < 2**24:
        from torcheval_tpu.ops.pallas_ustat import ustat_route_cap

        if ustat_cap is None:
            ustat_cap = ustat_route_cap(input, target, num_classes)
        else:
            from torcheval_tpu.metrics.functional.classification.auroc import (
                _pinned_cap_env_ok,
            )

            if not _pinned_cap_env_ok(_interpret):
                ustat_cap = None
        if ustat_cap is not None:
            from torcheval_tpu.ops.pallas_ustat import multiclass_auprc_ustat

            return multiclass_auprc_ustat(
                input,
                target,
                num_classes=num_classes,
                average=average,
                cap=ustat_cap,
                interpret=_interpret,
            )
    return _multiclass_auprc_compute_kernel(input, target, num_classes, average)


def multilabel_auprc(
    input,
    target,
    *,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
) -> jax.Array:
    """Per-label average precision over a ``(n, num_labels)`` 0/1 target
    matrix, macro-averaged by default.  Beyond the v0.0.4 snapshot
    (upstream torcheval added ``multilabel_auprc`` later); each label
    column is an independent binary AP through the shared tie-scan core."""
    _multilabel_auprc_param_check(num_labels, average)
    input, target = jnp.asarray(input), jnp.asarray(target)
    if num_labels is None:
        num_labels = input.shape[1] if input.ndim == 2 else None
    _multilabel_auprc_update_input_check(input, target, num_labels)
    if input.shape[0] == 0:
        return jnp.zeros(()) if average == "macro" else jnp.zeros(num_labels)
    return _multilabel_auprc_compute(input, target, average)


@partial(jax.jit, static_argnames=("average",))
def _multilabel_auprc_compute_kernel(
    input: jax.Array, target: jax.Array, average: Optional[str]
) -> jax.Array:
    ap = _auprc_rows(input.T, (target == 1).T)
    return ap.mean() if average == "macro" else ap


def _multilabel_auprc_compute(
    input: jax.Array, target: jax.Array, average: Optional[str]
) -> jax.Array:
    # Label columns are usually sparse — exactly the rare-positive regime
    # of the sort-free AP kernel.  Per-label rows ARE the binary (R, N)
    # case on transposed inputs (one routing implementation, no drift).
    ap = _binary_auprc_compute(input.T, target.T)
    return ap.mean() if average == "macro" else ap


def _multilabel_auprc_param_check(
    num_labels: Optional[int], average: Optional[str]
) -> None:
    average_options = ("macro", "none", None)
    if average not in average_options:
        raise ValueError(
            f"`average` was not in the allowed value of {average_options}, "
            f"got {average}."
        )
    if num_labels is not None and num_labels < 2:
        raise ValueError("`num_labels` has to be at least 2.")


@jax.jit
def _auprc_rows(scores: jax.Array, hits: jax.Array) -> jax.Array:
    """Row-wise AP over ``(R, N)`` scores/hits.

    AP = Σ_groups (tp_g − tp_{g−1})·P_g; since every element of a tie
    group shares the group-end precision, this equals summing each sorted
    hit weighted by its group-end precision — the group-end propagation is
    the shared ``_group_end_values`` used by the AUROC kernel."""
    _, is_last, cum_tp, cum_fp = sorted_tie_cumsums(scores, hits)
    tp_end = _group_end_values(cum_tp, is_last).astype(jnp.float32)
    fp_end = _group_end_values(cum_fp, is_last).astype(jnp.float32)
    precision = tp_end / jnp.maximum(tp_end + fp_end, 1.0)
    sorted_hits = jnp.diff(cum_tp, axis=-1, prepend=0).astype(jnp.float32)
    num_pos = cum_tp[..., -1].astype(jnp.float32)
    ap = (sorted_hits * precision).sum(axis=-1) / jnp.maximum(num_pos, 1.0)
    return jnp.where(num_pos == 0, 0.0, ap)


@jax.jit
def _binary_auprc_compute_kernel(input: jax.Array, target: jax.Array) -> jax.Array:
    squeeze = input.ndim == 1
    if squeeze:
        input, target = input[None], target[None]
    ap = _auprc_rows(input, (target == 1))
    return ap[0] if squeeze else ap


@partial(jax.jit, static_argnames=("num_classes", "average"))
def _multiclass_auprc_compute_kernel(
    input: jax.Array,
    target: jax.Array,
    num_classes: int,
    average: Optional[str],
) -> jax.Array:
    ap = _auprc_rows(input.T, class_hits(target, num_classes))
    return ap.mean() if average == "macro" else ap


def _multiclass_auprc_param_check(
    num_classes: int, average: Optional[str]
) -> None:
    average_options = ("macro", "none", None)
    if average not in average_options:
        raise ValueError(
            f"`average` was not in the allowed value of {average_options}, "
            f"got {average}."
        )
    if num_classes < 2:
        raise ValueError("`num_classes` has to be at least 2.")
