"""Binary normalized entropy — parity with reference
``torcheval/metrics/functional/classification/binary_normalized_entropy.py``
(152 LoC).

NE = (weighted BCE of predictions) / (entropy of the base positive rate),
eps-clamped (reference ``binary_normalized_entropy.py:86-117``), with
multi-task support via a leading task dimension (``:120-143``).

Precision divergence (documented): the reference accumulates in float64; TPU
has no native f64, so accumulators here are float32 unless ``jax_enable_x64``
is set (in which case float64 is honored).  For the eval-scale workloads in
the reference tests this matches to ≥6 significant digits."""

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional._host_checks import (
    all_concrete,
    bounds,
    value_checks_enabled,
)


def _accum_dtype() -> jnp.dtype:
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def binary_normalized_entropy(
    input,
    target,
    *,
    weight=None,
    num_tasks: int = 1,
    from_logits: bool = False,
) -> jax.Array:
    """Normalized cross entropy vs. the always-predict-base-rate baseline
    (reference ``binary_normalized_entropy.py:13-72``)."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    if weight is not None:
        weight = jnp.asarray(weight)
    cross_entropy, num_positive, num_examples = _binary_normalized_entropy_update(
        input, target, from_logits, num_tasks, weight
    )
    baseline_entropy = _baseline_update(num_positive, num_examples)
    return (cross_entropy / num_examples) / baseline_entropy


def _binary_normalized_entropy_update(
    input: jax.Array,
    target: jax.Array,
    from_logits: bool,
    num_tasks: int,
    weight: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    _ne_input_check(input, target, from_logits, num_tasks, weight)
    if weight is None:
        return _ne_update_kernel_unweighted(input, target, from_logits)
    return _ne_update_kernel(input, target, weight, from_logits)


@partial(jax.jit, static_argnames=("from_logits",))
def _ne_update_kernel_unweighted(
    input: jax.Array, target: jax.Array, from_logits: bool
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    return _ne_update_kernel(input, target, jnp.ones_like(input), from_logits)


@partial(jax.jit, static_argnames=("from_logits",))
def _ne_update_kernel(
    input: jax.Array,
    target: jax.Array,
    weight: jax.Array,
    from_logits: bool,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    dtype = _accum_dtype()
    if from_logits:
        # log-sum-exp-stable BCE-with-logits: max(x,0) - x*y + log1p(exp(-|x|))
        ce = (
            jnp.maximum(input, 0)
            - input * target
            + jnp.log1p(jnp.exp(-jnp.abs(input)))
        )
    else:
        eps = 1e-12
        clamped = jnp.clip(input, eps, 1 - eps)
        ce = -(target * jnp.log(clamped) + (1 - target) * jnp.log1p(-clamped))
    cross_entropy = (ce * weight).sum(axis=-1).astype(dtype)
    num_examples = jnp.sum(weight, axis=-1).astype(dtype)
    num_positive = jnp.sum(weight * target, axis=-1).astype(dtype)
    return cross_entropy, num_positive, num_examples


@jax.jit
def _baseline_update(num_positive: jax.Array, num_examples: jax.Array) -> jax.Array:
    """Entropy of always predicting the base positive rate, eps-clamped
    (reference ``binary_normalized_entropy.py:~95-110``)."""
    eps = float(jnp.finfo(_accum_dtype()).eps)
    base_pos_rate = jnp.clip(num_positive / num_examples, eps, 1 - eps)
    return -base_pos_rate * jnp.log(base_pos_rate) - (1 - base_pos_rate) * jnp.log1p(
        -base_pos_rate
    )


def _ne_input_check(
    input: jax.Array,
    target: jax.Array,
    from_logits: bool,
    num_tasks: int,
    weight: Optional[jax.Array] = None,
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            f"`input` shape ({input.shape}) is different from `target` shape "
            f"({target.shape})"
        )
    if weight is not None and input.shape != weight.shape:
        raise ValueError(
            f"`weight` shape ({weight.shape}) is different from `input` shape "
            f"({input.shape})"
        )
    if num_tasks == 1:
        if input.ndim > 1:
            raise ValueError(
                "`num_tasks = 1`, `input` is expected to be one-dimensional "
                f"tensor, but got shape ({input.shape})."
            )
    elif input.ndim == 1 or input.shape[0] != num_tasks:
        raise ValueError(
            f"`num_tasks = {num_tasks}`, `input`'s shape is expected to be "
            f"({num_tasks}, num_samples), but got shape ({input.shape})."
        )
    if (
        not from_logits
        and input.size
        and all_concrete(input)
        and value_checks_enabled()
    ):
        lo, hi = bounds(input)
        input_min, input_max = float(lo), float(hi)
        if input_max > 1.0 or input_min < 0.0:
            raise ValueError(
                f"`from_logits`={from_logits}, `input` should be probability "
                f"in range [0., 1.], but got `input` ranging from {input_min} "
                f"to {input_max}. Please set `from_logits = True` or convert "
                "`input` into valid probability value."
            )
