"""AUROC — parity with reference
``torcheval/metrics/functional/classification/auroc.py`` (253 LoC).

The reference's exact algorithm: sort descending, build a last-of-tie-group
mask, cumsum TP/FP, compact the masked values to the array tail with
``masked_scatter_`` (leading zeros act as the (0, 0) ROC anchor), trapezoid,
normalize by #P·#N, degenerate → 0.5 (reference ``auroc.py:106-142``).

TPU-first re-derivation (shape-stable, no data-dependent compaction —
SURVEY §7 hard part 3): replace each position's cumsum by the value at the
END of its tie group via a reverse ``cummin`` over ``where(is_last, cum,
+sentinel)`` (cumsum is nondecreasing, so the nearest flagged position to
the right carries the group-end value), then prepend an explicit (0, 0)
anchor and trapezoid — duplicate consecutive points add zero width, so the
result is exactly the reference's.  Everything is one jit-compiled XLA
program: sort + scans + dot.

The reference's opt-in ``use_fbgemm`` CUDA kernel becomes ``use_fused``
(``torcheval_tpu.ops.fused_auc``) — like fbgemm, an approximation that
skips tie masking (reference ``auroc.py:34-39,145-164``).
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification._sort_scan import (
    class_hits,
    sorted_tie_cumsums,
)
from torcheval_tpu.ops.fused_auc import fused_auc


def binary_auroc(
    input,
    target,
    *,
    num_tasks: int = 1,
    use_fused: Optional[bool] = False,
) -> jax.Array:
    """Area under the ROC curve for binary classification, multi-task via a
    leading dim (reference ``auroc.py:17-62``).  ``use_fused`` opts into the
    approximate fused kernel (the fbgemm analog)."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    _binary_auroc_update_input_check(input, target, num_tasks)
    return _binary_auroc_compute(input, target, use_fused)


def multiclass_auroc(
    input,
    target,
    *,
    num_classes: int,
    average: Optional[str] = "macro",
    ustat_cap: Optional[int] = None,
) -> jax.Array:
    """One-vs-rest AUROC per class, macro-averaged by default
    (reference ``auroc.py:65-103``).

    ``ustat_cap`` pins the sort-free rank-sum formulation's static table
    capacity (≥ the largest per-class count, a multiple of 16).  Leave it
    ``None`` for eager calls — the route self-decides from the data.  Set
    it when composing this function under YOUR OWN ``jax.jit``: the
    call-time route guard cannot inspect tracers, so an un-pinned jitted
    call always takes the sort path; a pinned cap keeps the routed kernel
    (measured 4.4× on the (2^17, 1000) headline) reachable under jit.
    Decide it eagerly with :func:`torcheval_tpu.ops.pallas_ustat.
    ustat_route_cap` on a representative batch.  Results match the sort
    path to 1 ULP per class (both are exact integer-count formulations;
    only the final float division rounds differently).  A pinned cap
    asserts the kernel's score domain — zero or 2^-100 ≤ |score| < 3e38
    — which eager calls validate; under ``skip_value_checks`` (or inside
    jit, where values are invisible) that domain is the caller's
    contract."""
    _multiclass_auroc_param_check(num_classes, average)
    input, target = jnp.asarray(input), jnp.asarray(target)
    _multiclass_auroc_update_input_check(input, target, num_classes)
    if ustat_cap is not None:
        _ustat_cap_check(input, target, num_classes, ustat_cap)
    return _multiclass_auroc_compute(
        input, target, num_classes, average, ustat_cap=ustat_cap
    )


def _pinned_cap_env_ok(_interpret: bool) -> bool:
    """Environment guard shared by every pinned-``ustat_cap`` entry point
    (AUROC and AUPRC): a pinned cap asserts the DATA preconditions, not
    the environment — backend and kill-switches are host-level facts,
    re-checked per call so pinned code still runs (on the sort path) on
    CPU or with Pallas disabled.  ``_interpret``, a test hook, runs the
    pinned kernel in Pallas interpret mode instead, so the route is
    exercisable off-TPU."""
    from torcheval_tpu.ops._flags import pallas_disabled, ustat_disabled

    if _interpret:
        return True
    return not (
        pallas_disabled()
        or ustat_disabled()
        or jax.default_backend() != "tpu"
    )


def _ustat_cap_check(
    input: jax.Array, target: jax.Array, num_classes: int, cap: int
) -> None:
    """Validate a user-pinned rank-sum table capacity.  An undersized cap
    would silently DROP the overflowing class's largest scores (the pack's
    out-of-bounds scatter indices are discarded), so eager calls verify it
    against the measured per-class maximum — one fused round trip, skipped
    under tracing or ``skip_value_checks`` (then the documented
    preconditions are the caller's contract)."""
    from torcheval_tpu.metrics.functional._host_checks import (
        all_concrete,
        value_checks_enabled,
    )
    from torcheval_tpu.ops.pallas_ustat import _BIG, _MAX_CAP, _route_stats

    if cap % 16 != 0 or cap < 16:
        raise ValueError(f"ustat_cap must be a positive multiple of 16, got {cap}.")
    if cap > _MAX_CAP:
        raise ValueError(
            f"ustat_cap={cap} exceeds the hardware-verified Mosaic operand "
            f"envelope (cap ≤ {_MAX_CAP}); leave ustat_cap=None for this "
            "shape."
        )
    if cap * input.shape[0] >= 2**29:
        raise ValueError(
            f"ustat_cap·N = {cap * input.shape[0]} exceeds the exact-int32 "
            "bound 2^29; leave ustat_cap=None for this shape."
        )
    if (
        not value_checks_enabled()
        or not all_concrete(input, target)
        or input.size == 0  # N=0 takes the degenerate path downstream
    ):
        return
    import numpy as np

    from torcheval_tpu.ops.pallas_ustat import _MIN_SPLIT

    lo, hi, max_count, min_nz = (
        float(x) for x in np.asarray(_route_stats(input, target))
    )
    if max_count > cap:
        raise ValueError(
            f"ustat_cap={cap} but one class has {int(max_count)} samples; "
            "raise the cap (or leave it None to self-decide)."
        )
    if not (-_BIG < lo and hi < _BIG) or min_nz < _MIN_SPLIT:
        raise ValueError(
            "the rank-sum formulation requires nonzero scores with "
            "2^-100 <= |score| < 3e38 (its bf16-split gather and pad "
            "sentinel); leave ustat_cap=None for such inputs."
        )


def _group_end_values(values: jax.Array, is_last: jax.Array) -> jax.Array:
    """Replace each position by ``values`` at the end of its tie group.

    ``values`` must be nondecreasing along the last axis; ``is_last`` flags
    the last element of each tie group.  Shape-stable (reverse cummin over a
    sentinel-masked array)."""
    sentinel = jnp.asarray(values.shape[-1] + 1, dtype=values.dtype)
    masked = jnp.where(is_last, values, sentinel)
    return jax.lax.cummin(masked, axis=values.ndim - 1, reverse=True)


@jax.jit
def _binary_auroc_compute_kernel(input: jax.Array, target: jax.Array) -> jax.Array:
    squeeze = input.ndim == 1
    if squeeze:
        input, target = input[None], target[None]
    _, is_last, cum_tp, cum_fp = sorted_tie_cumsums(input, target)
    tp_end = _group_end_values(cum_tp, is_last)
    fp_end = _group_end_values(cum_fp, is_last)
    zero = jnp.zeros((*cum_tp.shape[:-1], 1), dtype=cum_tp.dtype)
    roc_tp = jnp.concatenate([zero, tp_end], axis=-1)
    roc_fp = jnp.concatenate([zero, fp_end], axis=-1)
    factor = cum_tp[:, -1].astype(jnp.float32) * cum_fp[:, -1].astype(jnp.float32)
    area = jnp.trapezoid(roc_tp.astype(jnp.float32), roc_fp.astype(jnp.float32), axis=-1)
    auroc = jnp.where(factor == 0, 0.5, area / factor)
    return auroc[0] if squeeze else auroc


def _binary_auroc_compute(
    input: jax.Array,
    target: jax.Array,
    use_fused: Optional[bool] = False,
    ustat_route="auto",
) -> jax.Array:
    if input.shape[-1] == 0:
        # Degenerate (no samples) → 0.5, the same convention the kernel
        # applies when a task has no positives or no negatives.
        return jnp.full(input.shape[:-1], 0.5, dtype=jnp.float32)
    if use_fused:
        return fused_auc(input, target)
    # Sort-free rank-sum fast path for rare-class rows (ops/pallas_ustat):
    # when one class's per-row count is tiny, exact AUROC is a pair count
    # against the packed rare-side table instead of a row sort.  Pass
    # ustat_route to reuse a decision made on the same data (the sharded
    # gather-exact wrappers do, to stay bitwise-consistent); "auto"
    # decides here, None forces the sort path.
    from torcheval_tpu.ops.pallas_ustat import (
        binary_auroc_ustat,
        binary_ustat_route,
    )

    squeeze = input.ndim == 1
    rows = input[None] if squeeze else input
    t_rows = target[None] if squeeze else target
    if ustat_route == "auto":
        ustat_route = binary_ustat_route(rows, t_rows)
    if ustat_route is not None:
        side, cap = ustat_route
        auc = binary_auroc_ustat(
            rows, t_rows.astype(jnp.int32), cap=cap, table_side=side
        )
        return auc[0] if squeeze else auc
    if _use_pallas(input.shape[-1]):
        from torcheval_tpu.ops.pallas_auc import pallas_binary_auroc

        return pallas_binary_auroc(input, target)
    return _binary_auroc_compute_kernel(input, target)


def _use_pallas(num_samples: int) -> bool:
    """Route exact AUROC through the fused Pallas scan on TPU (identical
    math, single HBM pass; see ``torcheval_tpu/ops/pallas_auc.py``).  Set
    ``TORCHEVAL_TPU_DISABLE_PALLAS=1`` to force the pure-XLA path.

    The kernel carries counts in int32 (exact to 2^31 samples per row,
    with Kahan-compensated f32 area accumulation — the same precision
    class as the XLA trapezoid), so the headline path needs no fallback;
    only the int32 ceiling itself routes to the XLA path."""
    from torcheval_tpu.ops._flags import pallas_disabled

    if pallas_disabled():
        return False
    if num_samples >= 2**31:
        return False
    from torcheval_tpu.ops.pallas_auc import has_pallas

    return has_pallas()


def _multiclass_auroc_compute(
    input: jax.Array,
    target: jax.Array,
    num_classes: int,
    average: Optional[str] = "macro",
    ustat_cap: Optional[int] = None,
    _interpret: bool = False,
) -> jax.Array:
    if input.shape[0] == 0:
        # Degenerate (no samples) → 0.5 per class, matching the kernel's
        # no-positives/no-negatives convention.
        degenerate = jnp.full(num_classes, 0.5, dtype=jnp.float32)
        return degenerate.mean() if average == "macro" else degenerate
    # Sort-free rank-sum fast path: one-vs-rest positives are sparse, so
    # exact AUROC is a pair count against a tiny per-class table instead
    # of a (C, N) variadic sort (ops/pallas_ustat.py) — a large win in the
    # small-cap region, e.g. the (2^17, 1000) device-step headline where
    # per-class tables are ~256 entries.  Route selection is call-time and
    # eager (bigger caps keep the sort path — see ustat_route_cap's win
    # region); pass ustat_cap to reuse a decision made on the same data
    # (the sharded gather-exact path does, to stay bitwise-consistent).
    if ustat_cap is None:
        from torcheval_tpu.ops.pallas_ustat import ustat_route_cap

        ustat_cap = ustat_route_cap(input, target, num_classes)
    elif not _pinned_cap_env_ok(_interpret):
        ustat_cap = None
    if ustat_cap is not None:
        from torcheval_tpu.ops.pallas_ustat import multiclass_auroc_ustat

        return multiclass_auroc_ustat(
            input,
            target,
            num_classes=num_classes,
            average=average,
            cap=ustat_cap,
            interpret=_interpret,
        )
    if _use_pallas(input.shape[0]):
        return _multiclass_auroc_pallas_kernel(input, target, num_classes, average)
    return _multiclass_auroc_compute_kernel(input, target, num_classes, average)


@partial(jax.jit, static_argnames=("num_classes", "average"))
def _multiclass_auroc_pallas_kernel(
    input: jax.Array,
    target: jax.Array,
    num_classes: int,
    average: Optional[str],
) -> jax.Array:
    """One-vs-rest AUROC through the fused Pallas scan — one (C, N)
    multi-task call of the shared sort + kernel path."""
    from torcheval_tpu.ops.pallas_auc import pallas_binary_auroc

    aurocs = pallas_binary_auroc(input.T, class_hits(target, num_classes))
    return aurocs.mean() if average == "macro" else aurocs


@partial(jax.jit, static_argnames=("num_classes", "average"))
def _multiclass_auroc_compute_kernel(
    input: jax.Array,
    target: jax.Array,
    num_classes: int,
    average: Optional[str] = "macro",
) -> jax.Array:
    # One-vs-rest: per-class column sort (reference ``auroc.py:188-217``)
    _, is_last, cum_tp, cum_fp = sorted_tie_cumsums(
        input.T, class_hits(target, num_classes)
    )
    tp_end = _group_end_values(cum_tp, is_last)
    fp_end = _group_end_values(cum_fp, is_last)
    zero = jnp.zeros((num_classes, 1), dtype=cum_tp.dtype)
    roc_tp = jnp.concatenate([zero, tp_end], axis=1).astype(jnp.float32)
    roc_fp = jnp.concatenate([zero, fp_end], axis=1).astype(jnp.float32)
    factor = cum_tp[:, -1].astype(jnp.float32) * cum_fp[:, -1].astype(jnp.float32)
    auroc = jnp.where(factor == 0, 0.5, jnp.trapezoid(roc_tp, roc_fp, axis=1) / factor)
    if average == "macro":
        return auroc.mean()
    return auroc


def _binary_auroc_update_input_check(
    input: jax.Array,
    target: jax.Array,
    num_tasks: int,
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same shape, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if num_tasks == 1:
        if input.ndim > 1:
            raise ValueError(
                "`num_tasks = 1`, `input` is expected to be one-dimensional "
                f"tensor, but got shape ({input.shape})."
            )
    elif input.ndim == 1 or input.shape[0] != num_tasks:
        raise ValueError(
            f"`num_tasks = {num_tasks}`, `input`'s shape is expected to be "
            f"({num_tasks}, num_samples), but got shape ({input.shape})."
        )


def _multiclass_auroc_param_check(
    num_classes: int,
    average: Optional[str],
) -> None:
    average_options = ("macro", "none", None)
    if average not in average_options:
        raise ValueError(
            f"`average` was not in the allowed value of {average_options}, got {average}."
        )
    if num_classes < 2:
        raise ValueError("`num_classes` has to be at least 2.")


def _multiclass_auroc_update_input_check(
    input: jax.Array,
    target: jax.Array,
    num_classes: int,
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if not (input.ndim == 2 and input.shape[1] == num_classes):
        raise ValueError(
            "input should have shape of (num_sample, num_classes), "
            f"got {input.shape} and num_classes={num_classes}."
        )
