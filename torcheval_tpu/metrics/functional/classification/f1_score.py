"""F1 score — parity with reference
``torcheval/metrics/functional/classification/f1_score.py`` (271 LoC).

Sufficient statistics: ``num_tp`` / ``num_label`` / ``num_prediction``
(scalars for micro, per-class scatter-add vectors otherwise; reference jit
kernel at ``f1_score.py:164-230``).  Macro/weighted masking is computed
shape-stably (masked arithmetic instead of boolean indexing)."""

import logging
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.functional._host_checks import all_concrete
from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
    _class_counts,
    _counts_route,
)
from torcheval_tpu.metrics.functional.classification.precision import (
    _check_index_ranges,
)

_logger = logging.getLogger(__name__)


def binary_f1_score(input, target, *, threshold: float = 0.5) -> jax.Array:
    """Binary F1 = 2·TP / (#labels + #predictions) after thresholding
    (reference ``f1_score.py:15-48,118-132``)."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    num_tp, num_label, num_prediction = _binary_f1_score_update(
        input, target, threshold
    )
    return _f1_score_compute(num_tp, num_label, num_prediction, "micro")


def multiclass_f1_score(
    input,
    target,
    *,
    num_classes: Optional[int] = None,
    average: Optional[str] = "micro",
) -> jax.Array:
    """Multiclass F1 with micro/macro/weighted/None averaging
    (reference ``f1_score.py:51-115``)."""
    _f1_score_param_check(num_classes, average)
    input, target = jnp.asarray(input), jnp.asarray(target)
    num_tp, num_label, num_prediction = _f1_score_update(
        input, target, num_classes, average
    )
    return _f1_score_compute(num_tp, num_label, num_prediction, average)


def _f1_score_update(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    _f1_score_validate(input, target, num_classes, average)
    return _f1_score_update_kernel(
        input,
        target,
        num_classes,
        average,
        _counts_route(input, num_classes, average),
    )


def _f1_score_validate(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
) -> None:
    """Host-side update validation shared by the functional and class paths."""
    _f1_score_update_input_check(input, target, num_classes)
    if average != "micro":
        pairs = [(target, "target")]
        if input.ndim == 1:
            pairs.append((input, "input"))
        _check_index_ranges(pairs, num_classes)


@partial(jax.jit, static_argnames=("num_classes", "average", "route"))
def _f1_score_update_kernel(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
    route: str = "scatter",
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    if input.ndim == 2:
        input = jnp.argmax(input, axis=1)
    if average == "micro":
        if mask is None:
            num_tp = (input == target).sum()
            num_label = jnp.asarray(target.shape[0])
        else:
            m = mask.astype(jnp.int32)
            num_tp = ((input == target).astype(jnp.int32) * m).sum()
            num_label = m.sum()
        return num_tp, num_label, num_label
    # ONE routed (C, C)-slab accumulation instead of the reference's
    # three label scatters (each serializes on TPU) — see _class_counts.
    return _class_counts(input, target, num_classes, route, mask=mask)


def _f1_score_compute(
    num_tp: jax.Array,
    num_label: jax.Array,
    num_prediction: jax.Array,
    average: Optional[str],
) -> jax.Array:
    # numpy, not jnp: under an ambient trace even ops on concrete arrays
    # are staged, and a staged bool() would crash the trace.
    if (
        num_label.ndim
        and all_concrete(num_label)
        and bool(np.any(np.asarray(num_label) == 0))
    ):
        _logger.warning(
            "Warning: Some classes do not exist in the target. F1 scores for "
            "these classes will be cast to zeros."
        )
    return _f1_score_compute_kernel(num_tp, num_label, num_prediction, average)


@partial(jax.jit, static_argnames=("average",))
def _f1_score_compute_kernel(
    num_tp: jax.Array,
    num_label: jax.Array,
    num_prediction: jax.Array,
    average: Optional[str],
) -> jax.Array:
    precision = num_tp / num_prediction
    recall = num_tp / num_label
    f1 = jnp.nan_to_num(2 * precision * recall / (precision + recall))
    if average == "micro" or average is None:
        return f1
    mask = (num_label != 0) | (num_prediction != 0)
    if average == "macro":
        return jnp.sum(jnp.where(mask, f1, 0.0)) / jnp.sum(mask)
    # weighted
    return jnp.sum(f1 * num_label) / jnp.sum(num_label)


def _binary_f1_score_update(
    input: jax.Array, target: jax.Array, threshold: float
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    _binary_f1_score_update_input_check(input, target)
    return _binary_f1_score_update_kernel(input, target, threshold)


@partial(jax.jit, static_argnames=("threshold",))
def _binary_f1_score_update_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: float,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    pred = jnp.where(input < threshold, 0, 1)
    if mask is not None:
        m = mask.astype(target.dtype)
        target = target * m
        pred = pred * mask.astype(pred.dtype)
    num_tp = jnp.sum(pred * target)
    num_label = jnp.sum(target)
    num_prediction = jnp.sum(pred)
    return num_tp, num_label, num_prediction


def _f1_score_param_check(
    num_classes: Optional[int], average: Optional[str]
) -> None:
    average_options = ("micro", "macro", "weighted", None)
    if average not in average_options:
        raise ValueError(
            f"`average` was not in the allowed value of {average_options}, got {average}."
        )
    if average != "micro" and (num_classes is None or num_classes <= 0):
        raise ValueError(
            f"num_classes should be a positive number when average={average}, "
            f"got num_classes={num_classes}."
        )


def _f1_score_update_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int]
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if not input.ndim == 1 and not (
        input.ndim == 2 and (num_classes is None or input.shape[1] == num_classes)
    ):
        raise ValueError(
            "input should have shape of (num_sample,) or (num_sample, num_classes), "
            f"got {input.shape}."
        )


def _binary_f1_score_update_input_check(input: jax.Array, target: jax.Array) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same dimensions, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
