"""Binned precision-recall curves — parity with reference
``torcheval/metrics/functional/classification/binned_precision_recall_curve.py``
(242 LoC).

Fixed thresholds make the sufficient statistics fixed-shape per-bin TP/FP/FN
counters — the TPU-friendly formulation of a PR curve (mergeable by addition,
syncable by ``psum``; no sample buffers).  Updates ride the shared
binned-counts core (``binned_auc._binned_counts_rows``: one variadic sort +
``searchsorted`` per row, or the Pallas MXU histogram kernel on TPU)
instead of the reference's O(N·T·C) boolean broadcast-compare
(reference ``binned_precision_recall_curve.py:184-197``)."""

from functools import lru_cache, partial
from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.functional._host_checks import all_concrete
from torcheval_tpu.metrics.functional.classification.precision import (
    _check_index_range,
)


def binary_binned_precision_recall_curve(
    input,
    target,
    *,
    threshold: Union[int, List[float], "jax.Array"] = 100,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(precision, recall, thresholds) at fixed thresholds
    (reference ``binned_precision_recall_curve.py:17-110``)."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    threshold = _create_threshold_tensor(threshold)
    _binned_precision_recall_curve_param_check(threshold)
    num_tp, num_fp, num_fn = _binary_binned_precision_recall_curve_update(
        input, target, threshold
    )
    return _binary_binned_precision_recall_curve_compute(
        num_tp, num_fp, num_fn, threshold
    )


def multiclass_binned_precision_recall_curve(
    input,
    target,
    num_classes: Optional[int] = None,
    threshold: Union[int, List[float], "jax.Array"] = 100,
) -> Tuple[List[jax.Array], List[jax.Array], jax.Array]:
    """Per-class binned PR curves over the shared binned-counts core
    (reference ``binned_precision_recall_curve.py:113-221``)."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    threshold = _create_threshold_tensor(threshold)
    _binned_precision_recall_curve_param_check(threshold)
    if num_classes is None and input.ndim == 2:
        num_classes = input.shape[1]
    num_tp, num_fp, num_fn = _multiclass_binned_precision_recall_curve_update(
        input, target, num_classes, threshold
    )
    return _multiclass_binned_precision_recall_curve_compute(
        num_tp, num_fp, num_fn, num_classes, threshold
    )


def _binary_binned_precision_recall_curve_update(
    input: jax.Array,
    target: jax.Array,
    threshold: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    _binary_binned_update_input_check(input, target)
    return _binary_binned_update_kernel(input, target, threshold)


def _binary_binned_update_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: jax.Array,
    route: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    # Shared binned-counts core (broadcast-compare / Pallas MXU histogram
    # / sort, chosen by measured regime — see binned_auc._select_binned
    # _route).  The route is picked here at call time and baked into the
    # jit as a static arg, so the kill-switch env var stays call-time.
    # Lazy import: binned_auc imports this module's param-check helpers.
    from torcheval_tpu.metrics.functional.classification.binned_auc import (
        _select_binned_route,
    )

    if route is None:
        route = _select_binned_route(1, input.shape[0], threshold)
    return _binary_binned_update_jit(input, target, threshold, route)


@partial(jax.jit, static_argnames=("route",))
def _binary_binned_update_jit(
    input: jax.Array, target: jax.Array, threshold: jax.Array, route: str
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    from torcheval_tpu.metrics.functional.classification.binned_auc import (
        _binned_counts_rows,
    )

    num_tp, num_fp, num_pos, _ = _binned_counts_rows(
        input[None], (target == 1)[None], threshold, route=route
    )
    return num_tp[0], num_fp[0], num_pos[0] - num_tp[0]


@jax.jit
def _binary_binned_precision_recall_curve_compute(
    num_tp: jax.Array,
    num_fp: jax.Array,
    num_fn: jax.Array,
    threshold: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    # Precision defaults to 1.0 where there are no positive predictions;
    # a final (1.0, 0.0) sentinel anchors the curve on the y-axis
    # (reference ``binned_precision_recall_curve.py:81-110``).
    precision = jnp.nan_to_num(num_tp / (num_tp + num_fp), nan=1.0)
    recall = num_tp / (num_tp + num_fn)
    precision = jnp.concatenate([precision, jnp.ones(1)], axis=0)
    recall = jnp.concatenate([recall, jnp.zeros(1)], axis=0)
    return precision, recall, threshold


def _multiclass_binned_validate(
    input: jax.Array, target: jax.Array, num_classes: Optional[int]
) -> None:
    """Host-side update validation shared by the functional and class paths."""
    _multiclass_binned_update_input_check(input, target, num_classes)
    # OOB targets must raise — jax.nn.one_hot silently yields an all-zero
    # row where torch F.one_hot errors.
    _check_index_range(target, num_classes, "target")


def _multiclass_binned_precision_recall_curve_update(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    threshold: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    _multiclass_binned_validate(input, target, num_classes)
    return _multiclass_binned_update_kernel(input, target, threshold, num_classes)


def _multiclass_binned_update_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: jax.Array,
    num_classes: int,
    route: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    from torcheval_tpu.metrics.functional.classification.binned_auc import (
        _select_binned_route,
    )

    if route is None:
        route = _select_binned_route(num_classes, input.shape[0], threshold)
    return _multiclass_binned_update_jit(
        input, target, threshold, num_classes, route
    )


@partial(jax.jit, static_argnames=("num_classes", "route"))
def _multiclass_binned_update_jit(
    input: jax.Array,
    target: jax.Array,
    threshold: jax.Array,
    num_classes: int,
    route: str,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    # One thin epilogue over the SAME one-vs-rest counts jit the binned
    # AUC family uses — single source for the counts plumbing.
    from torcheval_tpu.metrics.functional.classification.binned_auc import (
        _multiclass_binned_counts_jit,
    )

    num_tp_c, num_fp_c, num_pos_c, _ = _multiclass_binned_counts_jit(
        input, target, threshold, num_classes, route
    )
    num_tp = num_tp_c.T  # (T, C) — the reference's state layout
    return num_tp, num_fp_c.T, num_pos_c[None, :] - num_tp


def _multiclass_binned_precision_recall_curve_compute(
    num_tp: jax.Array,
    num_fp: jax.Array,
    num_fn: jax.Array,
    num_classes: Optional[int],
    threshold: jax.Array,
) -> Tuple[List[jax.Array], List[jax.Array], jax.Array]:
    precision, recall = _multiclass_binned_compute_kernel(num_tp, num_fp, num_fn)
    return list(precision.T), list(recall.T), threshold


@jax.jit
def _multiclass_binned_compute_kernel(
    num_tp: jax.Array, num_fp: jax.Array, num_fn: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    num_classes = num_tp.shape[1]
    precision = jnp.nan_to_num(num_tp / (num_tp + num_fp), nan=1.0)
    recall = num_tp / (num_tp + num_fn)
    precision = jnp.concatenate([precision, jnp.ones((1, num_classes))], axis=0)
    recall = jnp.concatenate([recall, jnp.zeros((1, num_classes))], axis=0)
    return precision, recall


def _create_threshold_tensor(
    threshold: Union[int, List[float], "jax.Array"],
) -> jax.Array:
    """int → linspace(0, 1, n); list/array pass through
    (reference ``binned_precision_recall_curve.py:224-232``).  The
    linspace grids are cached per count: repeated eager calls then hand
    the SAME buffer to the kernels, whose per-buffer checks (e.g.
    ``pallas_binned._split_safe_thresholds``) stay memoized instead of
    re-fetching the grid every update."""
    if isinstance(threshold, int):
        return _linspace_grid(threshold)
    return jnp.asarray(threshold)


def _linspace_grid(count: int) -> jax.Array:
    # The x64 flag joins the cache key: a cached jax.Array would
    # otherwise freeze the dtype of the first call (stale under a later
    # jax_enable_x64 toggle).  Keeping the cache ON the device array (not
    # a host grid) matters — jnp.asarray re-transfers eagerly on every
    # call, and this grid is fetched per update; and the values must stay
    # jnp.linspace's exact f32 images (a host np.linspace computes in f64
    # and rounds differently by 1 ulp on ~1/8 of the entries).
    return _linspace_grid_cached(count, bool(jax.config.jax_enable_x64))


@lru_cache(maxsize=64)
def _linspace_grid_cached(count: int, _x64: bool) -> jax.Array:
    return jnp.linspace(0, 1.0, count)


def _binned_precision_recall_curve_param_check(threshold: jax.Array) -> None:
    """Thresholds must be sorted and within [0, 1]
    (reference ``binned_precision_recall_curve.py:235-242``)."""
    if not all_concrete(threshold):
        return  # tracing: data-dependent checks cannot run
    # Constructor-time check: pure numpy so it also works on concrete
    # arrays under an ambient trace (one host fetch, no dispatch at all).
    t = np.asarray(threshold)
    if bool(np.any(np.diff(t) < 0.0)):
        raise ValueError("The `threshold` should be a sorted array.")
    if bool(np.any(t < 0.0)) or bool(np.any(t > 1.0)):
        raise ValueError("The values in `threshold` should be in the range of [0, 1].")


def _binary_binned_update_input_check(input: jax.Array, target: jax.Array) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same shape, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if input.ndim != 1:
        raise ValueError(
            f"input should be a one-dimensional tensor, got shape {input.shape}."
        )


def _multiclass_binned_update_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int]
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if not (input.ndim == 2 and (num_classes is None or input.shape[1] == num_classes)):
        raise ValueError(
            "input should have shape of (num_sample, num_classes), "
            f"got {input.shape}."
        )
