"""Binned AUROC / AUPRC — fixed-threshold areas under ROC and PR curves.

Beyond the v0.0.4 snapshot (upstream torcheval added the binned AUC
families later).  Same counter-state design as the binned PR curves
(reference ``binned_precision_recall_curve.py``): per-threshold TP/FP
counts are the sufficient statistics — fully fixed-shape, mergeable by
addition, syncable by ``psum`` — so the unbounded sample buffers of the
exact AUROC/AUPRC metrics are traded for an O(T) state.

The shared update stage ``_binned_counts_rows`` dispatches between three
formulations returning bit-identical int32 counts, chosen by measured
regime (v5e device-loop clocks, BASELINE.md): a fused VPU
broadcast-compare for small work products (R·N·T ≤ 2^32; 1.24 ms at
4M×200 — 52× the sort), the Pallas MXU one-hot histogram kernel for
large ones (``ops/pallas_binned.py``; 6.1 ms at 4M×10k — 10.9× the
sort), and a scatter-free sort + ``searchsorted`` fallback (CPU /
kill-switch / out-of-bounds; itself measured 4.3-4.7× over scatter-add,
which serializes on TPU).
"""

from functools import partial
from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from torcheval_tpu.metrics.functional.classification._sort_scan import class_hits
from torcheval_tpu.metrics.functional.classification.auroc import (
    _binary_auroc_update_input_check,
    _multiclass_auroc_update_input_check,
)
from torcheval_tpu.metrics.functional.classification.binned_precision_recall_curve import (
    _binned_precision_recall_curve_param_check,
    _create_threshold_tensor,
    _multiclass_binned_compute_kernel,
)
from torcheval_tpu.metrics.functional.classification.precision import (
    _check_index_range,
)
from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _multilabel_precision_recall_curve_update_input_check,
)


def binary_binned_auroc(
    input,
    target,
    *,
    num_tasks: int = 1,
    threshold: Union[int, List[float], "jax.Array"] = 200,
) -> Tuple[jax.Array, jax.Array]:
    """(auroc, thresholds) at fixed thresholds; multi-task via a
    ``(num_tasks, n)`` leading dim.  Degenerate rows (no positives or no
    negatives) yield 0.5, matching the exact ``binary_auroc``."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    threshold = _create_threshold_tensor(threshold)
    _binned_precision_recall_curve_param_check(threshold)
    _binary_auroc_update_input_check(input, target, num_tasks)
    squeeze = input.ndim == 1
    if squeeze:
        input, target = input[None], target[None]
    auroc = _binned_auroc_from_counts(
        *_binned_counts_rows(input, target == 1, threshold)
    )
    return (auroc[0] if squeeze else auroc), threshold


def multiclass_binned_auroc(
    input,
    target,
    *,
    num_classes: int,
    average: Optional[str] = "macro",
    threshold: Union[int, List[float], "jax.Array"] = 200,
) -> Tuple[jax.Array, jax.Array]:
    """One-vs-rest binned AUROC with macro/None averaging."""
    _binned_auc_average_param_check(num_classes, average, "num_classes")
    input, target = jnp.asarray(input), jnp.asarray(target)
    threshold = _create_threshold_tensor(threshold)
    _binned_precision_recall_curve_param_check(threshold)
    _multiclass_binned_auc_validate(input, target, num_classes)
    auroc = _binned_auroc_from_counts(
        *_multiclass_binned_counts_kernel(input, target, threshold, num_classes)
    )
    return (auroc.mean() if average == "macro" else auroc), threshold


def binary_binned_auprc(
    input,
    target,
    *,
    num_tasks: int = 1,
    threshold: Union[int, List[float], "jax.Array"] = 100,
) -> Tuple[jax.Array, jax.Array]:
    """(average precision, thresholds) at fixed thresholds; multi-task via
    a ``(num_tasks, n)`` leading dim.  Rows with no positives yield 0,
    matching the exact ``binary_auprc``."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    threshold = _create_threshold_tensor(threshold)
    _binned_precision_recall_curve_param_check(threshold)
    _binary_auroc_update_input_check(input, target, num_tasks)
    squeeze = input.ndim == 1
    if squeeze:
        input, target = input[None], target[None]
    auprc = _binned_auprc_from_counts(
        *_binned_counts_rows(input, target == 1, threshold)
    )
    return (auprc[0] if squeeze else auprc), threshold


def multiclass_binned_auprc(
    input,
    target,
    *,
    num_classes: int,
    average: Optional[str] = "macro",
    threshold: Union[int, List[float], "jax.Array"] = 100,
) -> Tuple[jax.Array, jax.Array]:
    """One-vs-rest binned average precision with macro/None averaging."""
    _binned_auc_average_param_check(num_classes, average, "num_classes")
    input, target = jnp.asarray(input), jnp.asarray(target)
    threshold = _create_threshold_tensor(threshold)
    _binned_precision_recall_curve_param_check(threshold)
    _multiclass_binned_auc_validate(input, target, num_classes)
    auprc = _binned_auprc_from_counts(
        *_multiclass_binned_counts_kernel(input, target, threshold, num_classes)
    )
    return (auprc.mean() if average == "macro" else auprc), threshold


def multilabel_binned_auprc(
    input,
    target,
    *,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    threshold: Union[int, List[float], "jax.Array"] = 100,
) -> Tuple[jax.Array, jax.Array]:
    """Per-label binned average precision over a ``(n, num_labels)`` 0/1
    target matrix with macro/None averaging."""
    _binned_auc_average_param_check(num_labels, average, "num_labels")
    input, target = jnp.asarray(input), jnp.asarray(target)
    threshold = _create_threshold_tensor(threshold)
    _binned_precision_recall_curve_param_check(threshold)
    _multilabel_precision_recall_curve_update_input_check(input, target, num_labels)
    auprc = _binned_auprc_from_counts(
        *_multilabel_binned_counts_kernel(input, target, threshold)
    )
    return (auprc.mean() if average == "macro" else auprc), threshold


def multilabel_binned_precision_recall_curve(
    input,
    target,
    *,
    num_labels: Optional[int] = None,
    threshold: Union[int, List[float], "jax.Array"] = 100,
) -> Tuple[List[jax.Array], List[jax.Array], jax.Array]:
    """Per-label binned PR curves over a ``(n, num_labels)`` 0/1 target
    matrix (list of per-label precision/recall vectors with the (1.0, 0.0)
    sentinel point, plus the shared thresholds)."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    threshold = _create_threshold_tensor(threshold)
    _binned_precision_recall_curve_param_check(threshold)
    _multilabel_precision_recall_curve_update_input_check(input, target, num_labels)
    tp, fp, pos, _ = _multilabel_binned_counts_kernel(input, target, threshold)
    return _binned_curves_from_counts(tp, fp, pos, threshold)


def _binned_curves_from_counts(
    tp: jax.Array, fp: jax.Array, pos: jax.Array, threshold: jax.Array
) -> Tuple[List[jax.Array], List[jax.Array], jax.Array]:
    """Row-count layout (R, T) → the reference's (T, R) binned-curve
    compute, reusing its sentinel/NaN semantics."""
    fn = pos[:, None] - tp
    precision, recall = _multiclass_binned_compute_kernel(tp.T, fp.T, fn.T)
    return list(precision.T), list(recall.T), threshold


def _multiclass_binned_auc_validate(
    input: jax.Array, target: jax.Array, num_classes: int
) -> None:
    """Shape check + OOB-target raise shared by the functional and class
    paths — ``class_hits`` would otherwise silently count an out-of-range
    target as a negative for every class."""
    _multiclass_auroc_update_input_check(input, target, num_classes)
    _check_index_range(target, num_classes, "target")


# Work-product bound for the fused broadcast-compare formulation:
# measured ~680G compare-ops/s on v5e (1.3e9 ops in 1.9 ms), so 2^32 ops
# is ~6 ms — the Pallas histogram's fixed grid cost.  Above it the MXU
# kernel wins; below it the VPU broadcast does.
_BROADCAST_MAX_WORK = 2**32


def _select_binned_route(
    num_rows: int, num_samples: int, thresholds: jax.Array
) -> str:
    """Call-time formulation choice for the binned-counts stage.

    Evaluated OUTSIDE jit (the result rides into the jitted kernels as a
    static argument), so the ``TORCHEVAL_TPU_DISABLE_PALLAS`` kill-switch
    is honored per call even for already-compiled shapes, and the Pallas
    module is never imported while the switch is set.  Only the grid's
    static shape is consulted — no device sync on the update path.

    * ``"broadcast"`` — TPU, work = R·N·T ≤ 2^32: XLA fuses the
      ``(R, N, T)`` comparison straight into its two reductions (no
      materialization; ~680G compare-ops/s on the VPU).
    * ``"pallas"`` — TPU, larger work, within the MXU kernel's bounds
      (rows < 2^24 samples for exact f32 per-bin accumulation — the sort
      is int32-exact — and ≤ 2^15 thresholds for the VMEM one-hot tiles).
      The kernel's finite pad sentinel is safe here because every public
      binned entry point enforces thresholds within [0, 1]
      (``_binned_precision_recall_curve_param_check``), far below the
      3.0e38 pad; scores above it are clamped inside the kernel wrapper.
    * ``"sort"`` — CPU, kill-switch, or out-of-bounds fallback.
    """
    from torcheval_tpu.ops._flags import pallas_disabled

    num_thresholds = thresholds.shape[0]
    if pallas_disabled() or jax.default_backend() != "tpu":
        return "sort"
    if num_rows * num_samples * num_thresholds <= _BROADCAST_MAX_WORK:
        return "broadcast"
    if num_samples < 2**24 and num_thresholds <= 2**15:
        return "pallas"
    return "sort"


def _binned_counts_rows(
    scores: jax.Array,
    hits: jax.Array,
    thresholds: jax.Array,
    route: Optional[str] = None,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-threshold prediction counts for ``pred = score >= t`` over
    ``(R, N)`` score/hit rows — three formulations returning
    bit-identical int32 counts, chosen by :func:`_select_binned_route`
    (measured regimes in BASELINE.md).  Pass ``route`` when calling from
    inside jit (it must be selected at call time, outside the trace).

    ``mask`` (shape ``(N,)``) excludes padded samples exactly: their
    scores become ``-inf`` — below every threshold (public entry points
    enforce thresholds in [0, 1]) in every formulation, so they never
    count as predictions — their hits are zeroed out of ``num_tp`` /
    ``num_pos``, and ``num_total`` becomes ``mask.sum()``.  The Pallas
    histogram has no masked-row path (its pad sentinel is a large
    finite), so a mask downgrades that route to the bit-identical
    sort."""
    if route is None:
        route = _select_binned_route(
            scores.shape[0], scores.shape[-1], thresholds
        )
    if mask is not None:
        if route == "pallas":
            route = "sort"
        valid = mask.astype(jnp.bool_)
        scores = jnp.where(valid[None, :], scores, -jnp.inf)
        hits = jnp.logical_and(hits, valid[None, :])
    if route == "broadcast":
        out = _binned_counts_rows_broadcast(scores, hits, thresholds)
    elif route == "pallas":
        from torcheval_tpu.ops.pallas_binned import pallas_binned_counts

        out = pallas_binned_counts(scores, hits, thresholds)
    else:
        out = _binned_counts_rows_sort(scores, hits, thresholds)
    if mask is None:
        return out
    num_tp, num_fp, num_pos, num_total = out
    num_total = jnp.zeros_like(num_total) + valid.sum(dtype=jnp.int32)
    return num_tp, num_fp, num_pos, num_total


@jax.jit
def _binned_counts_rows_broadcast(
    scores: jax.Array, hits: jax.Array, thresholds: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fused broadcast-compare formulation (small-work TPU regime)."""
    num_rows, n = scores.shape
    ge = scores[:, :, None] >= thresholds[None, None, :]  # (R, N, T), fused
    hits_b = hits.astype(jnp.bool_)
    num_ge = ge.sum(axis=1, dtype=jnp.int32)
    num_tp = (ge & hits_b[:, :, None]).sum(axis=1, dtype=jnp.int32)
    num_pos = hits_b.sum(axis=-1, dtype=jnp.int32)
    return (
        num_tp,
        num_ge - num_tp,
        num_pos,
        jnp.full((num_rows,), n, jnp.int32),
    )


@jax.jit
def _binned_counts_rows_sort(
    scores: jax.Array, hits: jax.Array, thresholds: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Sort-formulation binned counts: one variadic sort co-sorts hits
    with scores, an inclusive cumsum gives hits-below-any-point, and
    ``searchsorted`` reads each threshold's boundary off the sorted row:
    ``num_tp(t) = total_hits − hits_below(t)``.  Scatter-free (TPU
    scatters serialize; sorting the row is several times faster).
    Returns ``(num_tp (R,T), num_fp (R,T), num_pos (R,), num_total (R,))``
    — the add-mergeable sufficient statistics of every binned AUC
    metric."""
    num_rows, n = scores.shape
    num_t = thresholds.shape[0]
    if n == 0:
        zero_t = jnp.zeros((num_rows, num_t), jnp.int32)
        zero_r = jnp.zeros((num_rows,), jnp.int32)
        return zero_t, zero_t, zero_r, zero_r
    # int8 payload: sort bandwidth dominates this pattern (see _sort_scan);
    # widen in the cumsum instead.  Single rows sort/scan in 1-D layout
    # (see _sort_scan.sort_row_1d).
    if num_rows == 1:
        from torcheval_tpu.metrics.functional.classification._sort_scan import (
            sort_row_1d,
        )

        s_1d, h_1d = sort_row_1d(scores[0], hits[0].astype(jnp.int8))
        s_sorted = s_1d[None]
        cum_hits = jnp.cumsum(h_1d, dtype=jnp.int32)[None]
    else:
        s_sorted, h_sorted = lax.sort(
            (scores, hits.astype(jnp.int8)), dimension=-1, num_keys=1
        )
        cum_hits = jnp.cumsum(h_sorted, axis=-1, dtype=jnp.int32)
    total_hits = cum_hits[:, -1:]
    idx = jax.vmap(
        lambda row: jnp.searchsorted(row, thresholds, side="left")
    )(s_sorted)
    hits_below = jnp.take_along_axis(
        jnp.concatenate(
            [jnp.zeros((num_rows, 1), jnp.int32), cum_hits], axis=-1
        ),
        idx,
        axis=-1,
    )
    num_tp = total_hits - hits_below
    num_fp = (n - idx).astype(jnp.int32) - num_tp
    return num_tp, num_fp, total_hits[:, 0], jnp.full((num_rows,), n, jnp.int32)


def _multiclass_binned_counts_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: jax.Array,
    num_classes: int,
    route: Optional[str] = None,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    # Route chosen at call time, then baked into the jit as static.  Class
    # metrics pass it explicitly (their fused update traces this function,
    # and the choice must not be frozen into the trace).
    if route is None:
        route = _select_binned_route(num_classes, input.shape[0], threshold)
    return _multiclass_binned_counts_jit(
        input, target, threshold, num_classes, route, mask=mask
    )


@partial(jax.jit, static_argnames=("num_classes", "route"))
def _multiclass_binned_counts_jit(
    input: jax.Array,
    target: jax.Array,
    threshold: jax.Array,
    num_classes: int,
    route: str,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    return _binned_counts_rows(
        input.T,
        class_hits(target, num_classes),
        threshold,
        route=route,
        mask=mask,
    )


def _multilabel_binned_counts_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: jax.Array,
    route: Optional[str] = None,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    if route is None:
        route = _select_binned_route(input.shape[1], input.shape[0], threshold)
    return _multilabel_binned_counts_jit(input, target, threshold, route, mask=mask)


@partial(jax.jit, static_argnames=("route",))
def _multilabel_binned_counts_jit(
    input: jax.Array,
    target: jax.Array,
    threshold: jax.Array,
    route: str,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    return _binned_counts_rows(
        input.T, (target == 1).T, threshold, route=route, mask=mask
    )


@jax.jit
def _binned_auroc_from_counts(
    num_tp: jax.Array,
    num_fp: jax.Array,
    num_pos: jax.Array,
    num_total: jax.Array,
) -> jax.Array:
    """Trapezoidal area under the binned ROC polyline.

    Thresholds ascend, so (FPR, TPR) descends toward the appended (0, 0)
    anchor; with thresholds starting at 0 and scores in [0, 1] the first
    point is (1, 1).  Degenerate rows (single class present) → 0.5."""
    num_rows = num_tp.shape[0]
    pos = num_pos.astype(jnp.float32)
    neg = (num_total - num_pos).astype(jnp.float32)
    tpr = num_tp / jnp.maximum(pos, 1.0)[:, None]
    fpr = num_fp / jnp.maximum(neg, 1.0)[:, None]
    zero = jnp.zeros((num_rows, 1))
    tpr = jnp.concatenate([tpr, zero], axis=-1)[:, ::-1]
    fpr = jnp.concatenate([fpr, zero], axis=-1)[:, ::-1]
    auroc = jnp.trapezoid(tpr, fpr, axis=-1)
    return jnp.where((num_pos == 0) | (num_pos == num_total), 0.5, auroc)


@jax.jit
def _binned_auprc_from_counts(
    num_tp: jax.Array,
    num_fp: jax.Array,
    num_pos: jax.Array,
    num_total: jax.Array,
) -> jax.Array:
    """Step-sum average precision over the binned PR points: with
    thresholds ascending (recall non-increasing),
    AP = Σ_t (R_t − R_{t+1}) · P_t with R fading to 0 past the last
    threshold — the same pairing as sklearn's step rule.  Rows with no
    positives → 0 (matching the exact AUPRC)."""
    del num_total
    pos = jnp.maximum(num_pos.astype(jnp.float32), 1.0)[:, None]
    precision = jnp.nan_to_num(num_tp / (num_tp + num_fp), nan=1.0)
    recall = num_tp / pos
    recall_next = jnp.concatenate(
        [recall[:, 1:], jnp.zeros((recall.shape[0], 1))], axis=-1
    )
    ap = ((recall - recall_next) * precision).sum(axis=-1)
    return jnp.where(num_pos == 0, 0.0, ap)


def _binned_auc_average_param_check(
    num_rows: Optional[int], average: Optional[str], name: str
) -> None:
    average_options = ("macro", "none", None)
    if average not in average_options:
        raise ValueError(
            f"`average` was not in the allowed value of {average_options}, "
            f"got {average}."
        )
    if num_rows is not None and num_rows < 2:
        raise ValueError(f"`{name}` has to be at least 2.")
