"""Precision-recall curves — parity with reference
``torcheval/metrics/functional/classification/precision_recall_curve.py``
(229 LoC).

Ragged outputs under static shapes (SURVEY §7 hard part 1): the jit kernel
computes fixed-shape sorted thresholds, tie-group masks and cumulative
TP/FP on device; the ragged per-class curves are materialized on the host
at the compute boundary by boolean-compacting the mask — the only
data-dependent-shape step, deliberately outside XLA."""

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.functional.classification._sort_scan import (
    class_hits,
    sorted_tie_cumsums,
)


def binary_precision_recall_curve(
    input,
    target,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(precision, recall, thresholds) over descending score thresholds
    (reference ``precision_recall_curve.py:18-90``)."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    _binary_precision_recall_curve_update_input_check(input, target)
    return _binary_precision_recall_curve_compute(input, target)


def multiclass_precision_recall_curve(
    input,
    target,
    *,
    num_classes: Optional[int] = None,
) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
    """Per-class PR curves; classes missing from target get recall 1.0
    (reference ``precision_recall_curve.py:93-203``)."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    if num_classes is None and input.ndim == 2:
        num_classes = input.shape[1]
    _multiclass_precision_recall_curve_update_input_check(input, target, num_classes)
    return _multiclass_precision_recall_curve_compute(input, target, num_classes)


@jax.jit
def _prc_device_kernel(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fixed-shape part: sort + tie mask + cumsums (binary, 1-D)."""
    threshold, is_last, num_tp, num_fp = sorted_tie_cumsums(
        input[None], (target == 1)[None]
    )
    return threshold[0], is_last[0], num_tp[0], num_fp[0]


def _binary_precision_recall_curve_compute(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    return _compute_for_each_class(input, target, 1)


def _materialize_curve(
    tp: np.ndarray, fp: np.ndarray, thresholds_masked: np.ndarray
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Shared host-side ragged materialization: flip to ascending thresholds,
    append the (1.0, 0.0) sentinel, NaN recall (no positives) → 1.0
    (reference jit kernel ``precision_recall_curve.py:206-229``)."""
    with np.errstate(invalid="ignore"):
        precision = (tp / (tp + fp))[::-1]
        total = tp[-1] if tp.size else 0
        recall = (tp / total)[::-1] if tp.size else tp.astype(np.float64)
    precision = np.concatenate([precision, np.ones(1)])
    recall = np.concatenate([recall, np.zeros(1)])
    if recall.size and np.isnan(recall[0]):
        recall = np.nan_to_num(recall, nan=1.0)
    return (
        jnp.asarray(precision.astype(np.float32)),
        jnp.asarray(recall.astype(np.float32)),
        jnp.asarray(thresholds_masked[::-1]),
    )


def _empty_curve() -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Zero-sample curve: just the (1.0, 0.0) sentinel point, no thresholds."""
    empty = np.zeros(0, dtype=np.int64)
    return _materialize_curve(empty, empty, np.zeros(0, dtype=np.float32))


def _compute_for_each_class(
    input: jax.Array, target: jax.Array, pos_label: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    if input.shape[-1] == 0:
        return _empty_curve()
    threshold, is_last, num_tp, num_fp = jax.device_get(
        _prc_device_kernel(input, jnp.asarray(target == pos_label, dtype=jnp.int32))
    )
    mask = np.asarray(is_last)
    return _materialize_curve(num_tp[mask], num_fp[mask], threshold[mask])


@jax.jit
def _prc_multiclass_device_kernel(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fixed-shape part, vectorized over classes: (C, N) sorts + cumsums."""
    return sorted_tie_cumsums(input.T, class_hits(target, input.shape[1]))


def _multiclass_precision_recall_curve_compute(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
    if num_classes is None:
        num_classes = input.shape[1]
    return _materialize_row_curves(
        _prc_multiclass_device_kernel, input, target, num_classes
    )


def _materialize_row_curves(
    device_kernel,
    input: jax.Array,
    target: jax.Array,
    num_rows: int,
) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
    """Shared ragged materialization for per-row (class/label) curve
    families: run the fixed-shape device kernel once, then compact each
    row's tie-group mask on the host."""
    if input.shape[0] == 0:
        curves = [_empty_curve() for _ in range(num_rows)]
        return tuple(list(xs) for xs in zip(*curves))
    thresholds, is_last, num_tp, num_fp = jax.device_get(
        device_kernel(input, target)
    )
    precisions, recalls, thresh_list = [], [], []
    for c in range(num_rows):
        mask = is_last[c]
        p, r, t = _materialize_curve(
            num_tp[c][mask], num_fp[c][mask], thresholds[c][mask]
        )
        precisions.append(p)
        recalls.append(r)
        thresh_list.append(t)
    return precisions, recalls, thresh_list


def multilabel_precision_recall_curve(
    input,
    target,
    *,
    num_labels: Optional[int] = None,
) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
    """Per-label PR curves over a ``(n_samples, num_labels)`` 0/1 target
    matrix.  Beyond the v0.0.4 snapshot (upstream torcheval added
    ``multilabel_precision_recall_curve`` later); each label column is an
    independent binary curve, vectorized through the same ``(R, N)``
    sort+tie-scan device kernel as the multiclass form."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    if num_labels is None and input.ndim == 2:
        num_labels = input.shape[1]
    _multilabel_precision_recall_curve_update_input_check(input, target, num_labels)
    return _multilabel_precision_recall_curve_compute(input, target, num_labels)


@jax.jit
def _prc_multilabel_device_kernel(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fixed-shape part, vectorized over labels: (L, N) sorts + cumsums."""
    return sorted_tie_cumsums(input.T, (target == 1).T)


def _multilabel_precision_recall_curve_compute(
    input: jax.Array,
    target: jax.Array,
    num_labels: Optional[int],
) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
    if num_labels is None:
        num_labels = input.shape[1]
    return _materialize_row_curves(
        _prc_multilabel_device_kernel, input, target, num_labels
    )


def _binary_precision_recall_curve_update_input_check(
    input: jax.Array, target: jax.Array
) -> None:
    if input.ndim != 1:
        raise ValueError(
            f"input should be a one-dimensional tensor, got shape {input.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same shape, "
            f"got shapes {input.shape} and {target.shape}."
        )


def _multiclass_precision_recall_curve_update_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int]
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if not (input.ndim == 2 and (num_classes is None or input.shape[1] == num_classes)):
        raise ValueError(
            "input should have shape of (num_sample, num_classes), "
            f"got {input.shape} and num_classes={num_classes}."
        )


def _multilabel_precision_recall_curve_update_input_check(
    input: jax.Array, target: jax.Array, num_labels: Optional[int]
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "Expected both input.shape and target.shape to have the same shape"
            f" but got {input.shape} and {target.shape}."
        )
    if not (input.ndim == 2 and (num_labels is None or input.shape[1] == num_labels)):
        raise ValueError(
            "input should have shape of (num_sample, num_labels), "
            f"got {input.shape} and num_labels={num_labels}."
        )
