"""Shared descending-sort + tie-group-mask + cumulative-count core used by
every threshold-curve kernel (AUROC and PR curves, binary and multiclass).

The reference implements this block separately inside each TorchScript
kernel (``auroc.py:111-142,188-217``, ``precision_recall_curve.py:154-180,
206-229``); here it is one jit-traceable helper so tie-handling semantics
can never drift between the exact and curve paths.
"""

from typing import Tuple

import jax
import jax.numpy as jnp


def sort_row_1d(keys: jax.Array, payload: jax.Array):
    """Stable variadic sort of ONE row in 1-D layout.

    XLA lays a ``(1, N)`` row out as 1 sublane × N lanes, so every
    sorting-network stage (and any cumsum/diff fused after it) runs at
    1/8 VPU occupancy — measured on v5e at N=2^22: 58.4 ms for the
    ``(1, N)`` variadic sort vs 7.3 ms flat.  Same values, same stable
    order — only the layout changes.  Shared by every single-row curve
    path (``sorted_tie_cumsums``, ``pallas_binary_auroc``, the binned
    sort formulation) so the workaround can never drift between them.
    ``keys``/``payload`` are 1-D; returns the sorted 1-D pair.
    """
    return jax.lax.sort((keys, payload), num_keys=1)


def sorted_tie_cumsums(
    scores: jax.Array, hits: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Row-wise threshold scan over ``(R, N)`` score/hit pairs.

    Sorts each row by descending score and returns
    ``(thresholds, is_last, cum_tp, cum_fp)``, all shaped ``(R, N)``:
    ``thresholds`` the sorted scores, ``is_last`` flagging the last element
    of each tie group, and the int32 cumulative true/false-positive counts.
    """
    # Variadic sort carries the hit payload through the sort itself; on TPU
    # this is ~20x faster than argsort + two take_along_axis gathers (the
    # gathers dominate at (1000, 131072): 3.95s vs 0.20s on v5e).
    #
    # Single rows sort AND scan in 1-D layout (see sort_row_1d).
    if scores.shape[0] == 1:
        neg_1d, hits_1d = sort_row_1d(-scores[0], hits[0].astype(jnp.int8))
        thresholds = -neg_1d
        sorted_hits = hits_1d.astype(jnp.bool_)
        is_last = jnp.concatenate(
            [jnp.diff(thresholds) != 0, jnp.ones((1,), dtype=jnp.bool_)]
        )
        cum_tp = jnp.cumsum(sorted_hits, dtype=jnp.int32)
        cum_fp = jnp.cumsum(~sorted_hits, dtype=jnp.int32)
        return thresholds[None], is_last[None], cum_tp[None], cum_fp[None]
    neg_thresholds, sorted_hits_i8 = jax.lax.sort(
        (-scores, hits.astype(jnp.int8)), num_keys=1
    )
    thresholds = -neg_thresholds
    sorted_hits = sorted_hits_i8.astype(jnp.bool_)
    is_last = jnp.concatenate(
        [
            jnp.diff(thresholds, axis=-1) != 0,
            jnp.ones((*thresholds.shape[:-1], 1), dtype=jnp.bool_),
        ],
        axis=-1,
    )
    cum_tp = jnp.cumsum(sorted_hits, axis=-1, dtype=jnp.int32)
    cum_fp = jnp.cumsum(~sorted_hits, axis=-1, dtype=jnp.int32)
    return thresholds, is_last, cum_tp, cum_fp


def class_hits(target: jax.Array, num_classes: int) -> jax.Array:
    """One-vs-rest hit matrix ``(C, N)``: row ``c`` flags ``target == c``."""
    return target[None, :] == jnp.arange(num_classes)[:, None]
