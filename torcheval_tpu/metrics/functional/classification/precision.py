"""Precision — parity with reference
``torcheval/metrics/functional/classification/precision.py`` (248 LoC).

Sufficient statistics: ``num_tp`` / ``num_fp`` / ``num_label`` counters
(scalars for micro, per-class vectors otherwise — scatter-add via
``zeros(C).at[idx].add(...)``, the XLA analog of ``Tensor.scatter_``).

Shape-stable divergence note: the reference masks classes absent from both
input and target via boolean indexing (``precision.py:140-175``); here the
same mean/weighting is computed with masked arithmetic so the kernel has a
static shape — results are identical.
"""

import logging
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
    _class_counts,
    _counts_route,
)
from torcheval_tpu.metrics.functional._host_checks import (
    all_concrete,
    check_index_ranges as _check_index_ranges,
)

_logger = logging.getLogger(__name__)


def binary_precision(input, target, *, threshold: float = 0.5) -> jax.Array:
    """TP / (TP + FP) after thresholding (reference ``precision.py:16-51``)."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    num_tp, num_fp, num_label = _binary_precision_update(input, target, threshold)
    return _precision_compute(num_tp, num_fp, num_label, "micro")


def multiclass_precision(
    input,
    target,
    *,
    num_classes: Optional[int] = None,
    average: Optional[str] = "micro",
) -> jax.Array:
    """Multiclass precision with micro/macro/weighted/None averaging
    (reference ``precision.py:54-110``)."""
    _precision_param_check(num_classes, average)
    input, target = jnp.asarray(input), jnp.asarray(target)
    num_tp, num_fp, num_label = _precision_update(input, target, num_classes, average)
    return _precision_compute(num_tp, num_fp, num_label, average)


def _precision_update(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    _precision_validate(input, target, num_classes, average)
    return _precision_update_kernel(
        input,
        target,
        num_classes,
        average,
        _counts_route(input, num_classes, average),
    )


def _precision_validate(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
) -> None:
    """Host-side update validation shared by the functional and class paths."""
    _precision_update_input_check(input, target, num_classes)
    if average != "micro":
        pairs = [(target, "target")]
        if input.ndim == 1:
            pairs.append((input, "input"))
        _check_index_ranges(pairs, num_classes)


@partial(jax.jit, static_argnames=("num_classes", "average", "route"))
def _precision_update_kernel(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
    route: str = "scatter",
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    if input.ndim == 2:
        input = jnp.argmax(input, axis=1)
    if average == "micro":
        if mask is None:
            num_tp = (input == target).sum()
            num_fp = (input != target).sum()
        else:
            m = mask.astype(jnp.int32)
            num_tp = ((input == target).astype(jnp.int32) * m).sum()
            num_fp = ((input != target).astype(jnp.int32) * m).sum()
        return num_tp, num_fp, jnp.asarray(0.0)
    # ONE routed (C, C)-slab accumulation instead of three label
    # scatters (each serializes on TPU) — see _class_counts; the false
    # positives are the prediction marginal minus the diagonal.
    num_tp, num_label, num_prediction = _class_counts(
        input, target, num_classes, route, mask=mask
    )
    return num_tp, num_prediction - num_tp, num_label


def _precision_compute(
    num_tp: jax.Array,
    num_fp: jax.Array,
    num_label: jax.Array,
    average: Optional[str],
) -> jax.Array:
    if average in (None, "None") and num_tp.ndim and all_concrete(num_tp, num_fp):
        # numpy, not jnp: under an ambient trace even ops on concrete
        # arrays are staged, and a staged bool() would crash the trace.
        nan_mask = (np.asarray(num_tp) + np.asarray(num_fp)) == 0
        if nan_mask.any():
            bad_class = np.nonzero(nan_mask)[0]
            _logger.warning(
                f"{bad_class} classes have zero instances in both the "
                "predictions and the ground truth labels. Precision is still "
                "logged as zero."
            )
    return _precision_compute_kernel(num_tp, num_fp, num_label, average)


@partial(jax.jit, static_argnames=("average",))
def _precision_compute_kernel(
    num_tp: jax.Array,
    num_fp: jax.Array,
    num_label: jax.Array,
    average: Optional[str],
) -> jax.Array:
    precision = jnp.nan_to_num(num_tp / (num_tp + num_fp))
    if average == "micro" or average in (None, "None"):
        return precision
    # macro / weighted: ignore classes absent from both input and target
    # (reference ``precision.py:140-147``), computed shape-stably.
    mask = (num_label != 0) | ((num_tp + num_fp) != 0)
    if average == "macro":
        return jnp.sum(jnp.where(mask, precision, 0.0)) / jnp.sum(mask)
    # weighted
    return jnp.sum(precision * num_label) / jnp.sum(num_label)


def _precision_param_check(
    num_classes: Optional[int], average: Optional[str]
) -> None:
    average_options = ("micro", "macro", "weighted", "None", None)
    if average not in average_options:
        raise ValueError(
            f"`average` was not in the allowed value of {average_options}, got {average}."
        )
    if average != "micro" and (num_classes is None or num_classes <= 0):
        raise ValueError(
            f"num_classes should be a positive number when average={average}."
            f" Got num_classes={num_classes}."
        )


def _precision_update_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int]
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if not input.ndim == 1 and not (
        input.ndim == 2 and (num_classes is None or input.shape[1] == num_classes)
    ):
        raise ValueError(
            "input should have shape of (num_sample,) or (num_sample, num_classes), "
            f"got {input.shape}."
        )


def _check_index_range(values: jax.Array, upper: Optional[int], name: str) -> None:
    """OOB class indices must raise (XLA scatter silently drops them where
    torch ``scatter_`` errors)."""
    _check_index_ranges([(values, name)], upper)


def _binary_precision_update(
    input: jax.Array, target: jax.Array, threshold: float = 0.5
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    _binary_precision_update_input_check(input, target)
    return _binary_precision_update_kernel(input, target, threshold)


@partial(jax.jit, static_argnames=("threshold",))
def _binary_precision_update_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: float,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    pred = jnp.where(input < threshold, 0, 1)
    target_b = target.astype(jnp.bool_)
    pred_b = pred.astype(jnp.bool_)
    if mask is not None:
        valid = mask.astype(jnp.bool_)
        pred_b = pred_b & valid
        target_b = target_b & valid
        num_fp = (pred_b & ~target_b & valid).sum()
    else:
        num_fp = (pred_b & ~target_b).sum()
    num_tp = (pred_b & target_b).sum()
    return num_tp, num_fp, jnp.asarray(0.0)


def _binary_precision_update_input_check(
    input: jax.Array, target: jax.Array
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same dimensions, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
