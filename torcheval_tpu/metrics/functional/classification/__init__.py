from torcheval_tpu.metrics.functional.classification.accuracy import (
    binary_accuracy,
    multiclass_accuracy,
    multilabel_accuracy,
    topk_multilabel_accuracy,
)

__all__ = [
    "binary_accuracy",
    "multiclass_accuracy",
    "multilabel_accuracy",
    "topk_multilabel_accuracy",
]
