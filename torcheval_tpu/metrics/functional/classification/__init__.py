from torcheval_tpu.metrics.functional.classification.auprc import (
    binary_auprc,
    multiclass_auprc,
    multilabel_auprc,
)
from torcheval_tpu.metrics.functional.classification.auroc import (
    binary_auroc,
    multiclass_auroc,
)
from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    binary_precision_recall_curve,
    multiclass_precision_recall_curve,
    multilabel_precision_recall_curve,
)
from torcheval_tpu.metrics.functional.classification.accuracy import (
    binary_accuracy,
    multiclass_accuracy,
    multilabel_accuracy,
    topk_multilabel_accuracy,
)
from torcheval_tpu.metrics.functional.classification.binary_normalized_entropy import (
    binary_normalized_entropy,
)
from torcheval_tpu.metrics.functional.classification.binned_auc import (
    binary_binned_auprc,
    binary_binned_auroc,
    multiclass_binned_auprc,
    multiclass_binned_auroc,
    multilabel_binned_auprc,
    multilabel_binned_precision_recall_curve,
)
from torcheval_tpu.metrics.functional.classification.binned_precision_recall_curve import (
    binary_binned_precision_recall_curve,
    multiclass_binned_precision_recall_curve,
)
from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
    binary_confusion_matrix,
    multiclass_confusion_matrix,
)
from torcheval_tpu.metrics.functional.classification.f1_score import (
    binary_f1_score,
    multiclass_f1_score,
)
from torcheval_tpu.metrics.functional.classification.precision import (
    binary_precision,
    multiclass_precision,
)
from torcheval_tpu.metrics.functional.classification.recall import (
    binary_recall,
    multiclass_recall,
)
from torcheval_tpu.metrics.functional.classification.recall_at_fixed_precision import (
    binary_recall_at_fixed_precision,
    multilabel_recall_at_fixed_precision,
)

__all__ = [
    "binary_accuracy",
    "binary_auprc",
    "binary_auroc",
    "binary_binned_auprc",
    "binary_binned_auroc",
    "binary_binned_precision_recall_curve",
    "binary_confusion_matrix",
    "binary_f1_score",
    "binary_normalized_entropy",
    "binary_precision",
    "binary_precision_recall_curve",
    "binary_recall",
    "binary_recall_at_fixed_precision",
    "multiclass_accuracy",
    "multiclass_auprc",
    "multiclass_auroc",
    "multiclass_binned_auprc",
    "multiclass_binned_auroc",
    "multiclass_binned_precision_recall_curve",
    "multiclass_confusion_matrix",
    "multiclass_f1_score",
    "multiclass_precision",
    "multiclass_precision_recall_curve",
    "multiclass_recall",
    "multilabel_accuracy",
    "multilabel_auprc",
    "multilabel_binned_auprc",
    "multilabel_binned_precision_recall_curve",
    "multilabel_precision_recall_curve",
    "multilabel_recall_at_fixed_precision",
    "topk_multilabel_accuracy",
]
