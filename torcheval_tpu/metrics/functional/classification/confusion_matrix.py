"""Confusion matrix — parity with reference
``torcheval/metrics/functional/classification/confusion_matrix.py`` (280 LoC).

TPU-first: where the reference builds a sparse COO tensor and densifies it
(reference ``confusion_matrix.py:217-232``), the update here dispatches
three ways (``_cm_route``): ONE MXU matmul of one-hot encodings (``cm =
onehot(target)ᵀ @ onehot(pred)``, up to 207× the scatter at tiny C — see
``_use_matmul_cm`` for the measured table), the bucket-compaction Pallas
kernel (``ops/pallas_cm.py``, 2.1× the scatter at 2^20×1000 and the
route's winner for C in (64, ~1150]), and a single scatter-add
``zeros((C, C)).at[target, pred].add(1)`` elsewhere.  F1/precision/recall
derive their per-class count trios from the same routed slab
(``_class_counts``) instead of the reference's three separate label
scatters.  The dead
``_binary_confusion_matrix_compute`` with swapped normalization dims
(reference ``confusion_matrix.py:150-160``) is intentionally not
reproduced (SURVEY §7 hard part 7)."""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional._host_checks import (
    all_concrete,
    bounds,
    value_checks_enabled,
)


def binary_confusion_matrix(
    input,
    target,
    *,
    threshold: float = 0.5,
    normalize: Optional[str] = None,
) -> jax.Array:
    """2×2 confusion matrix of thresholded predictions
    (reference ``confusion_matrix.py:14-64``)."""
    _confusion_matrix_param_check(2, normalize)
    input, target = jnp.asarray(input), jnp.asarray(target)
    matrix = _binary_confusion_matrix_update(input, target, threshold)
    return _confusion_matrix_compute(matrix, normalize)


def multiclass_confusion_matrix(
    input,
    target,
    num_classes: int,
    *,
    normalize: Optional[str] = None,
) -> jax.Array:
    """C×C matrix; entry (i, j) counts true class i predicted as j
    (reference ``confusion_matrix.py:67-147``)."""
    _confusion_matrix_param_check(num_classes, normalize)
    input, target = jnp.asarray(input), jnp.asarray(target)
    cm = _confusion_matrix_update(input, target, num_classes)
    return _confusion_matrix_compute(cm, normalize)


def _confusion_matrix_update(
    input: jax.Array, target: jax.Array, num_classes: int
) -> jax.Array:
    _confusion_matrix_update_input_check(input, target, num_classes)
    route = _cm_route(num_classes, input.shape[0])
    return _confusion_matrix_update_kernel(
        input,
        target,
        num_classes,
        route,
        row_chunk=_cm_row_chunk() if route == "matmul" else 0,
    )


def _cm_route(num_classes: int, num_samples: int) -> str:
    """Three-way route for the (C, C) count accumulation, decided at call
    time from shapes/backend/flags only (so it is identical under a
    caller's jit — no tracer-dependent downgrade):

    - ``"matmul"``: ONE dense one-hot MXU matmul — tiny C or tiny N
      (:func:`_use_matmul_cm`'s measured table; 0.12 ms at 2^20×64).
    - ``"pallas"``: the bucket-compaction kernel (`ops/pallas_cm.py`).
      Measured crossover sweep on v5e at N=2^20 (ms, adaptive CAP):

          C        64    128   256   512   768   1000  1100
          pallas   1.16  1.68  2.58  3.84  3.12  3.34  3.67
          matmul   0.12  3.38  3.63  4.43  —     —     —
          scatter  ~7.1 at every C

      and over N at C=1000 the kernel holds a ~2.1× lead down to 2^15
      (0.108 vs 0.224 ms), so: matmul below C=65, pallas everywhere its
      window/N bounds allow, scatter beyond.
    - ``"scatter"``: the reference formulation — any backend, any
      shape; O(N + C²) memory and exact int32 counts.
    """
    from torcheval_tpu.ops._flags import pallas_disabled

    matmul_ok = _use_matmul_cm(num_classes, num_samples)
    if matmul_ok and num_classes <= 64:
        return "matmul"
    if not pallas_disabled() and jax.default_backend() == "tpu":
        from torcheval_tpu.ops.pallas_cm import _MAX_W, class_window

        if (
            class_window(num_classes) <= _MAX_W
            and 2**15 <= num_samples < 2**24
        ):
            return "pallas"
    return "matmul" if matmul_ok else "scatter"


def _use_matmul_cm(num_classes: int, num_samples: int) -> bool:
    """Route the (C, C) accumulation through one MXU matmul of one-hot
    encodings on TPU for small/medium C.  TPU scatters serialize (~1
    element/cycle: flat ~7 ms for 2^20 samples at ANY C) while the matmul
    costs n·C² MACs.  Measured on v5e (2^20 samples, device-loop clock):

        C=16   scatter 9.3 ms   matmul 0.045 ms   207x
        C=64   scatter 7.1 ms   matmul 0.12 ms     59x
        C=128  scatter 7.1 ms   matmul 3.4 ms     2.1x
        C=512  scatter 7.1 ms   matmul 4.4 ms     1.6x
        C=1000 scatter 7.1 ms   matmul 11.1 ms   0.64x

    f32 accumulation bounds the exact count range to 2^24 per cell, and
    the two (n, C) bf16 one-hots bound memory — n·C over 2^28 (≈1 GiB of
    one-hots) keeps the O(n)-memory scatter.

    Called OUTSIDE jit (the ``_select_binned_route`` pattern) and passed
    into the kernel as a static argument, so the
    ``TORCHEVAL_TPU_DISABLE_PALLAS`` kill-switch is honored at call time
    rather than frozen into the first compilation per shape."""
    from torcheval_tpu.ops._flags import pallas_disabled

    if pallas_disabled():
        # Same kill-switch as the kernels: force the reference formulation.
        return False
    if num_classes > 512 or num_samples >= 2**24:
        return False
    if num_samples * num_classes > 2**28:
        return False
    return jax.default_backend() == "tpu"


def _matmul_cm(
    input: jax.Array,
    target: jax.Array,
    num_classes: int,
    mask: Optional[jax.Array] = None,
    chunk: Optional[int] = None,
) -> jax.Array:
    """(C, C) counts as ONE MXU matmul of one-hot encodings: cm =
    onehot(target)ᵀ @ onehot(pred).  0/1 one-hots are exact in bf16 and
    the f32 accumulation is exact below 2^24 per cell, so the result is
    bit-identical to the scatter formulation within the dispatch
    bounds."""
    return _onehot_cm(
        target, input, num_classes, mask=mask, chunk=chunk
    ).astype(jnp.int32)


def _cm_row_chunk() -> int:
    """Row cap for one one-hot materialization, resolved at call time.

    Unchunked, the matmul route builds two (n, width) bf16 one-hots —
    4·n·width bytes of HBM written and re-read per batch, a ~2·width
    re-read multiplier over the n-row label vectors themselves (at
    width=1000 that is the full (C, C)-scale re-read the route table
    prices).  Chunking bounds the live one-hots to 2·chunk·width bytes
    (≤ ~8 MB at the 512-class matmul ceiling at the default), small
    enough to stay fusion/cache-resident, while the per-chunk partial
    counts are exact f32 integers so the accumulated slab is
    bit-identical at ANY chunking — which is what makes the knob safe
    for the autotuner to probe.

    Resolution order: the typed ``TORCHEVAL_TPU_CM_ROW_CHUNK`` flag
    when explicitly set (an explicit flag always outranks a
    measurement), else the measured-cost layer's pick when it is on
    and has raced chunk sizes, else the flag default (4096)."""
    from torcheval_tpu import _flags
    from torcheval_tpu import routing_autotune as _autotune
    from torcheval_tpu.ops import _flags as _oflags

    chunk = _oflags.cm_row_chunk()
    if _autotune.ENABLED:
        if _flags.FLAGS["CM_ROW_CHUNK"].raw() is None:
            try:
                chunk = int(_autotune.decide("cm_row_chunk", "*", str(chunk)))
            except ValueError:  # pragma: no cover - corrupt store row
                pass
    return chunk


def _onehot_cm_block(
    t: jax.Array, p: jax.Array, width: int, mask: Optional[jax.Array] = None
) -> jax.Array:
    """``(width, width)`` f32 counts as one bf16 one-hot dot_general —
    the shared core of :func:`_matmul_cm` and the matmul branch of
    :func:`_class_counts` (which widens by a sentinel column).  ``mask``
    zeroes padded rows of the contracted (target) one-hot — 0/1 scaling
    is exact in bf16, so masked counts stay bit-identical to a scatter
    over only the valid rows."""
    classes = jnp.arange(width)
    oh_t = (t[:, None] == classes[None, :]).astype(jnp.bfloat16)
    if mask is not None:
        oh_t = oh_t * mask.astype(jnp.bfloat16)[:, None]
    oh_p = (p[:, None] == classes[None, :]).astype(jnp.bfloat16)
    return jax.lax.dot_general(
        oh_t,
        oh_p,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _onehot_cm(
    t: jax.Array,
    p: jax.Array,
    width: int,
    mask: Optional[jax.Array] = None,
    chunk: Optional[int] = None,
) -> jax.Array:
    """:func:`_onehot_cm_block` with the one-hot tile capped at ``chunk``
    rows: longer batches fold chunk-partial slabs with exact f32 integer
    adds (bit-identical, any chunking).  Pad rows carry the label
    ``width`` — outside ``arange(width)``, so their one-hot row is all
    zeros and they drop without needing a mask.

    When no ``chunk`` is threaded in, the trace-time fallback reads the
    typed flag ONLY (never the measured-cost store — no host store
    access from inside a trace); entry points that want the autotuned
    pick resolve :func:`_cm_row_chunk` outside jit and pass it down as
    a static argument."""
    if chunk:
        row_chunk = chunk
    else:
        from torcheval_tpu.ops import _flags as _oflags

        row_chunk = _oflags.cm_row_chunk()
    n = t.shape[0]
    if n <= row_chunk:
        return _onehot_cm_block(t, p, width, mask)
    chunks = -(-n // row_chunk)
    pad = chunks * row_chunk - n
    if pad:
        t = jnp.concatenate([t, jnp.full(pad, width, t.dtype)])
        p = jnp.concatenate([p, jnp.full(pad, width, p.dtype)])
        if mask is not None:
            mask = jnp.concatenate([mask, jnp.zeros(pad, mask.dtype)])
    tc = t.reshape(chunks, row_chunk)
    pc = p.reshape(chunks, row_chunk)
    mc = None if mask is None else mask.reshape(chunks, row_chunk)

    def body(i, acc):
        m_i = None if mc is None else mc[i]
        return acc + _onehot_cm_block(tc[i], pc[i], width, m_i)

    return jax.lax.fori_loop(
        0, chunks, body, jnp.zeros((width, width), jnp.float32)
    )


def _wrap_labels(x: jax.Array, num_classes: int) -> jax.Array:
    # Normalize numpy-style negative wrap-around up front so the matmul
    # and scatter formulations agree bit-for-bit even on out-of-range
    # labels under skip_value_checks: [-C, 0) wraps (what .at[] would do).
    # Anything still negative after the single wrap maps to the OOB
    # sentinel ``num_classes`` so BOTH paths drop it — the raw scatter
    # would otherwise wrap a second time and count labels in [-2C, -C).
    x = jnp.where(x < 0, x + num_classes, x)
    return jnp.where(x < 0, num_classes, x)


@partial(jax.jit, static_argnames=("num_classes", "route", "row_chunk"))
def _confusion_matrix_update_kernel(
    input: jax.Array,
    target: jax.Array,
    num_classes: int,
    route: str = "scatter",
    mask: Optional[jax.Array] = None,
    row_chunk: int = 0,
) -> jax.Array:
    if input.ndim == 2:
        input = jnp.argmax(input, axis=1)
    input = _wrap_labels(input, num_classes)
    target = _wrap_labels(target, num_classes)
    if mask is not None and route == "pallas":
        # The compaction kernel has no masked row path; the scatter is
        # bit-identical and adding a 0 is a no-op, so downgrade in-trace.
        route = "scatter"
    if route == "matmul":
        # row_chunk static (0 = read the flag at trace time) so a flag
        # flip retraces this program instead of reusing a stale chunk.
        return _matmul_cm(
            input, target, num_classes, mask=mask, chunk=row_chunk or None
        )
    if route == "pallas":
        from torcheval_tpu.ops.pallas_cm import confusion_slab

        slab = confusion_slab(
            jnp.minimum(target, num_classes),
            jnp.minimum(input, num_classes),
            num_classes=num_classes,
        )
        return slab[:num_classes, :num_classes].astype(jnp.int32)
    ones = (
        jnp.ones_like(target, dtype=jnp.int32)
        if mask is None
        else mask.astype(jnp.int32)
    )
    return (
        jnp.zeros((num_classes, num_classes), dtype=jnp.int32)
        .at[target, input]
        .add(ones, mode="drop")
    )


def _counts_route(input, num_classes, average) -> str:
    """Call-time route for the F1/precision/recall per-class count trio:
    the micro paths are scatter-free scalars, everything else follows the
    confusion-matrix route for its (N, C) shape."""
    if average == "micro" or num_classes is None:
        return "scatter"
    return _cm_route(num_classes, input.shape[0])


def _class_counts(
    pred: jax.Array,
    target: jax.Array,
    num_classes: int,
    route: str,
    interpret: bool = False,
    mask: Optional[jax.Array] = None,
    row_chunk: int = 0,
):
    """The per-class ``(num_tp, num_label, num_prediction)`` trio shared
    by F1 / precision / recall, through the same three-way route as the
    confusion matrix — ONE (C, C)-slab accumulation replaces the
    reference's three separate label scatters (reference
    ``f1_score.py:116-156``), which serialize on TPU (~7 ms each for 2^20
    samples).  The slab carries a sentinel row/column ``C`` so labels the
    scatters would drop stay accounted for in the marginals: a sample
    with an out-of-range prediction still counts in ``num_label`` and
    vice versa.  All three routes are bit-identical on the same defined
    OOB semantics as the confusion matrix itself (``_wrap_labels``):
    labels wrap numpy-style first and correctness is wrapped equality —
    so ``num_tp`` equals the diagonal of the metric's own confusion
    matrix even for ``(-1, C-1)``-style pairs reachable only under
    ``skip_value_checks``/tracing (the reference's torch scatters simply
    crash there).  ``pred`` must already be 1-D labels."""
    t = jnp.minimum(_wrap_labels(target, num_classes), num_classes)
    p = jnp.minimum(_wrap_labels(pred, num_classes), num_classes)
    c = num_classes
    if mask is not None and route == "pallas":
        route = "scatter"  # no masked-row path in the compaction kernel
    if route == "scatter":
        ones = (
            jnp.ones_like(t, dtype=jnp.int32)
            if mask is None
            else mask.astype(jnp.int32)
        )
        correct = ((t == p) & (t < c)).astype(jnp.int32) * ones
        num_label = jnp.zeros(c, jnp.int32).at[t].add(ones, mode="drop")
        num_prediction = jnp.zeros(c, jnp.int32).at[p].add(ones, mode="drop")
        num_tp = jnp.zeros(c, jnp.int32).at[t].add(correct, mode="drop")
        return num_tp, num_label, num_prediction
    if route == "pallas":
        from torcheval_tpu.ops.pallas_cm import confusion_slab

        slab = confusion_slab(
            t, p, num_classes=num_classes, interpret=interpret
        )
    else:  # matmul over the (C+1)-wide sentinel window
        slab = _onehot_cm(t, p, num_classes + 1, mask=mask, chunk=row_chunk or None)
    num_label = jnp.sum(slab[:c, :], axis=1).astype(jnp.int32)
    num_prediction = jnp.sum(slab[:, :c], axis=0).astype(jnp.int32)
    num_tp = jnp.diagonal(slab[:c, :c]).astype(jnp.int32)
    return num_tp, num_label, num_prediction


def _binary_confusion_matrix_validate(input: jax.Array, target: jax.Array) -> None:
    _binary_confusion_matrix_input_check(input, target)
    # OOB targets must raise — the XLA scatter would silently drop them
    # where torch ``scatter_`` errors.  (Skipped when tracing: data-
    # dependent checks cannot run at trace time.)
    if target.size and all_concrete(target) and value_checks_enabled():
        t_min, t_max = bounds(target)
        if t_min < 0 or t_max >= 2:
            raise ValueError(
                "Got `target` class which is larger than the number of classes, "
                "num_classes: 2 must be strictly greater than max target: "
                f"{int(t_max)}."
            )


@partial(jax.jit, static_argnames=("threshold", "use_matmul", "row_chunk"))
def _binary_confusion_matrix_update_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: float,
    use_matmul: bool = False,
    mask: Optional[jax.Array] = None,
    row_chunk: int = 0,
) -> jax.Array:
    pred = jnp.where(input < threshold, 0, 1)
    return _confusion_matrix_update_kernel(
        pred,
        target.astype(jnp.int32),
        2,
        "matmul" if use_matmul else "scatter",
        mask=mask,
        row_chunk=row_chunk,
    )


def _binary_confusion_matrix_update(
    input: jax.Array, target: jax.Array, threshold: float
) -> jax.Array:
    _binary_confusion_matrix_validate(input, target)
    use_matmul = _use_matmul_cm(2, input.shape[0])
    return _binary_confusion_matrix_update_kernel(
        input,
        target,
        threshold,
        use_matmul,
        row_chunk=_cm_row_chunk() if use_matmul else 0,
    )


def _confusion_matrix_compute(
    confusion_matrix: jax.Array, normalize: Optional[str]
) -> jax.Array:
    """Normalize over predictions (columns), true labels (rows), or all
    (reference ``confusion_matrix.py:195-207``: ``pred`` → L1 along dim 0,
    ``true`` → along dim 1)."""
    if normalize == "pred":
        return _normalize_cm(confusion_matrix, 0)
    elif normalize == "true":
        return _normalize_cm(confusion_matrix, 1)
    elif normalize == "all":
        return _normalize_cm(confusion_matrix, None)
    return confusion_matrix


@partial(jax.jit, static_argnames=("axis",))
def _normalize_cm(cm: jax.Array, axis: Optional[int]) -> jax.Array:
    cm = cm.astype(jnp.float32)
    if axis is None:
        return cm / jnp.sum(cm)
    # eps-clamped like torch.nn.functional.normalize (zero rows/cols -> 0)
    return cm / jnp.maximum(jnp.sum(cm, axis=axis, keepdims=True), 1e-12)


def _confusion_matrix_param_check(
    num_classes: int, normalize: Optional[str]
) -> None:
    if num_classes < 2:
        raise ValueError("Must be at least two classes for confusion matrix")
    if (normalize is not None) and (normalize not in ["all", "pred", "true", "none"]):
        raise ValueError("normalize must be one of 'all', 'pred', 'true', or 'none'.")


def _confusion_matrix_update_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int]
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if not input.ndim == 1:
        if not (input.ndim == 2 and (input.shape[1] == num_classes)):
            raise ValueError(
                "input should have shape of (num_sample,) or (num_sample, num_classes), "
                f"got {input.shape}."
            )
    # Range checks: all requested bounds in one fused dispatch — a check is
    # one device round trip, not one per bound.  Traced arrays are skipped
    # individually (their values don't exist at trace time); a concrete
    # array alongside a traced one keeps its eager raise behavior.  The
    # eager check order (input first, then target) is preserved.
    if not value_checks_enabled():
        return
    to_check = []
    if input.ndim == 1 and all_concrete(input):
        to_check.append(("input", input))
    if all_concrete(target):
        to_check.append(("target", target))
    if not to_check:
        return
    vals = bounds(*(v for _, v in to_check))
    for i, (name, _) in enumerate(to_check):
        lo, hi = vals[2 * i], vals[2 * i + 1]
        if name == "input":
            if hi >= num_classes:
                raise ValueError(
                    "Got `input` prediction class which is too large for the number of classes, "
                    f"num_classes: {num_classes} must be strictly greater than max "
                    f"class predicted: {int(hi)}."
                )
            if lo < 0:
                raise ValueError(
                    f"Got negative `input` prediction class {int(lo)}."
                )
        else:
            if hi >= num_classes:
                raise ValueError(
                    "Got `target` class which is larger than the number of classes, "
                    f"num_classes: {num_classes} must be strictly greater than max "
                    f"target: {int(hi)}."
                )
            if lo < 0:
                raise ValueError(f"Got negative `target` class {int(lo)}.")


def _binary_confusion_matrix_input_check(input: jax.Array, target: jax.Array) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same dimensions, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
