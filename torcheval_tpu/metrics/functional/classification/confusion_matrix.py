"""Confusion matrix — parity with reference
``torcheval/metrics/functional/classification/confusion_matrix.py`` (280 LoC).

TPU-first: where the reference builds a sparse COO tensor and densifies it
(reference ``confusion_matrix.py:217-232``), the update here dispatches
between ONE MXU matmul of one-hot encodings (``cm = onehot(target)ᵀ @
onehot(pred)``, up to 207× the scatter at small C — see ``_use_matmul_cm``
for the measured crossover) and a single scatter-add ``zeros((C,
C)).at[target, pred].add(1)`` for large C.  The dead
``_binary_confusion_matrix_compute`` with swapped normalization dims
(reference ``confusion_matrix.py:150-160``) is intentionally not
reproduced (SURVEY §7 hard part 7)."""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional._host_checks import (
    all_concrete,
    bounds,
    value_checks_enabled,
)


def binary_confusion_matrix(
    input,
    target,
    *,
    threshold: float = 0.5,
    normalize: Optional[str] = None,
) -> jax.Array:
    """2×2 confusion matrix of thresholded predictions
    (reference ``confusion_matrix.py:14-64``)."""
    _confusion_matrix_param_check(2, normalize)
    input, target = jnp.asarray(input), jnp.asarray(target)
    matrix = _binary_confusion_matrix_update(input, target, threshold)
    return _confusion_matrix_compute(matrix, normalize)


def multiclass_confusion_matrix(
    input,
    target,
    num_classes: int,
    *,
    normalize: Optional[str] = None,
) -> jax.Array:
    """C×C matrix; entry (i, j) counts true class i predicted as j
    (reference ``confusion_matrix.py:67-147``)."""
    _confusion_matrix_param_check(num_classes, normalize)
    input, target = jnp.asarray(input), jnp.asarray(target)
    cm = _confusion_matrix_update(input, target, num_classes)
    return _confusion_matrix_compute(cm, normalize)


def _confusion_matrix_update(
    input: jax.Array, target: jax.Array, num_classes: int
) -> jax.Array:
    _confusion_matrix_update_input_check(input, target, num_classes)
    use_matmul = _use_matmul_cm(num_classes, input.shape[0])
    return _confusion_matrix_update_kernel(input, target, num_classes, use_matmul)


def _use_matmul_cm(num_classes: int, num_samples: int) -> bool:
    """Route the (C, C) accumulation through one MXU matmul of one-hot
    encodings on TPU for small/medium C.  TPU scatters serialize (~1
    element/cycle: flat ~7 ms for 2^20 samples at ANY C) while the matmul
    costs n·C² MACs.  Measured on v5e (2^20 samples, device-loop clock):

        C=16   scatter 9.3 ms   matmul 0.045 ms   207x
        C=64   scatter 7.1 ms   matmul 0.12 ms     59x
        C=128  scatter 7.1 ms   matmul 3.4 ms     2.1x
        C=512  scatter 7.1 ms   matmul 4.4 ms     1.6x
        C=1000 scatter 7.1 ms   matmul 11.1 ms   0.64x

    f32 accumulation bounds the exact count range to 2^24 per cell, and
    the two (n, C) bf16 one-hots bound memory — n·C over 2^28 (≈1 GiB of
    one-hots) keeps the O(n)-memory scatter.

    Called OUTSIDE jit (the ``_select_binned_route`` pattern) and passed
    into the kernel as a static argument, so the
    ``TORCHEVAL_TPU_DISABLE_PALLAS`` kill-switch is honored at call time
    rather than frozen into the first compilation per shape."""
    from torcheval_tpu.ops._flags import pallas_disabled

    if pallas_disabled():
        # Same kill-switch as the kernels: force the reference formulation.
        return False
    if num_classes > 512 or num_samples >= 2**24:
        return False
    if num_samples * num_classes > 2**28:
        return False
    return jax.default_backend() == "tpu"


def _matmul_cm(
    input: jax.Array, target: jax.Array, num_classes: int
) -> jax.Array:
    """(C, C) counts as ONE MXU matmul of one-hot encodings: cm =
    onehot(target)ᵀ @ onehot(pred).  0/1 one-hots are exact in bf16 and
    the f32 accumulation is exact below 2^24 per cell, so the result is
    bit-identical to the scatter formulation within the dispatch
    bounds."""
    classes = jnp.arange(num_classes)
    oh_true = (target[:, None] == classes[None, :]).astype(jnp.bfloat16)
    oh_pred = (input[:, None] == classes[None, :]).astype(jnp.bfloat16)
    cm = jax.lax.dot_general(
        oh_true,
        oh_pred,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return cm.astype(jnp.int32)


def _wrap_labels(x: jax.Array, num_classes: int) -> jax.Array:
    # Normalize numpy-style negative wrap-around up front so the matmul
    # and scatter formulations agree bit-for-bit even on out-of-range
    # labels under skip_value_checks: [-C, 0) wraps (what .at[] would do).
    # Anything still negative after the single wrap maps to the OOB
    # sentinel ``num_classes`` so BOTH paths drop it — the raw scatter
    # would otherwise wrap a second time and count labels in [-2C, -C).
    x = jnp.where(x < 0, x + num_classes, x)
    return jnp.where(x < 0, num_classes, x)


@partial(jax.jit, static_argnames=("num_classes", "use_matmul"))
def _confusion_matrix_update_kernel(
    input: jax.Array,
    target: jax.Array,
    num_classes: int,
    use_matmul: bool = False,
) -> jax.Array:
    if input.ndim == 2:
        input = jnp.argmax(input, axis=1)
    input = _wrap_labels(input, num_classes)
    target = _wrap_labels(target, num_classes)
    if use_matmul:
        return _matmul_cm(input, target, num_classes)
    return (
        jnp.zeros((num_classes, num_classes), dtype=jnp.int32)
        .at[target, input]
        .add(1, mode="drop")
    )


def _binary_confusion_matrix_validate(input: jax.Array, target: jax.Array) -> None:
    _binary_confusion_matrix_input_check(input, target)
    # OOB targets must raise — the XLA scatter would silently drop them
    # where torch ``scatter_`` errors.  (Skipped when tracing: data-
    # dependent checks cannot run at trace time.)
    if target.size and all_concrete(target) and value_checks_enabled():
        t_min, t_max = bounds(target)
        if t_min < 0 or t_max >= 2:
            raise ValueError(
                "Got `target` class which is larger than the number of classes, "
                "num_classes: 2 must be strictly greater than max target: "
                f"{int(t_max)}."
            )


@partial(jax.jit, static_argnames=("threshold", "use_matmul"))
def _binary_confusion_matrix_update_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: float,
    use_matmul: bool = False,
) -> jax.Array:
    pred = jnp.where(input < threshold, 0, 1)
    return _confusion_matrix_update_kernel(
        pred, target.astype(jnp.int32), 2, use_matmul
    )


def _binary_confusion_matrix_update(
    input: jax.Array, target: jax.Array, threshold: float
) -> jax.Array:
    _binary_confusion_matrix_validate(input, target)
    use_matmul = _use_matmul_cm(2, input.shape[0])
    return _binary_confusion_matrix_update_kernel(
        input, target, threshold, use_matmul
    )


def _confusion_matrix_compute(
    confusion_matrix: jax.Array, normalize: Optional[str]
) -> jax.Array:
    """Normalize over predictions (columns), true labels (rows), or all
    (reference ``confusion_matrix.py:195-207``: ``pred`` → L1 along dim 0,
    ``true`` → along dim 1)."""
    if normalize == "pred":
        return _normalize_cm(confusion_matrix, 0)
    elif normalize == "true":
        return _normalize_cm(confusion_matrix, 1)
    elif normalize == "all":
        return _normalize_cm(confusion_matrix, None)
    return confusion_matrix


@partial(jax.jit, static_argnames=("axis",))
def _normalize_cm(cm: jax.Array, axis: Optional[int]) -> jax.Array:
    cm = cm.astype(jnp.float32)
    if axis is None:
        return cm / jnp.sum(cm)
    # eps-clamped like torch.nn.functional.normalize (zero rows/cols -> 0)
    return cm / jnp.maximum(jnp.sum(cm, axis=axis, keepdims=True), 1e-12)


def _confusion_matrix_param_check(
    num_classes: int, normalize: Optional[str]
) -> None:
    if num_classes < 2:
        raise ValueError("Must be at least two classes for confusion matrix")
    if (normalize is not None) and (normalize not in ["all", "pred", "true", "none"]):
        raise ValueError("normalize must be one of 'all', 'pred', 'true', or 'none'.")


def _confusion_matrix_update_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int]
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if not input.ndim == 1:
        if not (input.ndim == 2 and (input.shape[1] == num_classes)):
            raise ValueError(
                "input should have shape of (num_sample,) or (num_sample, num_classes), "
                f"got {input.shape}."
            )
    # Range checks: all requested bounds in one fused dispatch — a check is
    # one device round trip, not one per bound.  Traced arrays are skipped
    # individually (their values don't exist at trace time); a concrete
    # array alongside a traced one keeps its eager raise behavior.  The
    # eager check order (input first, then target) is preserved.
    if not value_checks_enabled():
        return
    to_check = []
    if input.ndim == 1 and all_concrete(input):
        to_check.append(("input", input))
    if all_concrete(target):
        to_check.append(("target", target))
    if not to_check:
        return
    vals = bounds(*(v for _, v in to_check))
    for i, (name, _) in enumerate(to_check):
        lo, hi = vals[2 * i], vals[2 * i + 1]
        if name == "input":
            if hi >= num_classes:
                raise ValueError(
                    "Got `input` prediction class which is too large for the number of classes, "
                    f"num_classes: {num_classes} must be strictly greater than max "
                    f"class predicted: {int(hi)}."
                )
            if lo < 0:
                raise ValueError(
                    f"Got negative `input` prediction class {int(lo)}."
                )
        else:
            if hi >= num_classes:
                raise ValueError(
                    "Got `target` class which is larger than the number of classes, "
                    f"num_classes: {num_classes} must be strictly greater than max "
                    f"target: {int(hi)}."
                )
            if lo < 0:
                raise ValueError(f"Got negative `target` class {int(lo)}.")


def _binary_confusion_matrix_input_check(input: jax.Array, target: jax.Array) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same dimensions, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
