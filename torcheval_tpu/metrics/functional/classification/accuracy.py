"""Accuracy family — functional kernels.

Capability parity with reference
``torcheval/metrics/functional/classification/accuracy.py`` (488 LoC):
``binary_accuracy``, ``multiclass_accuracy``, ``multilabel_accuracy``,
``topk_multilabel_accuracy``, with the same update/compute sufficient-statistic
split (counters mergeable by addition).

TPU-first notes
---------------
* The hot paths (``_*_update`` / ``_accuracy_compute``) are ``jax.jit``
  kernels with static hyper-params — the analog of the reference's
  ``@torch.jit.script`` sites (reference ``accuracy.py:277-287,399-432``).
* Per-class counters use ``zeros(C).at[target].add(mask)`` which XLA lowers
  to an efficient one-pass scatter-add (reference uses ``Tensor.scatter_``,
  ``accuracy.py:271-273``).
* Divergence from reference (documented): the reference's top-k multilabel
  update hardcodes ``topk(k=2)`` regardless of the ``k`` argument
  (reference ``accuracy.py:393-395``); we honor ``k``.
"""

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional._host_checks import check_index_ranges


# ---------------------------------------------------------------- public API


def binary_accuracy(
    input,
    target,
    *,
    threshold: float = 0.5,
) -> jax.Array:
    """Frequency of thresholded ``input`` matching ``target``.

    Parity: reference ``accuracy.py:13-45``. ``where(input < threshold, 0, 1)``
    is applied to ``input``; both arrays must be shape ``(n_samples,)``.
    """
    input, target = jnp.asarray(input), jnp.asarray(target)
    num_correct, num_total = _binary_accuracy_update(input, target, threshold)
    return _accuracy_compute(num_correct, num_total, "micro")


def multiclass_accuracy(
    input,
    target,
    *,
    average: Optional[str] = "micro",
    num_classes: Optional[int] = None,
    k: int = 1,
) -> jax.Array:
    """Multiclass accuracy with micro/macro/None averaging and top-k support.

    Parity: reference ``accuracy.py:48-103``. ``input`` is either predicted
    labels ``(n,)`` or scores/logits ``(n, C)``; for ``k > 1`` a sample counts
    as correct when strictly fewer than ``k`` classes outscore the target
    class. ``macro`` ignores classes with zero true instances; ``None``
    returns per-class accuracy with NaN for unseen classes.
    """
    _accuracy_param_check(average, num_classes, k)
    input, target = jnp.asarray(input), jnp.asarray(target)
    num_correct, num_total = _multiclass_accuracy_update(
        input, target, average, num_classes, k
    )
    return _accuracy_compute(num_correct, num_total, average)


def multilabel_accuracy(
    input,
    target,
    *,
    threshold: float = 0.5,
    criteria: str = "exact_match",
) -> jax.Array:
    """Multilabel accuracy under one of five match criteria.

    Parity: reference ``accuracy.py:106-173``. Criteria: ``exact_match``
    (subset accuracy), ``hamming``, ``overlap``, ``contain``, ``belong``.
    """
    _multilabel_accuracy_param_check(criteria)
    input, target = jnp.asarray(input), jnp.asarray(target)
    num_correct, num_total = _multilabel_accuracy_update(
        input, target, threshold, criteria
    )
    return _accuracy_compute(num_correct, num_total, "micro")


def topk_multilabel_accuracy(
    input,
    target,
    *,
    criteria: str = "exact_match",
    k: int = 2,
) -> jax.Array:
    """Multilabel accuracy of the top-k predicted label set.

    Parity: reference ``accuracy.py:176-243`` — except that the reference
    hardcodes ``topk(k=2)`` (reference ``accuracy.py:393-395``, a bug); this
    implementation honors ``k``.
    """
    _topk_multilabel_accuracy_param_check(criteria, k)
    input, target = jnp.asarray(input), jnp.asarray(target)
    num_correct, num_total = _topk_multilabel_accuracy_update(
        input, target, criteria, k
    )
    return _accuracy_compute(num_correct, num_total, "micro")


# ------------------------------------------------------------------- kernels


@partial(jax.jit, static_argnames=("average", "num_classes", "k"))
def _multiclass_accuracy_update_kernel(
    input: jax.Array,
    target: jax.Array,
    average: Optional[str],
    num_classes: Optional[int],
    k: int,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    if k == 1:
        if input.ndim == 2:
            input = jnp.argmax(input, axis=1)
        correct = (input == target).astype(jnp.int32)
    else:
        y_score = jnp.take_along_axis(input, target[:, None], axis=-1)
        rank = jnp.sum(input > y_score, axis=-1)
        correct = (rank < k).astype(jnp.float32)

    if mask is not None:
        # Padded rows contribute exact zeros: 0*correct to the numerator,
        # 0 to every per-class total (scatter-add of a 0 is a no-op).
        correct = correct * mask.astype(correct.dtype)
    if average == "micro":
        total = (
            jnp.asarray(target.shape[0])
            if mask is None
            else mask.astype(target.dtype).sum()
        )
        return correct.sum(), total

    num_correct = (
        jnp.zeros(num_classes, dtype=correct.dtype).at[target].add(correct)
    )
    ones = (
        jnp.ones_like(target) if mask is None else mask.astype(target.dtype)
    )
    num_total = jnp.zeros(num_classes, dtype=target.dtype).at[target].add(ones)
    return num_correct, num_total


def _multiclass_accuracy_validate(
    input: jax.Array,
    target: jax.Array,
    average: Optional[str],
    num_classes: Optional[int],
    k: int,
) -> None:
    """Host-side update validation shared by the functional and class paths."""
    _accuracy_update_input_check(input, target, num_classes, k)
    # Whenever target is used as an index (per-class scatter for
    # average!="micro", gather for k>1) an out-of-range value must raise:
    # XLA silently drops/clamps OOB indices where torch scatter_/gather error.
    if average != "micro" or k > 1:
        upper = num_classes if num_classes is not None else input.shape[-1]
        check_index_ranges([(target, "target")], upper)


def _multiclass_accuracy_update(
    input: jax.Array,
    target: jax.Array,
    average: Optional[str],
    num_classes: Optional[int],
    k: int,
) -> Tuple[jax.Array, jax.Array]:
    _multiclass_accuracy_validate(input, target, average, num_classes, k)
    return _multiclass_accuracy_update_kernel(input, target, average, num_classes, k)


@jax.jit
def _accuracy_compute_macro(num_correct: jax.Array, num_total: jax.Array) -> jax.Array:
    # Mean over classes with >0 true instances, shape-stably: NaN-mask then
    # nanmean (reference masks with boolean indexing, ``accuracy.py:283-285``).
    ratio = jnp.where(num_total != 0, num_correct / num_total, jnp.nan)
    return jnp.nanmean(ratio)


@jax.jit
def _accuracy_compute_ratio(num_correct: jax.Array, num_total: jax.Array) -> jax.Array:
    return num_correct / num_total


def _accuracy_compute(
    num_correct: jax.Array,
    num_total: jax.Array,
    average: Optional[str],
) -> jax.Array:
    if average == "macro":
        return _accuracy_compute_macro(num_correct, num_total)
    return _accuracy_compute_ratio(num_correct, num_total)


@partial(jax.jit, static_argnames=("threshold",))
def _binary_accuracy_update_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: float,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    pred = jnp.where(input < threshold, 0, 1)
    correct = (pred == target).astype(jnp.int32)
    if mask is None:
        return correct.sum(), jnp.asarray(target.shape[0])
    m = mask.astype(jnp.int32)
    return (correct * m).sum(), m.sum()


def _binary_accuracy_update(
    input: jax.Array, target: jax.Array, threshold: float = 0.5
) -> Tuple[jax.Array, jax.Array]:
    _binary_accuracy_update_input_check(input, target)
    return _binary_accuracy_update_kernel(input, target, threshold)


@partial(jax.jit, static_argnames=("criteria",))
def _multilabel_update(
    input: jax.Array,
    target: jax.Array,
    criteria: str = "exact_match",
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Shared top of the multilabel criteria lattice
    (reference ``accuracy.py:399-432``).  ``mask`` zeroes padded rows'
    contribution to both counters (hamming counts per-element, so its
    total is ``mask.sum() * num_labels``)."""
    if mask is None:
        n = jnp.asarray(target.shape[0])
        per_row = jnp.ones(target.shape[0], dtype=jnp.int32)
    else:
        per_row = mask.astype(jnp.int32)
        n = per_row.sum()
    if criteria == "exact_match":
        return (jnp.all(input == target, axis=1) * per_row).sum(), n
    if criteria == "hamming":
        eq = (input == target).astype(jnp.int32)
        return (eq * per_row[:, None]).sum(), n * target.shape[1]
    if criteria == "overlap":
        hit = jnp.max(jnp.logical_and(input == target, input == 1), axis=1)
        empty = jnp.all(jnp.logical_and(input == 0, target == 0), axis=1)
        return (hit * per_row).sum() + (empty * per_row).sum(), n
    if criteria == "contain":
        return (jnp.all((input - target) >= 0, axis=1) * per_row).sum(), n
    # belong
    return (jnp.all((input - target) <= 0, axis=1) * per_row).sum(), n


@partial(jax.jit, static_argnames=("threshold", "criteria"))
def _multilabel_accuracy_update_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: float,
    criteria: str,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    input_label = jnp.where(input < threshold, 0, 1)
    return _multilabel_update(input_label, target, criteria, mask=mask)


def _multilabel_accuracy_update(
    input: jax.Array,
    target: jax.Array,
    threshold: float = 0.5,
    criteria: str = "exact_match",
) -> Tuple[jax.Array, jax.Array]:
    _multilabel_accuracy_update_input_check(input, target)
    return _multilabel_accuracy_update_kernel(input, target, threshold, criteria)


@partial(jax.jit, static_argnames=("criteria", "k"))
def _topk_multilabel_accuracy_update_kernel(
    input: jax.Array,
    target: jax.Array,
    criteria: str,
    k: int,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    _, topk_idx = jax.lax.top_k(input, k)
    input_label = jnp.zeros(input.shape, dtype=jnp.float32).at[
        jnp.arange(input.shape[0])[:, None], topk_idx
    ].set(1.0)
    return _multilabel_update(input_label, target, criteria, mask=mask)


def _topk_multilabel_accuracy_update(
    input: jax.Array,
    target: jax.Array,
    criteria: str = "exact_match",
    k: int = 2,
) -> Tuple[jax.Array, jax.Array]:
    _topk_multilabel_accuracy_update_input_check(input, target, k)
    return _topk_multilabel_accuracy_update_kernel(input, target, criteria, k)


# ------------------------------------------------------------------- checks


def _accuracy_param_check(
    average: Optional[str],
    num_classes: Optional[int],
    k: int,
) -> None:
    average_options = ("micro", "macro", "none", None)
    if average not in average_options:
        raise ValueError(
            f"`average` was not in the allowed value of {average_options}, got {average}."
        )
    if average != "micro" and (num_classes is None or num_classes <= 0):
        raise ValueError(
            f"num_classes should be a positive number when average={average}."
            f" Got num_classes={num_classes}."
        )
    if type(k) is not int:
        raise TypeError(f"Expected `k` to be an integer, but {type(k)} was provided.")
    if k < 1:
        raise ValueError(
            f"Expected `k` to be an integer greater than 0, but {k} was provided."
        )


def _accuracy_update_input_check(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    k: int,
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if k > 1 and input.ndim != 2:
        raise ValueError(
            "input should have shape (num_sample, num_classes) for k > 1, "
            f"got shape {input.shape}."
        )
    if not input.ndim == 1 and not (
        input.ndim == 2 and (num_classes is None or input.shape[1] == num_classes)
    ):
        raise ValueError(
            "input should have shape of (num_sample,) or (num_sample, num_classes), "
            f"got {input.shape}."
        )


def _binary_accuracy_update_input_check(
    input: jax.Array,
    target: jax.Array,
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same dimensions, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )


def _multilabel_accuracy_param_check(criteria: str) -> None:
    criteria_options = ("exact_match", "hamming", "overlap", "contain", "belong")
    if criteria not in criteria_options:
        raise ValueError(
            f"`criteria` was not in the allowed value of {criteria_options}, got {criteria}."
        )


def _topk_multilabel_accuracy_param_check(criteria: str, k: int) -> None:
    _multilabel_accuracy_param_check(criteria)
    if type(k) is not int:
        raise TypeError(f"Expected `k` to be an integer, but {type(k)} was provided.")
    if k == 1:
        raise ValueError(
            f"Expected `k` to be an integer greater than 1, but {k} was provided. "
            "In such case, please use multilabel_accuracy metric."
        )
    if k < 1:
        raise ValueError(
            f"Expected `k` to be an integer greater than 1, but {k} was provided."
        )


def _multilabel_accuracy_update_input_check(
    input: jax.Array,
    target: jax.Array,
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same dimensions, "
            f"got shapes {input.shape} and {target.shape}."
        )


def _topk_multilabel_accuracy_update_input_check(
    input: jax.Array,
    target: jax.Array,
    k: int,
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same dimensions, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if input.ndim != 2:
        raise ValueError(
            "input should have shape (num_sample, num_classes) for k > 1, "
            f"got shape {input.shape}."
        )
