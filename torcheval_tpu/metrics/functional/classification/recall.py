"""Recall — parity with reference
``torcheval/metrics/functional/classification/recall.py`` (247 LoC).

Sufficient statistics: ``num_tp`` / ``num_labels`` / ``num_predictions``.

Divergence (documented): for macro/weighted averages with classes absent
from both input and target, the reference masks ``num_tp`` by boolean
indexing but forgets to mask ``num_labels`` (reference ``recall.py:169-180``),
which crashes on a shape mismatch whenever any class is actually masked.
This implementation computes the intended statistic shape-stably (identical
result when no class is masked, working result instead of a crash otherwise).
"""

import logging
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
    _class_counts,
    _counts_route,
)
from torcheval_tpu.metrics.functional._host_checks import all_concrete
from torcheval_tpu.metrics.functional.classification.precision import (
    _check_index_ranges,
)

_logger = logging.getLogger(__name__)


def binary_recall(input, target, *, threshold: float = 0.5) -> jax.Array:
    """TP / #positive-labels after thresholding (reference ``recall.py:13-46``)."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    num_tp, num_true_labels = _binary_recall_update(input, target, threshold)
    return _binary_recall_compute(num_tp, num_true_labels)


def _binary_recall_compute(num_tp: jax.Array, num_true_labels: jax.Array) -> jax.Array:
    """NaN (no positive labels) → 0 with a warning
    (reference ``recall.py:64-77``)."""
    recall = num_tp / num_true_labels
    if all_concrete(recall) and bool(jnp.isnan(recall)):
        _logger.warning(
            "No positive instances have been seen in target. Recall is "
            "converted from NaN to 0s."
        )
    # NaN→0 applies in eager AND traced modes (only the warning is
    # concrete-only); nan_to_num is the identity on non-NaN values.
    return jnp.nan_to_num(recall)


def multiclass_recall(
    input,
    target,
    *,
    num_classes: Optional[int] = None,
    average: Optional[str] = "micro",
) -> jax.Array:
    """Multiclass recall with micro/macro/weighted/None averaging
    (reference ``recall.py:95-151``)."""
    _recall_param_check(num_classes, average)
    input, target = jnp.asarray(input), jnp.asarray(target)
    num_tp, num_labels, num_predictions = _recall_update(
        input, target, num_classes, average
    )
    return _recall_compute(num_tp, num_labels, num_predictions, average)


def _recall_update(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    _recall_validate(input, target, num_classes, average)
    return _recall_update_kernel(
        input,
        target,
        num_classes,
        average,
        _counts_route(input, num_classes, average),
    )


def _recall_validate(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
) -> None:
    """Host-side update validation shared by the functional and class paths."""
    _recall_update_input_check(input, target, num_classes)
    if average != "micro":
        pairs = [(target, "target")]
        if input.ndim == 1:
            pairs.append((input, "input"))
        _check_index_ranges(pairs, num_classes)


@partial(jax.jit, static_argnames=("num_classes", "average", "route"))
def _recall_update_kernel(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
    route: str = "scatter",
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    if input.ndim == 2:
        input = jnp.argmax(input, axis=1)
    if average == "micro":
        if mask is None:
            num_tp = (input == target).sum()
            num_labels = jnp.asarray(target.size)
        else:
            m = mask.astype(jnp.int32)
            num_tp = ((input == target).astype(jnp.int32) * m).sum()
            num_labels = m.sum()
        return num_tp, num_labels, num_labels
    # ONE routed (C, C)-slab accumulation instead of three label
    # scatters (each serializes on TPU) — see _class_counts.
    return _class_counts(input, target, num_classes, route, mask=mask)


def _recall_compute(
    num_tp: jax.Array,
    num_labels: jax.Array,
    num_predictions: jax.Array,
    average: Optional[str],
) -> jax.Array:
    if num_tp.ndim and all_concrete(num_labels):
        # numpy, not jnp: under an ambient trace even ops on concrete
        # arrays are staged, and a staged bool() would crash the trace.
        nan_mask = np.asarray(num_labels) == 0
        if nan_mask.any():
            nan_classes = [int(i) for i in np.nonzero(nan_mask)[0]]
            _logger.warning(
                f"One or more NaNs identified, as no ground-truth instances of "
                f"{nan_classes} have been seen. These have been converted to zero."
            )
    return _recall_compute_kernel(num_tp, num_labels, num_predictions, average)


@partial(jax.jit, static_argnames=("average",))
def _recall_compute_kernel(
    num_tp: jax.Array,
    num_labels: jax.Array,
    num_predictions: jax.Array,
    average: Optional[str],
) -> jax.Array:
    recall = jnp.nan_to_num(num_tp / num_labels)
    if average == "micro" or average is None:
        return recall
    # macro/weighted ignore classes with no samples in target and input
    mask = (num_labels != 0) | (num_predictions != 0)
    if average == "macro":
        return jnp.sum(jnp.where(mask, recall, 0.0)) / jnp.sum(mask)
    # weighted
    return jnp.sum(recall * num_labels) / jnp.sum(num_labels)


def _recall_param_check(num_classes: Optional[int], average: Optional[str]) -> None:
    average_options = ("micro", "macro", "weighted", None)
    if average not in average_options:
        raise ValueError(
            f"`average` was not in the allowed values of {average_options}, "
            f"got {average}."
        )
    if average != "micro" and (num_classes is None or num_classes <= 0):
        raise ValueError(
            f"`num_classes` should be a positive number when average={average}, "
            f"got num_classes={num_classes}."
        )


def _recall_update_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int]
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"`target` should be a one-dimensional tensor, got shape {target.shape}."
        )
    if input.ndim != 1 and not (
        input.ndim == 2 and (num_classes is None or input.shape[1] == num_classes)
    ):
        raise ValueError(
            "`input` should have shape (num_samples,) or (num_samples, num_classes), "
            f"got {input.shape}."
        )


def _binary_recall_update(
    input: jax.Array, target: jax.Array, threshold: float = 0.5
) -> Tuple[jax.Array, jax.Array]:
    _binary_recall_update_input_check(input, target)
    return _binary_recall_update_kernel(input, target, threshold)


@partial(jax.jit, static_argnames=("threshold",))
def _binary_recall_update_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: float,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    pred = jnp.where(input < threshold, 0, 1)
    target_b = target.astype(jnp.bool_)
    if mask is not None:
        target_b = target_b & mask.astype(jnp.bool_)
    num_tp = (pred.astype(jnp.bool_) & target_b).sum()
    num_true_labels = target_b.sum()
    return num_tp, num_true_labels


def _binary_recall_update_input_check(input: jax.Array, target: jax.Array) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same dimensions, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
