"""Recall at fixed precision — best reachable recall under a precision
floor, and the decision threshold that reaches it.

Beyond the v0.0.4 snapshot (upstream torcheval added
``binary_recall_at_fixed_precision`` / ``multilabel_recall_at_fixed_precision``
later).  Built on the exact PR-curve cores: the device kernel produces the
fixed-shape sorted tie-group counts; the arg-selection over curve points is
a host-side epilogue at the compute boundary (like the ragged curve
materialization it shares).

Semantics: over all PR-curve points with ``precision >= min_precision``,
return the maximum recall and the *largest* threshold attaining it (the
most conservative operating point at that recall).  When no threshold
satisfies the floor, returns ``(0.0, 1e6)`` — the sentinel upstream
torcheval uses for "no feasible threshold".
"""

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_update_input_check,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_update_input_check,
)

_NO_THRESHOLD = 1e6


def binary_recall_at_fixed_precision(
    input,
    target,
    *,
    min_precision: float,
) -> Tuple[jax.Array, jax.Array]:
    """(max recall, threshold) such that precision >= ``min_precision``."""
    _recall_at_fixed_precision_param_check(min_precision)
    input, target = jnp.asarray(input), jnp.asarray(target)
    _binary_precision_recall_curve_update_input_check(input, target)
    return _binary_recall_at_fixed_precision_compute(input, target, min_precision)


def multilabel_recall_at_fixed_precision(
    input,
    target,
    *,
    num_labels: Optional[int] = None,
    min_precision: float,
) -> Tuple[List[jax.Array], List[jax.Array]]:
    """Per-label ``(max recalls, thresholds)`` lists such that each label's
    precision >= ``min_precision``."""
    _recall_at_fixed_precision_param_check(min_precision)
    input, target = jnp.asarray(input), jnp.asarray(target)
    if num_labels is None and input.ndim == 2:
        num_labels = input.shape[1]
    _multilabel_precision_recall_curve_update_input_check(input, target, num_labels)
    return _multilabel_recall_at_fixed_precision_compute(
        input, target, num_labels, min_precision
    )


def _best_point(
    precision: np.ndarray,
    recall: np.ndarray,
    thresholds: np.ndarray,
    min_precision: float,
) -> Tuple[jax.Array, jax.Array]:
    """Select max recall under the precision floor from one curve.  The
    curve arrays carry the (1.0, 0.0) sentinel as their last point, which
    has no threshold — it only matters when nothing else qualifies, and
    then the sentinel result (0.0, _NO_THRESHOLD) is returned anyway."""
    precision, recall = precision[:-1], recall[:-1]
    ok = precision >= min_precision
    if not ok.any() or float(recall[ok].max()) == 0.0:
        return jnp.asarray(0.0), jnp.asarray(_NO_THRESHOLD)
    max_recall = recall[ok].max()
    at_max = ok & (recall == max_recall)
    return (
        jnp.asarray(np.float32(max_recall)),
        jnp.asarray(np.float32(thresholds[at_max].max())),
    )


def _binary_recall_at_fixed_precision_compute(
    input: jax.Array, target: jax.Array, min_precision: float
) -> Tuple[jax.Array, jax.Array]:
    precision, recall, thresholds = _binary_precision_recall_curve_compute(
        input, target
    )
    return _best_point(
        np.asarray(precision), np.asarray(recall), np.asarray(thresholds),
        min_precision,
    )


def _multilabel_recall_at_fixed_precision_compute(
    input: jax.Array,
    target: jax.Array,
    num_labels: Optional[int],
    min_precision: float,
) -> Tuple[List[jax.Array], List[jax.Array]]:
    precisions, recalls, thresholds = _multilabel_precision_recall_curve_compute(
        input, target, num_labels
    )
    best = [
        _best_point(np.asarray(p), np.asarray(r), np.asarray(t), min_precision)
        for p, r, t in zip(precisions, recalls, thresholds)
    ]
    return [b[0] for b in best], [b[1] for b in best]


def _recall_at_fixed_precision_param_check(min_precision: float) -> None:
    if not isinstance(min_precision, float) or not 0.0 <= min_precision <= 1.0:
        raise ValueError(
            "Expected min_precision to be a float in the [0, 1] range, but got "
            f"{min_precision}."
        )
