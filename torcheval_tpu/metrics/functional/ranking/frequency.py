"""frequency_at_k — parity with reference
``torcheval/metrics/functional/ranking/frequency.py`` (42 LoC)."""

import jax
import jax.numpy as jnp


def frequency_at_k(input, k: float) -> jax.Array:
    """Binary indicator of frequencies below ``k``
    (reference ``frequency.py:33``)."""
    input = jnp.asarray(input)
    _frequency_input_check(input, k)
    return (input < k).astype(jnp.float32)


def _frequency_input_check(input: jax.Array, k: float) -> None:
    if input.ndim != 1:
        raise ValueError(
            f"input should be a one-dimensional tensor, got shape {input.shape}."
        )
    if k < 0:
        raise ValueError(f"k should not be negative, got {k}.")
