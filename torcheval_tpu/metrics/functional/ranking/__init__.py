from torcheval_tpu.metrics.functional.ranking.weighted_calibration import (
    weighted_calibration,
)

__all__ = ["weighted_calibration"]
