from torcheval_tpu.metrics.functional.ranking.frequency import frequency_at_k
from torcheval_tpu.metrics.functional.ranking.hit_rate import hit_rate
from torcheval_tpu.metrics.functional.ranking.num_collisions import num_collisions
from torcheval_tpu.metrics.functional.ranking.reciprocal_rank import reciprocal_rank
from torcheval_tpu.metrics.functional.ranking.retrieval import (
    retrieval_precision,
    retrieval_recall,
)
from torcheval_tpu.metrics.functional.ranking.weighted_calibration import (
    weighted_calibration,
)

__all__ = [
    "frequency_at_k",
    "hit_rate",
    "num_collisions",
    "reciprocal_rank",
    "retrieval_precision",
    "retrieval_recall",
    "weighted_calibration",
]
