"""Weighted calibration — parity with reference
``torcheval/metrics/functional/ranking/weighted_calibration.py`` (112 LoC).

``Σ w·input / Σ w·target`` per task (reference
``weighted_calibration.py:62-93``); sufficient statistics are two per-task
sums, mergeable by addition."""

from typing import Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np


def weighted_calibration(
    input,
    target,
    weight: Union[float, int, "jax.Array"] = 1.0,
    *,
    num_tasks: int = 1,
) -> jax.Array:
    """Weighted calibration Σw·input / Σw·target
    (reference ``weighted_calibration.py:13-59``)."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    weighted_input_sum, weighted_target_sum = _weighted_calibration_update(
        input, target, weight, num_tasks=num_tasks
    )
    return weighted_input_sum / weighted_target_sum


@jax.jit
def _wc_scalar_kernel(
    input: jax.Array, target: jax.Array, weight
) -> Tuple[jax.Array, jax.Array]:
    return weight * jnp.sum(input, axis=-1), weight * jnp.sum(target, axis=-1)


@jax.jit
def _wc_array_kernel(
    input: jax.Array, target: jax.Array, weight: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    return jnp.sum(weight * input, axis=-1), jnp.sum(weight * target, axis=-1)


def _weighted_calibration_select_kernel(
    input: jax.Array,
    target: jax.Array,
    weight: Union[float, int, "jax.Array"],
    *,
    num_tasks: int,
):
    """Validate and pick the matching jitted kernel; returns
    ``(kernel, args)`` so callers can dispatch it directly or fused."""
    _weighted_calibration_input_check(input, target, weight, num_tasks=num_tasks)
    if isinstance(weight, (float, int)):
        return _wc_scalar_kernel, (input, target, float(weight))
    if isinstance(weight, (jax.Array, jnp.ndarray, np.ndarray)) and input.shape == jnp.shape(
        weight
    ):
        return _wc_array_kernel, (input, target, weight)
    raise ValueError(
        "Weight must be either a float value or a tensor that matches the "
        f"input tensor size. Got {weight} instead."
    )


def _weighted_calibration_update(
    input: jax.Array,
    target: jax.Array,
    weight: Union[float, int, "jax.Array"],
    *,
    num_tasks: int,
) -> Tuple[jax.Array, jax.Array]:
    kernel, args = _weighted_calibration_select_kernel(
        input, target, weight, num_tasks=num_tasks
    )
    return kernel(*args)


def _weighted_calibration_input_check(
    input: jax.Array,
    target: jax.Array,
    weight: Union[float, int, "jax.Array"],
    num_tasks: int,
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            f"`input` shape ({input.shape}) is different from `target` shape "
            f"({target.shape})"
        )
    if num_tasks == 1:
        if input.ndim > 1:
            raise ValueError(
                "`num_tasks = 1`, `input` is expected to be one-dimensional "
                f"tensor, but got shape ({input.shape})."
            )
    elif input.ndim == 1 or input.shape[0] != num_tasks:
        raise ValueError(
            f"`num_tasks = {num_tasks}`, `input`'s shape is expected to be "
            f"({num_tasks}, num_samples), but got shape ({input.shape})."
        )
