"""Hit rate — parity with reference
``torcheval/metrics/functional/ranking/hit_rate.py`` (65 LoC)."""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def hit_rate(input, target, *, k: Optional[int] = None) -> jax.Array:
    """Per-sample hit indicator of the target class among the top-k
    predictions; rank = #(scores strictly above target's score)
    (reference ``hit_rate.py:40-46``)."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    _hit_rate_input_check(input, target, k)
    if k is None or k >= input.shape[-1]:
        return jnp.ones(target.shape, dtype=jnp.float32)
    return _hit_rate_kernel(input, target, k)


@partial(jax.jit, static_argnames=("k",))
def _hit_rate_kernel(input: jax.Array, target: jax.Array, k: int) -> jax.Array:
    y_score = jnp.take_along_axis(input, target[:, None], axis=-1)
    rank = jnp.sum(input > y_score, axis=-1)
    return (rank < k).astype(jnp.float32)


def _hit_rate_input_check(
    input: jax.Array, target: jax.Array, k: Optional[int] = None
) -> None:
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if input.ndim != 2:
        raise ValueError(
            f"input should be a two-dimensional tensor, got shape {input.shape}."
        )
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "`input` and `target` should have the same minibatch dimension, "
            f"got shapes {input.shape} and {target.shape}, respectively."
        )
    if k is not None and k <= 0:
        raise ValueError(f"k should be None or positive, got {k}.")
