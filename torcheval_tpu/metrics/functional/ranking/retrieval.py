"""Retrieval precision / recall at k.

Beyond the v0.0.4 snapshot (upstream torcheval added the retrieval
metrics later).  One call scores one query's candidate list (or
``num_tasks`` of them via a leading dim):

precision@k = relevant-in-top-k / k_eff
recall@k    = relevant-in-top-k / total-relevant

``k=None`` uses every candidate; ``limit_k_to_size`` clamps ``k`` to the
candidate count (so precision is not penalized for short lists).  The
top-k selection is a single ``lax.top_k`` — MXU-free, fused with the
gather and reductions under jit."""

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.functional._host_checks import (
    all_concrete,
    value_checks_enabled,
)


def retrieval_precision(
    input,
    target,
    k: Optional[int] = None,
    *,
    limit_k_to_size: bool = False,
    num_tasks: int = 1,
) -> jax.Array:
    """Fraction of the top-``k`` scored candidates that are relevant."""
    input, target, k_eff, k_sel = _retrieval_prepare(
        input, target, k, limit_k_to_size, num_tasks
    )
    hits = _topk_hits(input, target, k_sel)
    out = hits / k_eff
    return out[0] if num_tasks == 1 else out


def retrieval_recall(
    input,
    target,
    k: Optional[int] = None,
    *,
    limit_k_to_size: bool = False,
    num_tasks: int = 1,
) -> jax.Array:
    """Fraction of all relevant candidates found in the top ``k``."""
    input, target, _, k_sel = _retrieval_prepare(
        input, target, k, limit_k_to_size, num_tasks
    )
    hits = _topk_hits(input, target, k_sel)
    total = (target == 1).sum(axis=-1)
    out = hits / total
    return out[0] if num_tasks == 1 else out


def _retrieval_prepare(
    input,
    target,
    k: Optional[int],
    limit_k_to_size: bool,
    num_tasks: int,
) -> Tuple[jax.Array, jax.Array, int, int]:
    """Validate, lift to (num_tasks, n), and resolve the effective k
    (the precision denominator) and the selection k (``<= n``)."""
    _retrieval_param_check(k, limit_k_to_size)
    input, target = jnp.asarray(input), jnp.asarray(target)
    _retrieval_input_check(input, target, num_tasks)
    if input.ndim == 1:
        input, target = input[None], target[None]
    n = input.shape[-1]
    k_eff = n if k is None else (min(k, n) if limit_k_to_size else k)
    return input, target, k_eff, min(k_eff, n)


@partial(jax.jit, static_argnames=("k_sel",))
def _topk_hits(input: jax.Array, target: jax.Array, k_sel: int) -> jax.Array:
    """Relevant count among each row's top ``k_sel`` scored candidates."""
    _, idx = jax.lax.top_k(input, k_sel)
    return jnp.take_along_axis(target, idx, axis=-1).sum(axis=-1)


def _retrieval_param_check(k: Optional[int], limit_k_to_size: bool) -> None:
    if k is not None and k < 1:
        raise ValueError(f"`k` should be a positive integer, got k={k}.")
    if limit_k_to_size and k is None:
        raise ValueError(
            "when `limit_k_to_size` is True, `k` must not be None."
        )


def _retrieval_input_check(
    input: jax.Array, target: jax.Array, num_tasks: int
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same shape, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if num_tasks == 1:
        if input.ndim != 1:
            raise ValueError(
                "`input` should be a one-dimensional tensor for num_tasks = 1, "
                f"got shape {input.shape}."
            )
    elif input.ndim != 2 or input.shape[0] != num_tasks:
        raise ValueError(
            f"`input` should have shape ({num_tasks}, num_candidates) for "
            f"num_tasks = {num_tasks}, got shape {input.shape}."
        )
    # Relevance must be 0/1 — graded targets would inflate the top-k hit
    # sum against the exact-1 relevant count.  Data-dependent, so skipped
    # under tracing like every host-side value check (_host_checks.py).
    if target.size and all_concrete(target) and value_checks_enabled():
        ok = np.asarray(jax.device_get(_binary_target_probe(target)))
        if not bool(ok):
            raise ValueError(
                "`target` should be a binary tensor of 0/1 relevance labels."
            )


@jax.jit
def _binary_target_probe(target: jax.Array) -> jax.Array:
    return jnp.all((target == 0) | (target == 1))
