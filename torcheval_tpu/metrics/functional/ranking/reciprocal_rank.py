"""Reciprocal rank — parity with reference
``torcheval/metrics/functional/ranking/reciprocal_rank.py`` (63 LoC)."""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def reciprocal_rank(input, target, *, k: Optional[int] = None) -> jax.Array:
    """Per-sample 1/(rank+1) of the target class, zeroed past k
    (reference ``reciprocal_rank.py:41-47``)."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    _reciprocal_rank_input_check(input, target)
    return _reciprocal_rank_kernel(input, target, k)


@partial(jax.jit, static_argnames=("k",))
def _reciprocal_rank_kernel(
    input: jax.Array, target: jax.Array, k: Optional[int]
) -> jax.Array:
    y_score = jnp.take_along_axis(input, target[:, None], axis=-1)
    rank = jnp.sum(input > y_score, axis=-1)
    score = 1.0 / (rank + 1.0)
    if k is not None:
        score = jnp.where(rank >= k, 0.0, score)
    return score


def _reciprocal_rank_input_check(input: jax.Array, target: jax.Array) -> None:
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if input.ndim != 2:
        raise ValueError(
            f"input should be a two-dimensional tensor, got shape {input.shape}."
        )
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "`input` and `target` should have the same minibatch dimension, "
            f"got shapes {input.shape} and {target.shape}, respectively."
        )
