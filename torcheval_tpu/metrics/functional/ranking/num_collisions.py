"""num_collisions — parity with reference
``torcheval/metrics/functional/ranking/num_collisions.py`` (52 LoC).

O(N²) broadcast equality minus self (reference ``num_collisions.py:31-35``)."""

import jax
import jax.numpy as jnp


def num_collisions(input) -> jax.Array:
    """Per-id count of other ids equal to it."""
    input = jnp.asarray(input)
    _num_collisions_input_check(input)
    return _num_collisions_kernel(input)


@jax.jit
def _num_collisions_kernel(input: jax.Array) -> jax.Array:
    return (input[None, :] == input[:, None]).sum(axis=1) - 1


def _num_collisions_input_check(input: jax.Array) -> None:
    if input.ndim != 1:
        raise ValueError(
            f"input should be a one-dimensional tensor, got shape {input.shape}."
        )
    if not jnp.issubdtype(input.dtype, jnp.integer):
        raise ValueError(f"input should be an integer tensor, got {input.dtype}.")
