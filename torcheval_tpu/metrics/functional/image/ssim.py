"""Structural similarity (SSIM), Wang et al. 2004.

Beyond the v0.0.4 snapshot (upstream torcheval added image metrics
later).  The 11×11 σ=1.5 gaussian windowing is two depthwise
convolutions per moment — ``lax.conv_general_dilated`` with
``feature_group_count=C`` — which XLA fuses and tiles onto the TPU
convolution units; the SSIM map is averaged over the valid region.
Sufficient statistics are the per-image SSIM sum and image count."""

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def structural_similarity(
    input,
    target,
    *,
    data_range: float = 1.0,
    kernel_size: int = 11,
    sigma: float = 1.5,
    k1: float = 0.01,
    k2: float = 0.03,
) -> jax.Array:
    """Mean SSIM over a batch of ``(N, C, H, W)`` image pairs."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    _ssim_input_check(input, target, kernel_size)
    per_image = _ssim_per_image(
        input, target, data_range, kernel_size, sigma, k1, k2
    )
    return per_image.mean()


@partial(
    jax.jit, static_argnames=("data_range", "kernel_size", "sigma", "k1", "k2")
)
def _ssim_per_image(
    input: jax.Array,
    target: jax.Array,
    data_range: float,
    kernel_size: int,
    sigma: float,
    k1: float,
    k2: float,
) -> jax.Array:
    """Per-image mean SSIM, shape ``(N,)``."""
    channels = input.shape[1]
    x = input.astype(jnp.float32)
    y = target.astype(jnp.float32)
    blur = partial(_depthwise_gaussian, channels=channels,
                   kernel_size=kernel_size, sigma=sigma)
    mu_x, mu_y = blur(x), blur(y)
    mu_xx, mu_yy, mu_xy = mu_x * mu_x, mu_y * mu_y, mu_x * mu_y
    sigma_x = blur(x * x) - mu_xx
    sigma_y = blur(y * y) - mu_yy
    sigma_xy = blur(x * y) - mu_xy
    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2
    ssim_map = ((2 * mu_xy + c1) * (2 * sigma_xy + c2)) / (
        (mu_xx + mu_yy + c1) * (sigma_x + sigma_y + c2)
    )
    return ssim_map.mean(axis=(1, 2, 3))


def _depthwise_gaussian(
    x: jax.Array, *, channels: int, kernel_size: int, sigma: float
) -> jax.Array:
    """Valid-padding depthwise gaussian filter over (N, C, H, W)."""
    g = _gaussian_1d(kernel_size, sigma)
    window = jnp.asarray(np.outer(g, g), dtype=jnp.float32)
    kernel = jnp.broadcast_to(
        window, (channels, 1, kernel_size, kernel_size)
    )
    return jax.lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=channels,
    )


def _gaussian_1d(kernel_size: int, sigma: float) -> np.ndarray:
    half = (kernel_size - 1) / 2.0
    coords = np.arange(kernel_size) - half
    g = np.exp(-(coords**2) / (2.0 * sigma**2))
    return g / g.sum()


def _ssim_input_check(
    input: jax.Array, target: jax.Array, kernel_size: int
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same shape, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if input.ndim != 4:
        raise ValueError(
            "input should have shape (num_images, channels, height, width), "
            f"got {input.shape}."
        )
    if min(input.shape[2], input.shape[3]) < kernel_size:
        raise ValueError(
            f"image spatial dims {input.shape[2:]} must be at least the "
            f"gaussian kernel size {kernel_size}."
        )
