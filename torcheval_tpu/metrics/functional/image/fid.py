"""Fréchet distance between multivariate gaussians — the core of FID.

Beyond the v0.0.4 snapshot (upstream torcheval added
``FrechetInceptionDistance`` later).

d²((μ₁,Σ₁), (μ₂,Σ₂)) = |μ₁−μ₂|² + tr(Σ₁ + Σ₂ − 2·(Σ₁Σ₂)^½)

The matrix square root never materializes: tr((Σ₁Σ₂)^½) equals the sum
of square-rooted eigenvalues of the symmetric PSD matrix
Σ₁^½ Σ₂ Σ₁^½, so two ``eigh`` calls (stable, XLA-native) replace the
non-symmetric ``sqrtm`` that CPU implementations lean on scipy for."""

import jax
import jax.numpy as jnp


def gaussian_frechet_distance(
    mu_x, cov_x, mu_y, cov_y
) -> jax.Array:
    """Fréchet (2-Wasserstein²) distance between two gaussians given by
    mean vectors ``(D,)`` and covariance matrices ``(D, D)``."""
    mu_x, cov_x = jnp.asarray(mu_x), jnp.asarray(cov_x)
    mu_y, cov_y = jnp.asarray(mu_y), jnp.asarray(cov_y)
    _frechet_input_check(mu_x, cov_x, mu_y, cov_y)
    return _gaussian_frechet_distance_kernel(mu_x, cov_x, mu_y, cov_y)


@jax.jit
def _gaussian_frechet_distance_kernel(
    mu_x: jax.Array, cov_x: jax.Array, mu_y: jax.Array, cov_y: jax.Array
) -> jax.Array:
    # Full float64 precision under jax_enable_x64; float32 otherwise
    # (requesting f64 without x64 would only emit a truncation warning).
    dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    mu_x, cov_x = mu_x.astype(dtype), cov_x.astype(dtype)
    mu_y, cov_y = mu_y.astype(dtype), cov_y.astype(dtype)
    diff = mu_x - mu_y
    # Σx^{1/2} via eigendecomposition (Σx symmetric PSD up to noise).
    w, v = jnp.linalg.eigh(cov_x)
    sqrt_x = (v * jnp.sqrt(jnp.clip(w, 0.0))) @ v.T
    # eigvals of Σx^{1/2} Σy Σx^{1/2} = eigvals of Σx Σy, but symmetric.
    prod = sqrt_x @ cov_y @ sqrt_x
    prod_w = jnp.linalg.eigvalsh((prod + prod.T) / 2.0)
    tr_sqrt = jnp.sqrt(jnp.clip(prod_w, 0.0)).sum()
    return (
        diff @ diff + jnp.trace(cov_x) + jnp.trace(cov_y) - 2.0 * tr_sqrt
    )


def _frechet_input_check(
    mu_x: jax.Array, cov_x: jax.Array, mu_y: jax.Array, cov_y: jax.Array
) -> None:
    d = mu_x.shape[0] if mu_x.ndim == 1 else -1
    if mu_x.ndim != 1 or mu_y.shape != (d,):
        raise ValueError(
            "mean vectors should be one-dimensional and equally sized, got "
            f"{mu_x.shape} and {mu_y.shape}."
        )
    if cov_x.shape != (d, d) or cov_y.shape != (d, d):
        raise ValueError(
            f"covariances should have shape ({d}, {d}), got "
            f"{cov_x.shape} and {cov_y.shape}."
        )
