"""Peak signal-to-noise ratio.

Beyond the v0.0.4 snapshot (upstream torcheval added image metrics
later).  PSNR = 10·log10(data_range² / MSE); sufficient statistics are
the summed squared error and element count — add-mergeable counters,
one fused reduction per batch."""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def peak_signal_noise_ratio(
    input,
    target,
    data_range: Optional[float] = None,
) -> jax.Array:
    """PSNR between two images or batches of images of the same shape.
    ``data_range`` defaults to ``max(target) − min(target)`` of the data
    seen (the convention upstream uses when unset)."""
    _psnr_param_check(data_range)
    input, target = jnp.asarray(input), jnp.asarray(target)
    _psnr_input_check(input, target)
    sum_se, n, observed_range = _psnr_update_kernel(input, target)
    if data_range is not None:
        observed_range = jnp.asarray(float(data_range))
    return _psnr_compute(sum_se, n, observed_range)


@jax.jit
def _psnr_update_kernel(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    err = (input - target).astype(jnp.float32)
    return (
        jnp.sum(jnp.square(err)),
        jnp.asarray(input.size, jnp.float32),
        (target.max() - target.min()).astype(jnp.float32),
    )


@jax.jit
def _psnr_compute(
    sum_se: jax.Array, n: jax.Array, data_range: jax.Array
) -> jax.Array:
    mse = sum_se / n
    return 10.0 * jnp.log10(jnp.square(data_range) / mse)


def _psnr_param_check(data_range: Optional[float]) -> None:
    if data_range is not None:
        if not isinstance(data_range, (int, float)):
            raise ValueError(
                f"`data_range` should be a float, got {type(data_range)}."
            )
        if data_range <= 0:
            raise ValueError(
                f"`data_range` should be positive, got {data_range}."
            )


def _psnr_input_check(input: jax.Array, target: jax.Array) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same shape, "
            f"got shapes {input.shape} and {target.shape}."
        )
