from torcheval_tpu.metrics.functional.image.fid import gaussian_frechet_distance
from torcheval_tpu.metrics.functional.image.psnr import peak_signal_noise_ratio
from torcheval_tpu.metrics.functional.image.ssim import structural_similarity

__all__ = [
    "gaussian_frechet_distance",
    "peak_signal_noise_ratio",
    "structural_similarity",
]
