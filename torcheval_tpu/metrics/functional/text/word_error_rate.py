"""Word error rate and word information preserved/lost.

Beyond the v0.0.4 snapshot (upstream torcheval added the text metrics
later).  These are host-side string metrics — no device tensor exists
until the sufficient statistics are formed — so the hot kernel is the
native batched Levenshtein in ``torcheval_tpu/native`` (C++ via ctypes,
pure-Python fallback).  Sufficient statistics are scalar counters,
add-mergeable like every counter metric here.

WER  = edit_errors / target_words
WIP  = (target_words − errors)/target_words · (target_words − errors)/input_words
       (the Morris et al. hit proxy H ≈ N_ref − E in both numerators)
WIL  = 1 − WIP
"""

from typing import List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.native import edit_distance_batch

TText = Union[str, Sequence[str]]


def word_error_rate(input: TText, target: TText) -> jax.Array:
    """WER over one or more (hypothesis, reference) string pairs."""
    errors, target_total, _ = _word_stats_update(input, target)
    return jnp.asarray(errors / target_total if target_total else float("nan"))


def word_information_preserved(input: TText, target: TText) -> jax.Array:
    """Word information preserved over (hypothesis, reference) pairs."""
    errors, target_total, input_total = _word_stats_update(input, target)
    return _wip_compute(
        jnp.asarray(float(errors)),
        jnp.asarray(float(target_total)),
        jnp.asarray(float(input_total)),
    )


def word_information_lost(input: TText, target: TText) -> jax.Array:
    """Word information lost: ``1 − WIP``."""
    return 1.0 - word_information_preserved(input, target)


@jax.jit
def _wip_compute(
    errors: jax.Array, target_total: jax.Array, input_total: jax.Array
) -> jax.Array:
    hits = target_total - errors
    return (hits / target_total) * (hits / input_total)


def _as_list(text: TText, name: str) -> List[str]:
    if isinstance(text, str):
        return [text]
    if isinstance(text, Sequence) and all(isinstance(t, str) for t in text):
        return list(text)
    raise ValueError(
        f"`{name}` should be a string or a sequence of strings, got {type(text)}."
    )


def _word_stats_update(input: TText, target: TText) -> Tuple[int, int, int]:
    """(edit errors, target word count, input word count) over the batch —
    the shared sufficient statistics of WER/WIP/WIL."""
    input, target = _as_list(input, "input"), _as_list(target, "target")
    if len(input) != len(target):
        raise ValueError(
            "`input` and `target` should have the same number of sequences, "
            f"got {len(input)} and {len(target)}."
        )
    vocab: dict = {}

    def ids(sentence: str) -> List[int]:
        return [vocab.setdefault(w, len(vocab)) for w in sentence.split()]

    input_ids = [ids(s) for s in input]
    target_ids = [ids(s) for s in target]
    errors = int(np.sum(edit_distance_batch(input_ids, target_ids))) if input else 0
    return (
        errors,
        sum(len(s) for s in target_ids),
        sum(len(s) for s in input_ids),
    )
