"""Word error rate and word information preserved/lost.

Beyond the v0.0.4 snapshot (upstream torcheval added the text metrics
later).  Two input flavors share the same sufficient statistics (edit
errors, target words, input words — scalar counters, add-mergeable like
every counter metric here):

* **strings** — host-side: per-batch word→id interning feeds the native
  batched Levenshtein in ``torcheval_tpu/native`` (C++ via ctypes,
  pure-Python fallback).
* **token-id arrays** — device-resident: padded ``(n, len)`` int32 ids
  under the negative-trailing-pad convention (``metrics/text/_tokens``),
  or ``(n, seq, vocab)`` float logits whose greedy-argmax hypothesis is
  derived in-kernel; the distances come from the anti-diagonal wavefront
  routes in ``ops/pallas_wavefront.py`` and the whole update is one
  fusable device program.

WER  = edit_errors / target_words
WIP  = (target_words − errors)/target_words · (target_words − errors)/input_words
       (the Morris et al. hit proxy H ≈ N_ref − E in both numerators)
WIL  = 1 − WIP
"""

from functools import partial
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.native import edit_distance_batch

TText = Union[str, Sequence[str]]


def word_error_rate(input, target) -> jax.Array:
    """WER over (hypothesis, reference) pairs — strings, token-id
    arrays, or logits (see module docstring for the array contract)."""
    if _is_tokens(input):
        errors, target_total, _ = _word_stats_tokens(input, target)
        return errors / target_total
    errors, target_total, _ = _word_stats_update(input, target)
    return jnp.asarray(errors / target_total if target_total else float("nan"))


def word_information_preserved(input, target) -> jax.Array:
    """Word information preserved over (hypothesis, reference) pairs."""
    if _is_tokens(input):
        errors, target_total, input_total = _word_stats_tokens(input, target)
        return _wip_compute(
            errors.astype(jnp.float32),
            target_total.astype(jnp.float32),
            input_total.astype(jnp.float32),
        )
    errors, target_total, input_total = _word_stats_update(input, target)
    return _wip_compute(
        jnp.asarray(float(errors)),
        jnp.asarray(float(target_total)),
        jnp.asarray(float(input_total)),
    )


def word_information_lost(input, target) -> jax.Array:
    """Word information lost: ``1 − WIP``."""
    return 1.0 - word_information_preserved(input, target)


@jax.jit
def _wip_compute(
    errors: jax.Array, target_total: jax.Array, input_total: jax.Array
) -> jax.Array:
    hits = target_total - errors
    return (hits / target_total) * (hits / input_total)


def _as_list(text: TText, name: str) -> List[str]:
    if isinstance(text, str):
        return [text]
    if isinstance(text, Sequence) and all(isinstance(t, str) for t in text):
        return list(text)
    raise ValueError(
        f"`{name}` should be a string or a sequence of strings, got {type(text)}."
    )


def _is_tokens(x) -> bool:
    """Array-flavored input (token ids or logits) vs the host string
    path: anything with an ``ndim`` is an array, including tracers."""
    return hasattr(x, "ndim") and not isinstance(x, (str, bytes))


def _word_stats_tokens_check(input: jax.Array, target: jax.Array) -> None:
    if target.ndim != 2 or not jnp.issubdtype(target.dtype, jnp.integer):
        raise ValueError(
            "target should be (num_sequences, num_tokens) integer token "
            f"ids, got shape {target.shape} dtype {target.dtype}."
        )
    if input.ndim == 3:
        if not jnp.issubdtype(input.dtype, jnp.inexact):
            raise ValueError(
                "3-D input should be (num_sequences, num_tokens, "
                f"vocab_size) float logits, got dtype {input.dtype}."
            )
        if input.shape[:2] != target.shape:
            raise ValueError(
                "The leading dimensions of input and target should "
                f"match, got {input.shape} and {target.shape}."
            )
    elif input.ndim == 2:
        if not jnp.issubdtype(input.dtype, jnp.integer):
            raise ValueError(
                "2-D input should be (num_sequences, num_tokens) integer "
                f"token ids, got dtype {input.dtype}."
            )
        if input.shape[0] != target.shape[0]:
            raise ValueError(
                "`input` and `target` should have the same number of "
                f"sequences, got {input.shape[0]} and {target.shape[0]}."
            )
    else:
        raise ValueError(
            "input should be (n, len) token ids or (n, seq, vocab) "
            f"logits, got shape {input.shape}."
        )


@partial(jax.jit, static_argnames=("route",))
def _word_stats_device_kernel(
    input: jax.Array,
    target: jax.Array,
    route: str,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Device-resident sibling of :func:`_word_stats_update`: the three
    counter deltas from padded token-id arrays (negative trailing pads).

    A 3-D float ``input`` contributes its greedy-argmax hypothesis at
    the reference's live positions (token error rate of the decoded
    stream) — derived in-kernel so the whole update stays one program.
    ``route`` ("pallas" | "xla") is :func:`~torcheval_tpu.ops.
    pallas_wavefront.wavefront_route`'s eager decision, riding the jit
    cache key; the native host DP cannot run under a trace, so it never
    appears here.
    """
    from torcheval_tpu.ops.pallas_wavefront import (
        _edit_distance_pallas,
        _edit_distance_xla,
        lens_from_ids,
    )

    target = target.astype(jnp.int32)
    if input.ndim == 3:
        hyp = jnp.where(
            target >= 0, jnp.argmax(input, axis=-1).astype(jnp.int32), -1
        )
    else:
        hyp = input.astype(jnp.int32)
    a_lens = lens_from_ids(hyp)
    b_lens = lens_from_ids(target)
    dist_fn = _edit_distance_pallas if route == "pallas" else _edit_distance_xla
    dist = dist_fn(hyp, target, a_lens, b_lens)
    if mask is not None:
        # Padded bucket rows contribute exact zeros to all three counters.
        live = mask.astype(jnp.int32)
        dist = dist * live
        a_lens = a_lens * live
        b_lens = b_lens * live
    return dist.sum(), b_lens.sum(), a_lens.sum()


def _word_stats_tokens(
    input, target, mask: Optional[jax.Array] = None
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Validate + route one tokenized batch through the device kernel."""
    from torcheval_tpu.ops.pallas_wavefront import wavefront_route

    input, target = jnp.asarray(input), jnp.asarray(target)
    _word_stats_tokens_check(input, target)
    # concrete=False: the kernel is jitted, so the eager-only native DP
    # is never a candidate here (strings keep it as their engine).
    return _word_stats_device_kernel(
        input, target, wavefront_route(False), mask=mask
    )


def _word_stats_update(input: TText, target: TText) -> Tuple[int, int, int]:
    """(edit errors, target word count, input word count) over the batch —
    the shared sufficient statistics of WER/WIP/WIL."""
    input, target = _as_list(input, "input"), _as_list(target, "target")
    if len(input) != len(target):
        raise ValueError(
            "`input` and `target` should have the same number of sequences, "
            f"got {len(input)} and {len(target)}."
        )
    vocab: dict = {}

    def ids(sentence: str) -> List[int]:
        return [vocab.setdefault(w, len(vocab)) for w in sentence.split()]

    input_ids = [ids(s) for s in input]
    target_ids = [ids(s) for s in target]
    errors = int(np.sum(edit_distance_batch(input_ids, target_ids))) if input else 0
    return (
        errors,
        sum(len(s) for s in target_ids),
        sum(len(s) for s in input_ids),
    )
