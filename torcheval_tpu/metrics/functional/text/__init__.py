from torcheval_tpu.metrics.functional.text.bleu import bleu_score
from torcheval_tpu.metrics.functional.text.perplexity import perplexity
from torcheval_tpu.metrics.functional.text.word_error_rate import (
    word_error_rate,
    word_information_lost,
    word_information_preserved,
)

__all__ = [
    "bleu_score",
    "perplexity",
    "word_error_rate",
    "word_information_lost",
    "word_information_preserved",
]
