"""Perplexity from next-token logits.

Beyond the v0.0.4 snapshot (upstream torcheval added ``perplexity``
later).  The one genuinely-device text metric: sufficient statistics are
the summed token negative log-likelihood and the token count, produced by
a single fused ``log_softmax`` + gather kernel — add-mergeable,
``psum``-syncable."""

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def perplexity(
    input,
    target,
    ignore_index: Optional[int] = None,
) -> jax.Array:
    """``exp(mean NLL)`` over ``(n, seq_len, vocab)`` logits and
    ``(n, seq_len)`` target token ids; ``ignore_index`` tokens are
    excluded from the mean."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    _perplexity_input_check(input, target)
    sum_nll, count = _perplexity_update_kernel(input, target, ignore_index)
    return _perplexity_compute(sum_nll, count)


@partial(jax.jit, static_argnames=("ignore_index",))
def _perplexity_update_kernel(
    input: jax.Array,
    target: jax.Array,
    ignore_index: Optional[int],
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    # Each token's log-prob is its gathered logit minus the vocab-axis
    # logsumexp — the full (n, seq, vocab) log-prob tensor is never
    # formed (a log_softmax-then-gather writes and re-reads the whole
    # cube, tripling HBM traffic at LLM vocab sizes).  Negative target
    # ids (the tokenized-text pad convention) gather through a clipped
    # index; ``valid`` zeroes their contribution.
    logits = input.astype(jnp.float32)
    token_logit = jnp.take_along_axis(
        logits, jnp.clip(target, 0)[..., None], axis=-1
    )[..., 0]
    token_ll = token_logit - jax.scipy.special.logsumexp(logits, axis=-1)
    if ignore_index is None:
        valid = jnp.ones(target.shape, jnp.float32)
    else:
        valid = (target != ignore_index).astype(jnp.float32)
    if mask is not None:
        # Padded bucket rows contribute exact zeros to both counters.
        valid = valid * mask.astype(jnp.float32)[:, None]
    return -(token_ll * valid).sum(), valid.sum()


@jax.jit
def _perplexity_compute(sum_nll: jax.Array, count: jax.Array) -> jax.Array:
    return jnp.exp(sum_nll / count)


def _perplexity_input_check(input: jax.Array, target: jax.Array) -> None:
    if input.ndim != 3:
        raise ValueError(
            "input should have shape (num_sequences, num_tokens, vocab_size), "
            f"got {input.shape}."
        )
    if target.ndim != 2:
        raise ValueError(
            "target should have shape (num_sequences, num_tokens), "
            f"got {target.shape}."
        )
    if input.shape[:2] != target.shape:
        raise ValueError(
            "The leading dimensions of input and target should match, got "
            f"{input.shape} and {target.shape}."
        )
