"""Perplexity from next-token logits.

Beyond the v0.0.4 snapshot (upstream torcheval added ``perplexity``
later).  The one genuinely-device text metric: sufficient statistics are
the summed token negative log-likelihood and the token count, produced by
a single fused ``log_softmax`` + gather kernel — add-mergeable,
``psum``-syncable."""

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def perplexity(
    input,
    target,
    ignore_index: Optional[int] = None,
) -> jax.Array:
    """``exp(mean NLL)`` over ``(n, seq_len, vocab)`` logits and
    ``(n, seq_len)`` target token ids; ``ignore_index`` tokens are
    excluded from the mean."""
    input, target = jnp.asarray(input), jnp.asarray(target)
    _perplexity_input_check(input, target)
    sum_nll, count = _perplexity_update_kernel(input, target, ignore_index)
    return _perplexity_compute(sum_nll, count)


@partial(jax.jit, static_argnames=("ignore_index",))
def _perplexity_update_kernel(
    input: jax.Array, target: jax.Array, ignore_index: Optional[int]
) -> Tuple[jax.Array, jax.Array]:
    logp = jax.nn.log_softmax(input.astype(jnp.float32), axis=-1)
    token_ll = jnp.take_along_axis(logp, target[..., None], axis=-1)[..., 0]
    if ignore_index is None:
        return -token_ll.sum(), jnp.asarray(token_ll.size, jnp.float32)
    mask = target != ignore_index
    return -(token_ll * mask).sum(), mask.sum().astype(jnp.float32)


@jax.jit
def _perplexity_compute(sum_nll: jax.Array, count: jax.Array) -> jax.Array:
    return jnp.exp(sum_nll / count)


def _perplexity_input_check(input: jax.Array, target: jax.Array) -> None:
    if input.ndim != 3:
        raise ValueError(
            "input should have shape (num_sequences, num_tokens, vocab_size), "
            f"got {input.shape}."
        )
    if target.ndim != 2:
        raise ValueError(
            "target should have shape (num_sequences, num_tokens), "
            f"got {target.shape}."
        )
    if input.shape[:2] != target.shape:
        raise ValueError(
            "The leading dimensions of input and target should match, got "
            f"{input.shape} and {target.shape}."
        )
