"""BLEU score.

Beyond the v0.0.4 snapshot (upstream torcheval added ``bleu_score``
later).  Host-side n-gram counting (strings never touch the device); the
sufficient statistics are four add-mergeable counters — candidate/
reference lengths and per-order clipped/possible n-gram match counts —
so the class metric merges and syncs like every counter metric."""

import warnings
from collections import Counter
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

TBleuInput = Union[str, Sequence[str]]
TBleuTarget = Union[str, Sequence[str], Sequence[Sequence[str]]]


def bleu_score(
    input: TBleuInput,
    target: TBleuTarget,
    *,
    n_gram: int = 4,
    weights: Optional[Sequence[float]] = None,
) -> jax.Array:
    """Corpus BLEU of candidate sentence(s) against their reference set(s),
    with modified n-gram precision up to ``n_gram`` and the standard
    brevity penalty.  ``weights`` defaults to uniform ``1/n_gram``."""
    weights_arr = _bleu_param_check(n_gram, weights)
    input_len, target_len, matches, possible = _bleu_update(input, target, n_gram)
    return _bleu_compute(
        jnp.asarray(float(input_len)),
        jnp.asarray(float(target_len)),
        jnp.asarray(matches, dtype=jnp.float32),
        jnp.asarray(possible, dtype=jnp.float32),
        weights_arr,
    )


def _bleu_param_check(
    n_gram: int, weights: Optional[Sequence[float]]
) -> jax.Array:
    if n_gram < 1:
        raise ValueError(f"`n_gram` should be at least 1, got {n_gram}.")
    if weights is None:
        return jnp.full(n_gram, 1.0 / n_gram)
    if len(weights) != n_gram:
        raise ValueError(
            f"the length of `weights` should equal `n_gram`, got "
            f"{len(weights)} and {n_gram}."
        )
    if any(w < 0 for w in weights):
        raise ValueError(
            f"`weights` should be non-negative, got {list(weights)}."
        )
    total = float(sum(weights))
    if total <= 0:
        raise ValueError(
            f"`weights` should have a positive sum, got {list(weights)}."
        )
    if abs(total - 1.0) > 1e-6:
        # Un-normalized weights silently rescale log-BLEU by their sum;
        # normalize to what the caller almost certainly meant, loudly.
        warnings.warn(
            f"`weights` sum to {total:g}, not 1; normalizing them. Pass "
            "weights summing to 1 to silence this.",
            UserWarning,
            stacklevel=3,
        )
        weights = [w / total for w in weights]
    return jnp.asarray(weights, dtype=jnp.float32)


def _normalize_pairs(
    input: TBleuInput, target: TBleuTarget
) -> Tuple[List[str], List[List[str]]]:
    """Canonicalize to (candidates, per-candidate reference lists)."""
    if isinstance(input, str):
        candidates = [input]
        if isinstance(target, str):
            references: List[List[str]] = [[target]]
        else:
            references = [list(target)]
    else:
        candidates = list(input)
        if isinstance(target, str):
            raise ValueError(
                "When `input` is a sequence of candidates, `target` must be "
                "a sequence of references (one str or list of str per "
                "candidate), got a bare string."
            )
        references = [[t] if isinstance(t, str) else list(t) for t in target]
    if len(candidates) != len(references):
        raise ValueError(
            "`input` and `target` should have the same number of sentences, "
            f"got {len(candidates)} and {len(references)}."
        )
    for refs in references:
        if not refs:
            raise ValueError("Every candidate needs at least one reference.")
    return candidates, references


def _ngram_counts(tokens: List[str], n_gram: int) -> List[Counter]:
    return [
        Counter(
            tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1)
        )
        for n in range(1, n_gram + 1)
    ]


def _bleu_update(
    input: TBleuInput, target: TBleuTarget, n_gram: int
) -> Tuple[int, int, np.ndarray, np.ndarray]:
    """Sufficient statistics: candidate length, closest-reference length,
    clipped matches and possible matches per n-gram order."""
    candidates, references = _normalize_pairs(input, target)
    input_len = 0
    target_len = 0
    matches = np.zeros(n_gram, dtype=np.int64)
    possible = np.zeros(n_gram, dtype=np.int64)
    for cand, refs in zip(candidates, references):
        cand_tokens = cand.split()
        ref_tokens = [r.split() for r in refs]
        input_len += len(cand_tokens)
        # closest reference length; ties break toward the shorter reference
        target_len += min(
            (len(r) for r in ref_tokens),
            key=lambda L: (abs(L - len(cand_tokens)), L),
        )
        cand_counts = _ngram_counts(cand_tokens, n_gram)
        ref_counts = [_ngram_counts(r, n_gram) for r in ref_tokens]
        for n in range(n_gram):
            max_ref: Counter = Counter()
            for rc in ref_counts:
                for gram, count in rc[n].items():
                    max_ref[gram] = max(max_ref[gram], count)
            matches[n] += sum(
                min(count, max_ref[gram])
                for gram, count in cand_counts[n].items()
            )
            possible[n] += max(0, len(cand_tokens) - n)
    return input_len, target_len, matches, possible


@jax.jit
def _bleu_compute(
    input_len: jax.Array,
    target_len: jax.Array,
    matches: jax.Array,
    possible: jax.Array,
    weights: jax.Array,
) -> jax.Array:
    """Brevity penalty × exp(Σ wₙ log pₙ); 0 when any order has no match
    (log undefined — standard corpus-BLEU convention)."""
    precisions = matches / jnp.maximum(possible, 1.0)
    log_p = jnp.log(jnp.maximum(precisions, 1e-30))
    geo = jnp.exp((weights * log_p).sum())
    bp = jnp.where(
        input_len > target_len, 1.0, jnp.exp(1.0 - target_len / input_len)
    )
    return jnp.where((matches == 0).any() | (input_len == 0), 0.0, bp * geo)
