"""Mergeable sketch states — O(bins) merge payloads for sample-buffer
metrics.

The hierarchical fleet merge (:mod:`torcheval_tpu.parallel.fleet_merge`)
ships a payload per tree level; for counter metrics that payload is a
few scalars, but buffer metrics (AUROC, AUPRC, PR curves) carry every
sample, so the bytes through the root grow O(total samples).  A *sketch*
is a fixed-size summary of a buffer with two properties: it **merges by
a commutative, associative operation** (so tree order doesn't matter)
and its compute error is **bounded as a function of the sketch size
only** (so the accuracy/bytes trade is explicit).

Five kinds, selected by ``Metric.sketch_state(kind=...)``:

``"exact"`` — :class:`ExactSketch`
    The whole prepared metric; lossless, payload O(samples).  The
    default for every metric; the only kind the base class supports.
``"reservoir"`` — :class:`ReservoirSketch`
    Bottom-k priority sampling over (score, target) pairs: each sample
    draws a uniform key from a seeded stream; merge keeps the k smallest
    keys from either side.  Order-independent, so tree and flat merges
    keep the *same* k samples.  A u-statistic over the kept samples
    (AUROC is one) has standard error **O(1/sqrt(capacity))** —
    capacity 4096 gives ~0.016 one-sigma on AUROC.
``"histogram"`` — :class:`HistogramSketch`
    Per-class binned score counts over [0, 1] (scores clipped); merge
    is elementwise addition.  Rank-based curve metrics computed from the
    bins are off by at most the within-bin rank ambiguity: absolute
    error **O(1/bins)** for AUROC/AUPRC — 1024 bins gives < 1e-3.
``"count"`` — :class:`CountSketchState`
    A signed count-sketch (depth x width hashed counters) over the
    discretized score distribution, one sheet per class.  Per-bin count
    estimates err by at most **n / sqrt(width)** with probability
    1 - 2^-depth (median-of-depth estimator); useful when the score
    distribution is heavy-hitter dominated and width << bins.  Curve
    metrics inherit the per-bin count error on top of the histogram's
    O(1/bins) discretization.
``"rank"`` — :class:`RankSketch`
    The rank-sketch sufficient statistics (``ops/rank_sketch.py``):
    per-edge ``score >= edge`` counts over ``(rows, bins)``, per-row
    positives/totals, merge by integer addition — associative,
    commutative, and **bit-deterministic across merge orders**.  Rank
    error ≤ **1/(bins-1)**; supports multi-row metrics (multi-task
    binary, one-vs-rest multiclass) where the other compressed kinds
    are binary-only.  The *native* payload of a ``sketch=True`` metric
    — its device state ships as-is, O(compactors) — and buildable from
    sample buffers too (same ``searchsorted`` binning as the device
    kernel, so both sides of a fleet agree bit-for-bit).

Sketches travel pickled (numpy arrays only — no device state), merge in
place via :meth:`Sketch.merge`, report their wire size via
:meth:`Sketch.nbytes`, and produce the final metric value via
:meth:`Sketch.compute`.  ``ExactSketch`` and ``ReservoirSketch`` also
restore into a live metric (``Metric.merge_sketch``); the bin-domain
kinds are terminal — their samples are gone, use ``.compute()``.
"""

from __future__ import annotations

import copy
from typing import Any, Iterable, Optional

import numpy as np


def _auc_from_histogram(pos: np.ndarray, neg: np.ndarray) -> float:
    """AUROC from per-bin positive/negative counts (ascending score
    order): each positive beats every negative in a strictly lower bin
    and ties (0.5) the negatives sharing its bin."""
    p, n = float(pos.sum()), float(neg.sum())
    if p == 0.0 or n == 0.0:
        return 0.0
    neg_below = np.concatenate(([0.0], np.cumsum(neg)[:-1]))
    wins = float((pos * neg_below).sum()) + 0.5 * float((pos * neg).sum())
    return wins / (p * n)


def _ap_from_histogram(pos: np.ndarray, neg: np.ndarray) -> float:
    """Average precision from per-bin counts: sweep bins in descending
    score order, accumulate (recall delta x precision) per bin."""
    p = float(pos.sum())
    if p == 0.0:
        return 0.0
    pos_d, neg_d = pos[::-1].astype(np.float64), neg[::-1].astype(np.float64)
    tp = np.cumsum(pos_d)
    fp = np.cumsum(neg_d)
    denom = np.maximum(tp + fp, 1e-12)
    precision = tp / denom
    return float((pos_d * precision).sum() / p)


def _compute_from_samples(metric_kind: str, scores, targets) -> Any:
    import jax.numpy as jnp

    from torcheval_tpu.metrics.functional.classification.auprc import (
        _binary_auprc_compute,
    )
    from torcheval_tpu.metrics.functional.classification.auroc import (
        _binary_auroc_compute,
    )

    scores = jnp.asarray(np.asarray(scores))
    targets = jnp.asarray(np.asarray(targets))
    if metric_kind == "binary_auroc":
        return _binary_auroc_compute(scores, targets, False)
    if metric_kind == "binary_auprc":
        return _binary_auprc_compute(scores, targets)
    raise ValueError(f"unknown sketched metric kind {metric_kind!r}")


def _compute_from_bins(metric_kind: str, pos: np.ndarray, neg: np.ndarray):
    import jax.numpy as jnp

    if metric_kind == "binary_auroc":
        return jnp.asarray(_auc_from_histogram(pos, neg))
    if metric_kind == "binary_auprc":
        return jnp.asarray(_ap_from_histogram(pos, neg))
    raise ValueError(f"unknown sketched metric kind {metric_kind!r}")


class Sketch:
    """Interface every sketch kind implements; see the module docstring
    for the merge/size/error contract."""

    kind: str = ""
    metric_kind: str = ""

    def merge(self, other: "Sketch") -> "Sketch":
        raise NotImplementedError

    def nbytes(self) -> int:
        raise NotImplementedError

    def compute(self) -> Any:
        raise NotImplementedError

    def merge_into(self, metric: Any) -> None:
        """Restore this (merged) sketch into a live metric, when the
        sketch domain permits it."""
        raise NotImplementedError(
            f"{type(self).__name__} is bin-domain: its samples are gone, "
            "so it cannot repopulate a buffer metric. Read the fleet "
            "value from sketch.compute() instead."
        )

    def _check_mergeable(self, other: "Sketch") -> None:
        if type(other) is not type(self):
            raise TypeError(
                f"cannot merge {type(other).__name__} into "
                f"{type(self).__name__}"
            )
        if other.metric_kind != self.metric_kind:
            raise ValueError(
                f"cannot merge a {other.metric_kind!r} sketch into a "
                f"{self.metric_kind!r} sketch"
            )


class ExactSketch(Sketch):
    """The identity sketch: the whole prepared metric rides the wire.

    Lossless — merge is ``merge_state`` in arrival order, so a tree
    merge that delivers envelopes in rank order is bit-identical to the
    flat gather-and-merge.  Payload is O(samples); this is the baseline
    the compressed kinds are measured against."""

    kind = "exact"

    def __init__(self, metric: Any) -> None:
        self.metric = metric

    @classmethod
    def from_metric(cls, metric: Any) -> "ExactSketch":
        metric._prepare_for_merge_state()
        return cls(copy.deepcopy(metric))

    def merge(self, other: "Sketch") -> "ExactSketch":
        if not isinstance(other, ExactSketch):
            raise TypeError(
                f"cannot merge {type(other).__name__} into ExactSketch"
            )
        self.metric.merge_state([other.metric])
        return self

    def nbytes(self) -> int:
        return state_nbytes(self.metric)

    def compute(self) -> Any:
        return self.metric.compute()

    def merge_into(self, metric: Any) -> None:
        metric.merge_state([self.metric])


class ReservoirSketch(Sketch):
    """Mergeable uniform sample of (score, target) pairs, bottom-k by
    seeded key.

    Each source sample draws a key from ``default_rng((seed, salt))`` —
    ``salt`` MUST differ per producing rank (the fleet merge passes the
    rank) or two ranks' streams collide and the joint sample is no
    longer uniform.  Merge concatenates and keeps the ``capacity``
    smallest keys, which commutes and associates: any merge order keeps
    the same sample.  Error: a u-statistic over k uniform samples has
    standard error O(1/sqrt(k))."""

    kind = "reservoir"

    def __init__(
        self,
        metric_kind: str,
        capacity: int,
        keys: np.ndarray,
        scores: np.ndarray,
        targets: np.ndarray,
        total_seen: int,
    ) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.metric_kind = metric_kind
        self.capacity = int(capacity)
        self.keys = keys
        self.scores = scores
        self.targets = targets
        self.total_seen = int(total_seen)

    @classmethod
    def from_samples(
        cls,
        metric_kind: str,
        scores: np.ndarray,
        targets: np.ndarray,
        *,
        capacity: int = 4096,
        seed: int = 0,
        salt: int = 0,
    ) -> "ReservoirSketch":
        scores = np.asarray(scores, dtype=np.float32).reshape(-1)
        targets = np.asarray(targets, dtype=np.float32).reshape(-1)
        rng = np.random.default_rng((int(seed), int(salt)))
        keys = rng.random(scores.shape[0])
        sketch = cls(
            metric_kind,
            capacity,
            keys,
            scores,
            targets,
            total_seen=scores.shape[0],
        )
        sketch._shrink()
        return sketch

    def _shrink(self) -> None:
        # Canonical order: ALWAYS sorted by key (not just when over
        # capacity), so any merge order — flat, tree, ring — leaves the
        # identical array in the identical order and downstream compute
        # is bit-reproducible across topologies.
        order = np.argsort(self.keys, kind="stable")[: self.capacity]
        self.keys = self.keys[order]
        self.scores = self.scores[order]
        self.targets = self.targets[order]

    def merge(self, other: "Sketch") -> "ReservoirSketch":
        self._check_mergeable(other)
        self.capacity = min(self.capacity, other.capacity)
        self.keys = np.concatenate([self.keys, other.keys])
        self.scores = np.concatenate([self.scores, other.scores])
        self.targets = np.concatenate([self.targets, other.targets])
        self.total_seen += other.total_seen
        self._shrink()
        return self

    def nbytes(self) -> int:
        return int(
            self.keys.nbytes + self.scores.nbytes + self.targets.nbytes
        )

    def compute(self) -> Any:
        return _compute_from_samples(
            self.metric_kind, self.scores, self.targets
        )

    def merge_into(self, metric: Any) -> None:
        import jax
        import jax.numpy as jnp

        # Sample-domain: repopulate the metric's buffers with the kept
        # sample (the fleet-wide approximation of its merged state).
        metric.inputs = [
            jax.device_put(jnp.asarray(self.scores), metric.device)
        ]
        metric.targets = [
            jax.device_put(jnp.asarray(self.targets), metric.device)
        ]


class HistogramSketch(Sketch):
    """Per-class binned score counts over [0, 1]; merge is addition.

    Scores are clipped into [0, 1] (probability-scale metrics) and
    counted into ``bins`` uniform bins per class.  Rank statistics
    computed from the bins treat within-bin order as ties, so AUROC /
    average-precision error is bounded by the within-bin mass:
    absolute error O(1/bins)."""

    kind = "histogram"

    def __init__(
        self, metric_kind: str, pos: np.ndarray, neg: np.ndarray
    ) -> None:
        self.metric_kind = metric_kind
        self.pos = pos
        self.neg = neg

    @classmethod
    def from_samples(
        cls,
        metric_kind: str,
        scores: np.ndarray,
        targets: np.ndarray,
        *,
        bins: int = 1024,
    ) -> "HistogramSketch":
        if bins < 2:
            raise ValueError(f"bins must be >= 2, got {bins}")
        scores = np.asarray(scores, dtype=np.float64).reshape(-1)
        targets = np.asarray(targets).reshape(-1)
        idx = np.clip((scores * bins).astype(np.int64), 0, bins - 1)
        is_pos = targets > 0.5
        pos = np.bincount(idx[is_pos], minlength=bins).astype(np.int64)
        neg = np.bincount(idx[~is_pos], minlength=bins).astype(np.int64)
        return cls(metric_kind, pos, neg)

    def merge(self, other: "Sketch") -> "HistogramSketch":
        self._check_mergeable(other)
        if other.pos.shape != self.pos.shape:
            raise ValueError(
                f"bin-count mismatch: {self.pos.shape[0]} vs "
                f"{other.pos.shape[0]}"
            )
        self.pos = self.pos + other.pos
        self.neg = self.neg + other.neg
        return self

    def nbytes(self) -> int:
        return int(self.pos.nbytes + self.neg.nbytes)

    def compute(self) -> Any:
        return _compute_from_bins(self.metric_kind, self.pos, self.neg)


class CountSketchState(Sketch):
    """Signed count-sketch over the discretized score distribution.

    Two depth x width counter sheets (one per class); each of the
    ``bins`` score cells hashes to one column per row with a +/-1 sign
    (multiply-shift hashing seeded from ``seed``, so every rank builds
    the same hash family and merge stays elementwise addition).  A
    cell's count is recovered as the median of its depth signed
    readings: error <= n/sqrt(width) with probability 1 - 2^-depth.
    Curve metrics are computed from the recovered histogram and add
    that count error to the histogram's O(1/bins) discretization."""

    kind = "count"
    _MASK = (1 << 61) - 1

    def __init__(
        self,
        metric_kind: str,
        pos: np.ndarray,
        neg: np.ndarray,
        bins: int,
        seed: int,
    ) -> None:
        self.metric_kind = metric_kind
        self.pos = pos            # (depth, width) signed counts
        self.neg = neg
        self.bins = int(bins)
        self.seed = int(seed)

    @classmethod
    def _hash_family(
        cls, depth: int, bins: int, width: int, seed: int
    ) -> tuple:
        rng = np.random.default_rng(int(seed))
        a = rng.integers(1, cls._MASK, size=(depth, 1), dtype=np.int64) | 1
        b = rng.integers(0, cls._MASK, size=(depth, 1), dtype=np.int64)
        cells = np.arange(bins, dtype=np.int64)[None, :]
        h = (a * cells + b) & cls._MASK
        cols = (h % width).astype(np.int64)                 # (depth, bins)
        signs = (((h >> 32) & 1) * 2 - 1).astype(np.int64)  # (depth, bins)
        return cols, signs

    @classmethod
    def from_samples(
        cls,
        metric_kind: str,
        scores: np.ndarray,
        targets: np.ndarray,
        *,
        width: int = 1024,
        depth: int = 5,
        bins: int = 8192,
        seed: int = 0,
    ) -> "CountSketchState":
        if width < 1 or depth < 1:
            raise ValueError(
                f"width/depth must be >= 1, got {width}/{depth}"
            )
        scores = np.asarray(scores, dtype=np.float64).reshape(-1)
        targets = np.asarray(targets).reshape(-1)
        idx = np.clip((scores * bins).astype(np.int64), 0, bins - 1)
        is_pos = targets > 0.5
        pos_counts = np.bincount(idx[is_pos], minlength=bins)
        neg_counts = np.bincount(idx[~is_pos], minlength=bins)
        cols, signs = cls._hash_family(depth, bins, width, seed)
        pos = np.zeros((depth, width), dtype=np.int64)
        neg = np.zeros((depth, width), dtype=np.int64)
        for r in range(depth):
            np.add.at(pos[r], cols[r], signs[r] * pos_counts)
            np.add.at(neg[r], cols[r], signs[r] * neg_counts)
        return cls(metric_kind, pos, neg, bins, seed)

    def merge(self, other: "Sketch") -> "CountSketchState":
        self._check_mergeable(other)
        if (
            other.pos.shape != self.pos.shape
            or other.bins != self.bins
            or other.seed != self.seed
        ):
            raise ValueError(
                "count-sketch geometry mismatch: both sides must share "
                "width/depth/bins/seed"
            )
        self.pos = self.pos + other.pos
        self.neg = self.neg + other.neg
        return self

    def nbytes(self) -> int:
        return int(self.pos.nbytes + self.neg.nbytes)

    def _recover(self, mat: np.ndarray) -> np.ndarray:
        depth, width = mat.shape
        cols, signs = self._hash_family(depth, self.bins, width, self.seed)
        readings = signs * np.take_along_axis(mat, cols, axis=1)
        return np.maximum(np.median(readings, axis=0), 0.0)

    def compute(self) -> Any:
        return _compute_from_bins(
            self.metric_kind, self._recover(self.pos), self._recover(self.neg)
        )


class RankSketch(Sketch):
    """Mergeable rank-sketch counts — the binned sufficient statistics
    of the curve family as a wire payload.

    ``num_tp``/``num_fp`` are ``(rows, bins)`` per-edge ge-counts,
    ``num_pos``/``num_total`` per-row scalars, over the shared ``edges``
    vector.  Merge is elementwise integer addition after a geometry
    check: exactly associative/commutative, so every merge order (fleet
    tree, flat gather, checkpoint resume) produces bit-identical counts
    and therefore a bit-identical compute.  The estimate itself carries
    the rank-sketch bound: error ≤ 1/(bins-1)
    (:func:`torcheval_tpu.ops.rank_sketch.rank_error_bound`)."""

    kind = "rank"

    def __init__(
        self,
        metric_kind: str,
        edges: np.ndarray,
        num_tp: np.ndarray,
        num_fp: np.ndarray,
        num_pos: np.ndarray,
        num_total: np.ndarray,
        average: Optional[str] = None,
    ) -> None:
        self.metric_kind = metric_kind
        self.edges = np.asarray(edges, dtype=np.float32)
        self.num_tp = np.asarray(num_tp, dtype=np.int64)
        self.num_fp = np.asarray(num_fp, dtype=np.int64)
        self.num_pos = np.asarray(num_pos, dtype=np.int64)
        self.num_total = np.asarray(num_total, dtype=np.int64)
        self.average = average

    @classmethod
    def from_samples(
        cls,
        metric_kind: str,
        scores: np.ndarray,
        targets: np.ndarray,
        *,
        bins: int = 512,
    ) -> "RankSketch":
        """Bin a flat sample buffer into rank counts with the *same*
        arithmetic as the device kernel (``searchsorted`` over the f32
        uniform edges, suffix sums), so a buffer-mode rank sketch and a
        ``sketch=True`` metric over the same stream agree bit-for-bit."""
        from torcheval_tpu.ops.rank_sketch import uniform_edges

        # The device edge constructor, so the f32 edge values (and hence
        # every boundary comparison) match a sketch=True metric exactly.
        edges = np.asarray(uniform_edges(bins))
        scores = np.asarray(scores, dtype=np.float32).reshape(-1)
        targets = np.asarray(targets).reshape(-1)
        idx = np.searchsorted(edges, scores, side="right")
        is_pos = targets > 0.5
        cells = np.bincount(idx, minlength=bins + 1).astype(np.int64)
        tp_cells = np.bincount(idx[is_pos], minlength=bins + 1).astype(np.int64)
        num_ge = np.cumsum(cells[::-1])[::-1][1:]
        num_tp = np.cumsum(tp_cells[::-1])[::-1][1:]
        return cls(
            metric_kind,
            edges,
            num_tp[None, :],
            (num_ge - num_tp)[None, :],
            np.asarray([int(is_pos.sum())]),
            np.asarray([scores.shape[0]]),
        )

    def merge(self, other: "Sketch") -> "RankSketch":
        self._check_mergeable(other)
        if (
            other.edges.shape != self.edges.shape
            or other.num_tp.shape != self.num_tp.shape
        ):
            raise ValueError(
                "rank-sketch geometry mismatch: both sides must share the "
                f"edge vector and row count ({self.num_tp.shape} vs "
                f"{other.num_tp.shape})"
            )
        self.num_tp = self.num_tp + other.num_tp
        self.num_fp = self.num_fp + other.num_fp
        self.num_pos = self.num_pos + other.num_pos
        self.num_total = self.num_total + other.num_total
        return self

    def nbytes(self) -> int:
        return int(
            self.edges.nbytes
            + self.num_tp.nbytes
            + self.num_fp.nbytes
            + self.num_pos.nbytes
            + self.num_total.nbytes
        )

    def compute(self) -> Any:
        import jax.numpy as jnp

        from torcheval_tpu.metrics.functional.classification.binned_auc import (
            _binned_auprc_from_counts,
            _binned_auroc_from_counts,
        )

        args = (
            jnp.asarray(self.num_tp, jnp.int32),
            jnp.asarray(self.num_fp, jnp.int32),
            jnp.asarray(self.num_pos, jnp.int32),
            jnp.asarray(self.num_total, jnp.int32),
        )
        if self.metric_kind in ("binary_auroc", "multiclass_auroc"):
            score = _binned_auroc_from_counts(*args)
        elif self.metric_kind == "binary_auprc":
            score = _binned_auprc_from_counts(*args)
        else:
            raise ValueError(
                f"unknown rank-sketched metric kind {self.metric_kind!r}"
            )
        if self.metric_kind == "multiclass_auroc":
            return score.mean() if self.average == "macro" else score
        return score[0] if score.shape[0] == 1 else score


def state_nbytes(metric: Any) -> int:
    """Wire-size proxy for a metric: total bytes of its state arrays."""
    total = 0
    for value in metric.state_dict().values():
        if isinstance(value, (list, tuple)):
            total += sum(int(np.asarray(v).nbytes) for v in value)
        elif isinstance(value, dict):
            total += sum(int(np.asarray(v).nbytes) for v in value.values())
        else:
            total += int(np.asarray(value).nbytes)
    return total


_SAMPLE_KINDS = ("exact", "reservoir", "histogram", "count", "rank")


def sketch_from_buffers(
    metric: Any,
    metric_kind: str,
    kind: str,
    *,
    capacity: int = 4096,
    bins: int = 1024,
    width: int = 1024,
    depth: int = 5,
    seed: int = 0,
    salt: int = 0,
) -> Sketch:
    """Build a sketch from a buffer metric's ``inputs``/``targets`` lists
    — the shared implementation behind the BinaryAUROC/BinaryAUPRC
    ``sketch_state`` overrides."""
    if kind not in _SAMPLE_KINDS:
        raise ValueError(
            f"sketch kind must be one of {_SAMPLE_KINDS}, got {kind!r}"
        )
    if kind == "exact":
        return ExactSketch.from_metric(metric)
    if getattr(metric, "num_tasks", 1) != 1:
        raise ValueError(
            "compressed sketches support num_tasks=1 only; "
            "use kind='exact' for multi-task buffers"
        )
    if metric.inputs:
        scores = np.concatenate(
            [np.asarray(v).reshape(-1) for v in metric.inputs]
        )
        targets = np.concatenate(
            [np.asarray(v).reshape(-1) for v in metric.targets]
        )
    else:
        scores = np.zeros(0, dtype=np.float32)
        targets = np.zeros(0, dtype=np.float32)
    if kind == "reservoir":
        return ReservoirSketch.from_samples(
            metric_kind, scores, targets,
            capacity=capacity, seed=seed, salt=salt,
        )
    if kind == "histogram":
        return HistogramSketch.from_samples(
            metric_kind, scores, targets, bins=bins
        )
    if kind == "rank":
        # bins defaults to the shared 1024 here; pass bins=512 (the
        # sketch=True construction default) for bit-parity with a
        # device rank-sketch metric.
        return RankSketch.from_samples(metric_kind, scores, targets, bins=bins)
    return CountSketchState.from_samples(
        metric_kind, scores, targets,
        width=width, depth=depth, seed=seed,
    )


def merge_sketches(
    base: Sketch, others: Iterable[Optional[Sketch]]
) -> Sketch:
    """Fold ``others`` (Nones skipped — excised ranks) into ``base``."""
    for other in others:
        if other is not None:
            base.merge(other)
    return base
