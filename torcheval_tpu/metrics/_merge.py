"""Shared merge helpers for counter-state metrics.

Counter states merge by elementwise addition — the property that lets the
sync toolkit reduce them with a single fused ``psum`` over the mesh axis
instead of gathering buffers."""

from typing import Iterable

import jax

from torcheval_tpu.metrics.metric import Metric


def merge_add(metric: Metric, metrics: Iterable[Metric], *state_names: str) -> None:
    """Add each named counter state of ``metrics`` into ``metric``."""
    for other in metrics:
        for name in state_names:
            setattr(
                metric,
                name,
                getattr(metric, name)
                + jax.device_put(getattr(other, name), metric.device),
            )
