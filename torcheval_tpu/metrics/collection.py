"""MetricCollection — one object driving a named set of metrics.

The reference has no collection type (each metric is updated by hand in
the training loop, reference ``examples/simple_example.py:67``); tracking
five metrics means five update calls and five compute calls.  A collection
makes the common case one line, and under this framework each member's
update is already a single fused dispatch (``_fuse.py``), so a collection
update costs exactly one program launch per member with no extra host
round trips.

State-dict keys are namespaced ``"{name}/{state}"`` so a collection
checkpoints like any single metric (orbax-compatible flat mapping).
"""

import copy
import time
from contextlib import nullcontext as _nullcontext
from typing import (
    Any,
    Dict,
    Iterable,
    Iterator,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

import jax
import jax.numpy as jnp

from torcheval_tpu._stats import bump_trace
from torcheval_tpu.metrics._bucket import DEFAULT_MIN_BUCKET, pad_to_bucket
from torcheval_tpu.metrics.functional._host_checks import all_concrete
from torcheval_tpu.metrics.metric import Metric, _move_state
from torcheval_tpu.ops import _flags
from torcheval_tpu.telemetry import events as _telemetry
from torcheval_tpu.telemetry import health as _health
from torcheval_tpu.telemetry import perfscope as _perfscope


def _call_signature(args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> Any:
    """Hashable shape/dtype signature of one fused call — mirrors the jit
    cache key (structure, shapes, dtypes, weak types) closely enough that
    a previously-seen signature implies a compiled-program cache hit.  A
    hit means no trace can run, which is what lets ``fused_update`` skip
    the per-step fusability sweep on the steady state."""
    leaves, treedef = jax.tree.flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is None:
            sig.append((type(leaf).__name__,))
        else:
            sig.append(
                (
                    tuple(shape),
                    str(leaf.dtype),
                    bool(getattr(leaf, "weak_type", False)),
                )
            )
    return (treedef, tuple(sig))


class MetricCollection:
    """A named, ordered set of metrics updated with the same batch.

    All members must accept the same ``update(*args, **kwargs)``
    signature (e.g. ``(input, target)`` classification metrics).

    ``bucket=True`` pads every update batch's leading dim up to a
    power-of-two bucket (``metrics/_bucket.py``) and threads the validity
    mask into every member — a ragged stream of M distinct batch sizes
    then costs O(log max_batch) compiled programs instead of M.  Every
    member must be mask-aware (``Metric._supports_mask``).

    ``donate`` controls buffer donation of the fused-update state operand
    (``None`` follows :func:`torcheval_tpu.ops._flags.donation_enabled`):
    XLA aliases old→new member states in place, halving state HBM
    traffic per batch.

    ``slices=K`` adds a slice axis: every update additionally carries a
    per-row ``slice_ids=`` int vector (values in ``[0, K)``), and the
    collection maintains K per-slice clones of each member alongside the
    global one.  Slice restriction is a masked segment reduction *inside
    the same traced program* — clone ``k`` updates with
    ``mask * (slice_ids == k)``, reusing the validity-mask plumbing of
    ``metrics/_bucket.py`` — so ONE fused/scan dispatch computes the
    global figures and all K slices with no extra HBM passes over the
    batch.  Read per-slice results with :meth:`compute_slices`.  Every
    member must be mask-aware.
    """

    def __init__(
        self,
        metrics: Mapping[str, Metric],
        *,
        bucket: bool = False,
        min_bucket: int = DEFAULT_MIN_BUCKET,
        donate: Optional[bool] = None,
        slices: Optional[int] = None,
        slice_labels: Optional[Sequence[str]] = None,
    ) -> None:
        if not metrics:
            raise ValueError("MetricCollection requires at least one metric.")
        if slices is None and slice_labels is not None:
            raise ValueError("slice_labels requires slices=.")
        if slices is not None and slices < 1:
            raise ValueError(f"slices must be >= 1, got {slices}.")
        for name, metric in metrics.items():
            if not isinstance(metric, Metric):
                raise TypeError(
                    f"MetricCollection values must be Metric instances, got "
                    f"{name}={type(metric).__name__}."
                )
            if "/" in name:
                # "/" is the state_dict namespace separator; a name
                # containing it could not round-trip through checkpoints.
                raise ValueError(
                    f"Metric names must not contain '/', got {name!r}."
                )
            if slices is not None and "@" in name:
                # "@" namespaces per-slice clones in state_dict keys
                # ("name@k/state"); a member name containing it could
                # not round-trip.
                raise ValueError(
                    f"Metric names must not contain '@' when slices= is "
                    f"set, got {name!r}."
                )
            if bucket and not metric._supports_mask:
                raise ValueError(
                    f"bucket=True requires mask-aware members; "
                    f"{name}={type(metric).__name__} does not support "
                    f"update(..., mask=)."
                )
            if slices is not None and not metric._supports_mask:
                raise ValueError(
                    f"slices= requires mask-aware members (slice "
                    f"restriction is a masked reduction); "
                    f"{name}={type(metric).__name__} does not support "
                    f"update(..., mask=)."
                )
        self._metrics: Dict[str, Metric] = dict(metrics)
        self._bucket = bool(bucket)
        self._min_bucket = int(min_bucket)
        self._donate = donate
        self._slices: Optional[int] = None if slices is None else int(slices)
        if slices is None:
            self._slice_labels: Tuple[str, ...] = ()
            self._slice_members: Dict[str, Metric] = {}
        else:
            labels = (
                tuple(str(v) for v in slice_labels)
                if slice_labels is not None
                else tuple(str(k) for k in range(slices))
            )
            if len(labels) != slices:
                raise ValueError(
                    f"slice_labels must name all {slices} slices; got "
                    f"{len(labels)}."
                )
            if len(set(labels)) != len(labels):
                raise ValueError(f"slice_labels must be unique; got {labels}.")
            self._slice_labels = labels
            # Per-slice clones: independent state, identical config.
            self._slice_members = {
                f"{name}@{k}": copy.deepcopy(metric)
                for name, metric in self._metrics.items()
                for k in range(slices)
            }
        # Every state-carrying member — plain metrics plus slice clones —
        # under its state_dict namespace key.
        self._all_members: Dict[str, Metric] = dict(self._metrics)
        self._all_members.update(self._slice_members)
        self._fused_apply: Optional[Any] = None
        self._fused_apply_donated: Optional[bool] = None
        self._fused_apply_health: Optional[bool] = None
        self._fused_apply_token: Optional[Any] = None
        self._health_bounds: Tuple[Tuple[str, int], ...] = ()
        # The fused paths read every member state once per step; a
        # precomputed (name, metric, state-names) layout makes that a
        # flat loop instead of rebuilding the registry iteration each
        # time.  Members register all states in __init__, so the layout
        # is fixed for the collection's lifetime.
        self._state_layout: Tuple[Tuple[str, Metric, Tuple[str, ...]], ...] = (
            tuple(
                (name, m, tuple(m._state_name_to_default))
                for name, m in self._all_members.items()
            )
        )
        # Call signatures fused_update has already executed.  A hit means
        # the jitted program is compiled-cache resident — no trace can
        # run — so the per-step fusability sweep is skipped.
        self._fused_seen: set = set()

    def _bucket_args(
        self, args: Tuple[Any, ...], kwargs: Dict[str, Any]
    ) -> Tuple[Tuple[Any, ...], Dict[str, Any]]:
        """Pad positional batch arrays to their bucket and merge the
        validity mask into ``kwargs`` (combining with a caller-supplied
        ``mask=`` if present)."""
        if not self._bucket or not args:
            return args, kwargs
        kwargs = dict(kwargs)
        mask = kwargs.pop("mask", None)
        slice_ids = kwargs.pop("slice_ids", None)
        if slice_ids is not None:
            # The slice-id vector is a per-row array: pad it alongside
            # the batch (edge-replicated pad rows are harmless — the
            # mask zeroes them out of every slice).
            padded, mask = pad_to_bucket(
                *args, slice_ids, mask=mask, min_bucket=self._min_bucket
            )
            args, kwargs["slice_ids"] = padded[:-1], padded[-1]
        else:
            args, mask = pad_to_bucket(
                *args, mask=mask, min_bucket=self._min_bucket
            )
        kwargs["mask"] = mask
        return args, kwargs

    # ------------------------------------------------------------- container
    def __getitem__(self, name: str) -> Metric:
        return self._metrics[name]

    def __iter__(self) -> Iterator[str]:
        return iter(self._metrics)

    def __len__(self) -> int:
        return len(self._metrics)

    def items(self) -> Iterable[Tuple[str, Metric]]:
        return self._metrics.items()

    # ------------------------------------------------------------- lifecycle
    @property
    def slices(self) -> Optional[int]:
        """Number of slices, or ``None`` for an unsliced collection."""
        return self._slices

    @property
    def slice_labels(self) -> Tuple[str, ...]:
        """Slice labels in slice-id order (empty when unsliced)."""
        return self._slice_labels

    def _trace_update(
        self, args: Tuple[Any, ...], kwargs: Dict[str, Any]
    ) -> None:
        """The one update body shared by every path — plain ``update``,
        the fused program, and the engine scan step.  Global members see
        the base validity mask; slice clone ``k`` sees
        ``mask * (slice_ids == k)`` — a masked segment reduction, so the
        slice axis adds arithmetic to the SAME program instead of extra
        dispatches or HBM passes."""
        kwargs = dict(kwargs)
        slice_ids = kwargs.pop("slice_ids", None)
        if self._slices is None:
            if slice_ids is not None:
                raise TypeError(
                    "slice_ids= passed to an unsliced MetricCollection; "
                    "construct it with slices=K first."
                )
            handled = self._maybe_megakernel(args, kwargs, None)
            for name, m in self._metrics.items():
                if name not in handled:
                    m.update(*args, **kwargs)
            return
        if slice_ids is None:
            raise TypeError(
                f"This MetricCollection has slices={self._slices}; every "
                "update must carry slice_ids= (per-row int vector in "
                f"[0, {self._slices}))."
            )
        base_mask = kwargs.pop("mask", None)
        sids = jnp.asarray(slice_ids)
        if base_mask is not None:
            kwargs["mask"] = base_mask
        handled = self._maybe_megakernel(args, kwargs, sids)
        for name, m in self._metrics.items():
            if name not in handled:
                m.update(*args, **kwargs)
        for k in range(self._slices):
            smask = (sids == k).astype(jnp.int32)
            if base_mask is not None:
                smask = smask * base_mask
            kwargs["mask"] = smask
            for name in self._metrics:
                if name not in handled:
                    self._slice_members[f"{name}@{k}"].update(*args, **kwargs)

    def _maybe_megakernel(
        self, args: Tuple[Any, ...], kwargs: Dict[str, Any], sids
    ) -> frozenset:
        """Run every megakernel-supported member's update in ONE Pallas
        dispatch (one HBM pass over the batch for all of them, slice
        clones included) and return the handled member names; the caller
        runs only the rest on the per-member path.

        Engages only under tracing — exactly the three compiled hot
        paths (``fused_update``, the engine scan block, serve's shared
        bundles).  The plain eager ``update()`` keeps full per-member
        value validation, whose host checks could not run at trace time
        anyway.  ``ops/_mega_plan.plan_for`` owns the flag/backend/shape
        gating, so this preview-able decision matches the route token
        the hot paths fold into their program-cache keys."""
        from torcheval_tpu.ops import _mega_plan

        plan = _mega_plan.plan_for(self._metrics, args, kwargs, self._slices)
        if plan is None:
            return frozenset()
        mask = kwargs.get("mask")
        probe = [x for x in args + (mask, sids) if x is not None]
        if all_concrete(*probe):
            return frozenset()
        from torcheval_tpu.ops import pallas_mega

        pallas_mega.run_plan(
            plan, self._metrics, self._slice_members, args, mask, sids
        )
        return plan.member_names

    def update(self, *args: Any, **kwargs: Any) -> "MetricCollection":
        args, kwargs = self._bucket_args(args, kwargs)
        self._trace_update(args, kwargs)
        return self

    def fused_update(self, *args: Any, **kwargs: Any) -> "MetricCollection":
        """Update every member in ONE XLA program.

        ``update`` already costs one fused dispatch per member
        (``_fuse.py``); this goes one further and traces all members'
        updates into a single jitted program, so a five-metric collection
        pays one program launch per batch instead of five.  Member updates
        are pure state transitions, which is exactly what makes them
        traceable together.

        Restrictions (checked up front): every member state must be a
        ``jax.Array`` — sample-buffer members (Python-list states) would
        leak tracers, and ring-window members would bake their host-side
        column cursor into the trace as a constant.  Data-dependent value
        validation is skipped inside the trace (exactly as when composing
        the functional metrics into a user jit program); shape/parameter
        validation still applies."""
        args, kwargs = self._bucket_args(args, kwargs)
        donate = (
            self._donate
            if self._donate is not None
            else _flags.donation_enabled()
        )
        health = _health.ENABLED
        from torcheval_tpu.ops import _mega_plan

        # The megakernel decision is previewable from shapes/dtypes
        # alone, so the same plan_for call that routes inside the trace
        # also names the program here (for perfscope/trace counters) —
        # and the route token joins the rebuild condition so flag or
        # backend flips — or a routing_autotune epoch bump — retrace
        # instead of reusing a stale route.
        token = _mega_plan.route_token()
        program = (
            "mega_collection"
            if _mega_plan.plan_for(self._metrics, args, kwargs, self._slices)
            is not None
            else "fused_collection"
        )
        if (
            self._fused_apply is None
            or self._fused_apply_donated != donate
            or self._fused_apply_health != health
            or self._fused_apply_token != token
        ):
            metrics = self._metrics
            # With the monitor off the program is byte-identical to a
            # build without health.py: no side outputs, no extra
            # dispatches (the zero-cost-when-off contract).
            bounds = _health.label_bounds(metrics) if health else ()

            def apply(states, a, kw):
                bump_trace(
                    "mega_collection"
                    if _mega_plan.plan_for(
                        self._metrics, a, kw, self._slices
                    )
                    is not None
                    else "fused_collection"
                )
                for name, m in self._all_members.items():
                    for s, v in states[name].items():
                        setattr(m, s, v)
                self._trace_update(a, kw)
                if health:
                    return (
                        self._read_states(),
                        _health.stats_for_update(a, kw, bounds),
                    )
                return self._read_states()

            self._fused_apply = jax.jit(
                apply, donate_argnums=(0,) if donate else ()
            )
            self._fused_apply_donated = donate
            self._fused_apply_health = health
            self._fused_apply_token = token
            self._health_bounds = bounds
            self._fused_seen = set()
        key = _call_signature(args, kwargs)
        first_at_signature = key not in self._fused_seen
        if first_at_signature:
            # Only a first-at-this-signature call can trace; the steady
            # state (compiled-cache hit) skips the O(members x states)
            # fusability sweep.
            self._check_fusable()
        before = self._read_states()
        t0 = time.monotonic() if _telemetry.ENABLED else 0.0
        # A first donated call may compile; donated executables must not
        # enter the persistent compilation cache (ROADMAP item 6), so the
        # compile runs under the scoped bypass.  Steady state never
        # enters the context.
        bypass = (
            _flags.cache_bypass()
            if donate and first_at_signature
            else _nullcontext()
        )
        try:
            with bypass:
                out = self._fused_apply(before, args, kwargs)
        except BaseException:
            # An aborted trace (including KeyboardInterrupt mid-compile)
            # leaves tracer attrs on members; restore the concrete states.
            # Under donation an abort can also land AFTER the donated
            # buffers were consumed — any deleted snapshot entry falls
            # back to the member's registered default (a fresh reset
            # state), keeping every state attribute concrete + readable.
            if _telemetry.ENABLED and donate:
                _telemetry.record_donation("abort")
            # tpulint: disable=TPU004 -- abort-restore reads `before` with guard_deleted=True: deleted entries fall back to reset defaults
            self._install_states(before, guard_deleted=True)
            raise
        self._fused_seen.add(key)
        if self._fused_apply_health:
            new_states, health_stats = out
        else:
            new_states, health_stats = out, None
        self._install_states(new_states)
        if _perfscope.ENABLED:
            # Priced once per (signature, build flags); the steady state
            # pays one set lookup.  Shadow lowering works from avals, so
            # donated-and-deleted `before` entries are fine — but the
            # re-trace setattrs tracers onto the live members, so the
            # concrete states must be re-installed when pricing ran.
            profiled = _perfscope.profile_program(
                program,
                self._fused_apply,
                # tpulint: disable=TPU004 -- shadow lowering reads avals only; deleted donated buffers still carry shape/dtype
                (before, args, kwargs),
                batch_args=(args, kwargs),
                donate=donate,
                signature=(key, donate, health, token),
            )
            if profiled is not None:
                self._install_states(new_states)
        if _telemetry.ENABLED:
            _telemetry.record_span(
                "update",
                "MetricCollection.fused",
                time.monotonic() - t0,
                sum(
                    _telemetry.state_nbytes(m)
                    for m in self._all_members.values()
                ),
            )
        if health_stats is not None:
            # After _install_states: a raise-on-corrupt escalation must
            # not leave tracer/deleted states behind — the batch was
            # applied, the monitor only reports it.
            # tpulint: disable=TPU001 -- health_stats is non-None only when the program was built with health=_health.ENABLED
            _health.inspect(
                health_stats,
                source="fused_update",
                bounds=self._health_bounds,
            )
        return self

    def _check_fusable(self) -> None:
        from torcheval_tpu.metrics._buffer import RingWindowMixin

        for name, m in self._all_members.items():
            if isinstance(m, RingWindowMixin):
                raise ValueError(
                    f"fused_update does not support windowed member {name!r}: "
                    "its host-side ring cursor would become a trace constant."
                )
            for s in m._state_name_to_default:
                if not isinstance(getattr(m, s), jax.Array):
                    raise ValueError(
                        f"fused_update requires array states; member {name!r} "
                        f"state {s!r} is {type(getattr(m, s)).__name__}. "
                        "Use update() for buffer-state metrics."
                    )

    def _read_states(self) -> Dict[str, Dict[str, Any]]:
        return {
            name: {s: getattr(m, s) for s in states}
            for name, m, states in self._state_layout
        }

    def _install_states(
        self, states: Dict[str, Dict[str, Any]], guard_deleted: bool = False
    ) -> None:
        for name, per_state in states.items():
            m = self._all_members[name]
            for s, v in per_state.items():
                if (
                    guard_deleted
                    and isinstance(v, jax.Array)
                    and v.is_deleted()
                ):
                    v = _move_state(
                        m._state_name_to_default[s], m._device, fresh=True
                    )
                    if _telemetry.ENABLED:
                        # The donated buffer was consumed before the
                        # abort; this state restarts from its registered
                        # default — an operator-visible data-loss event.
                        _telemetry.record_donation("restore")
                setattr(m, s, v)

    def compute(self) -> Dict[str, Any]:
        # Members' own compute spans fire inside this loop (metric.py's
        # phase wrapper); no collection-level span, which would double
        # count every member.
        return {name: m.compute() for name, m in self._metrics.items()}

    def compute_slices(self) -> Dict[str, Dict[str, Any]]:
        """Per-slice results: ``{slice_label: {metric_name: value}}``,
        labels in slice-id order.  The global (unsliced) figures stay in
        :meth:`compute`."""
        if self._slices is None:
            raise ValueError(
                "compute_slices() on an unsliced MetricCollection; "
                "construct it with slices=K first."
            )
        return {
            label: {
                name: self._slice_members[f"{name}@{k}"].compute()
                for name in self._metrics
            }
            for k, label in enumerate(self._slice_labels)
        }

    def reset(self) -> "MetricCollection":
        for metric in self._all_members.values():
            metric.reset()
        return self

    def merge_state(
        self, collections: Iterable["MetricCollection"]
    ) -> "MetricCollection":
        """Merge same-shaped collections memberwise (each member follows its
        own ``merge_state`` semantics — add, concat, max, window-grow).

        Members must be the same metric type under each name AND identically
        configured (same ``average``/``num_classes``/...): per-metric
        ``merge_state`` assumes identically-configured sources, here exactly
        as in the reference (``metric.py:91-110``)."""
        collections = list(collections)
        for other in collections:
            if set(other._metrics) != set(self._metrics):
                raise ValueError(
                    "Merged collections must hold the same metric names; got "
                    f"{sorted(self._metrics)} vs {sorted(other._metrics)}."
                )
            if (
                other._slices != self._slices
                or other._slice_labels != self._slice_labels
            ):
                raise ValueError(
                    "Merged collections must share the slice axis; got "
                    f"slices={self._slices} labels={self._slice_labels} vs "
                    f"slices={other._slices} labels={other._slice_labels}."
                )
            for name, metric in self._metrics.items():
                if type(other._metrics[name]) is not type(metric):
                    raise ValueError(
                        f"Member {name!r} is {type(metric).__name__} here but "
                        f"{type(other._metrics[name]).__name__} in a merged "
                        "collection."
                    )
        for name, metric in self._all_members.items():
            metric.merge_state(
                [other._all_members[name] for other in collections]
            )
        return self

    # ------------------------------------------------------- toolkit compat
    # The sync toolkit treats a collection like any metric object: it is
    # pickled whole through the process group, pre-concatenated via
    # _prepare_for_merge_state, moved with to(), and merged memberwise.
    @property
    def device(self) -> Any:
        return next(iter(self._metrics.values())).device

    def _prepare_for_merge_state(self) -> None:
        for metric in self._all_members.values():
            metric._prepare_for_merge_state()

    # ----------------------------------------------------------- checkpoint
    def state_dict(self) -> Dict[str, Any]:
        # Slice clones checkpoint under "name@k/state" alongside the
        # global "name/state" keys, so a sliced collection round-trips
        # through the same flat mapping.
        out: Dict[str, Any] = {}
        for name, metric in self._all_members.items():
            for key, value in metric.state_dict().items():
                out[f"{name}/{key}"] = value
        return out

    def load_state_dict(
        self, state_dict: Mapping[str, Any], strict: bool = True
    ) -> None:
        per_metric: Dict[str, Dict[str, Any]] = {
            name: {} for name in self._all_members
        }
        unexpected = []
        for key, value in state_dict.items():
            name, _, state_key = key.partition("/")
            if name in per_metric and state_key:
                per_metric[name][state_key] = value
            else:
                unexpected.append(key)
        if strict:
            problems = []
            if unexpected:
                problems.append(
                    f"Unexpected keys in state_dict: {sorted(unexpected)}"
                )
            # A member with zero keys would silently keep its current
            # state — raise up front (before any member loads) so a
            # partially-written checkpoint cannot half-install.
            missing_members = sorted(
                name
                for name, states in per_metric.items()
                if not states
                and self._all_members[name]._state_name_to_default
            )
            if missing_members:
                problems.append(
                    "state_dict is missing every state of member(s) "
                    f"{missing_members}"
                )
            if problems:
                raise RuntimeError("; ".join(problems) + ".")
        # Atomic install: a failure on ANY member (including a strict
        # mismatch raised AFTER that member set some of its states) rolls
        # every already-touched member back to its pre-call arrays, so a
        # bad checkpoint can never leave the collection half-mutated.
        snapshots = {
            name: {
                s: getattr(metric, s)
                for s in metric._state_name_to_default
                if hasattr(metric, s)
            }
            for name, metric in self._all_members.items()
        }
        try:
            for name, metric in self._all_members.items():
                metric.load_state_dict(per_metric[name], strict=strict)
        except BaseException:
            for name, metric in self._all_members.items():
                for s, value in snapshots[name].items():
                    setattr(metric, s, value)
            raise

    def to(self, device: Any) -> "MetricCollection":
        for metric in self._all_members.values():
            metric.to(device)
        return self

    # The jitted fused-update program is a local closure — unpicklable, and
    # meaningless in another process anyway.  Drop it from the pickle the
    # sync toolkit ships and rebuild lazily on next fused_update.
    def __getstate__(self) -> Dict[str, Any]:
        state = self.__dict__.copy()
        state["_fused_apply"] = None
        # Seen signatures hold treedefs (unpicklable) and describe a jit
        # cache that dies with this process anyway.
        state["_fused_seen"] = set()
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)

    def __repr__(self) -> str:
        inner = ", ".join(
            f"{name}={type(m).__name__}" for name, m in self._metrics.items()
        )
        if self._slices is not None:
            inner += f", slices={self._slices}"
        return f"MetricCollection({inner})"
