"""MetricCollection — one object driving a named set of metrics.

The reference has no collection type (each metric is updated by hand in
the training loop, reference ``examples/simple_example.py:67``); tracking
five metrics means five update calls and five compute calls.  A collection
makes the common case one line, and under this framework each member's
update is already a single fused dispatch (``_fuse.py``), so a collection
update costs exactly one program launch per member with no extra host
round trips.

State-dict keys are namespaced ``"{name}/{state}"`` so a collection
checkpoints like any single metric (orbax-compatible flat mapping).
"""

from typing import Any, Dict, Iterable, Iterator, Mapping, Tuple

from torcheval_tpu.metrics.metric import Metric


class MetricCollection:
    """A named, ordered set of metrics updated with the same batch.

    All members must accept the same ``update(*args, **kwargs)``
    signature (e.g. ``(input, target)`` classification metrics).
    """

    def __init__(self, metrics: Mapping[str, Metric]) -> None:
        if not metrics:
            raise ValueError("MetricCollection requires at least one metric.")
        for name, metric in metrics.items():
            if not isinstance(metric, Metric):
                raise TypeError(
                    f"MetricCollection values must be Metric instances, got "
                    f"{name}={type(metric).__name__}."
                )
            if "/" in name:
                # "/" is the state_dict namespace separator; a name
                # containing it could not round-trip through checkpoints.
                raise ValueError(
                    f"Metric names must not contain '/', got {name!r}."
                )
        self._metrics: Dict[str, Metric] = dict(metrics)

    # ------------------------------------------------------------- container
    def __getitem__(self, name: str) -> Metric:
        return self._metrics[name]

    def __iter__(self) -> Iterator[str]:
        return iter(self._metrics)

    def __len__(self) -> int:
        return len(self._metrics)

    def items(self) -> Iterable[Tuple[str, Metric]]:
        return self._metrics.items()

    # ------------------------------------------------------------- lifecycle
    def update(self, *args: Any, **kwargs: Any) -> "MetricCollection":
        for metric in self._metrics.values():
            metric.update(*args, **kwargs)
        return self

    def compute(self) -> Dict[str, Any]:
        return {name: m.compute() for name, m in self._metrics.items()}

    def reset(self) -> "MetricCollection":
        for metric in self._metrics.values():
            metric.reset()
        return self

    def merge_state(
        self, collections: Iterable["MetricCollection"]
    ) -> "MetricCollection":
        """Merge same-shaped collections memberwise (each member follows its
        own ``merge_state`` semantics — add, concat, max, window-grow).

        Members must be the same metric type under each name AND identically
        configured (same ``average``/``num_classes``/...): per-metric
        ``merge_state`` assumes identically-configured sources, here exactly
        as in the reference (``metric.py:91-110``)."""
        collections = list(collections)
        for other in collections:
            if set(other._metrics) != set(self._metrics):
                raise ValueError(
                    "Merged collections must hold the same metric names; got "
                    f"{sorted(self._metrics)} vs {sorted(other._metrics)}."
                )
            for name, metric in self._metrics.items():
                if type(other._metrics[name]) is not type(metric):
                    raise ValueError(
                        f"Member {name!r} is {type(metric).__name__} here but "
                        f"{type(other._metrics[name]).__name__} in a merged "
                        "collection."
                    )
        for name, metric in self._metrics.items():
            metric.merge_state([other._metrics[name] for other in collections])
        return self

    # ------------------------------------------------------- toolkit compat
    # The sync toolkit treats a collection like any metric object: it is
    # pickled whole through the process group, pre-concatenated via
    # _prepare_for_merge_state, moved with to(), and merged memberwise.
    @property
    def device(self) -> Any:
        return next(iter(self._metrics.values())).device

    def _prepare_for_merge_state(self) -> None:
        for metric in self._metrics.values():
            metric._prepare_for_merge_state()

    # ----------------------------------------------------------- checkpoint
    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, metric in self._metrics.items():
            for key, value in metric.state_dict().items():
                out[f"{name}/{key}"] = value
        return out

    def load_state_dict(
        self, state_dict: Mapping[str, Any], strict: bool = True
    ) -> None:
        per_metric: Dict[str, Dict[str, Any]] = {name: {} for name in self._metrics}
        unexpected = []
        for key, value in state_dict.items():
            name, _, state_key = key.partition("/")
            if name in per_metric and state_key:
                per_metric[name][state_key] = value
            else:
                unexpected.append(key)
        if strict and unexpected:
            raise RuntimeError(
                f"Unexpected keys in state_dict: {sorted(unexpected)}."
            )
        for name, metric in self._metrics.items():
            metric.load_state_dict(per_metric[name], strict=strict)

    def to(self, device: Any) -> "MetricCollection":
        for metric in self._metrics.values():
            metric.to(device)
        return self

    def __repr__(self) -> str:
        inner = ", ".join(
            f"{name}={type(m).__name__}" for name, m in self._metrics.items()
        )
        return f"MetricCollection({inner})"
