"""Fused update-accumulate dispatch for counter-state metrics.

A class-metric ``update()`` used to launch one device program for the
sufficient-statistic kernel and one more per state for the ``state + delta``
add — three or more dispatches per batch.  Each dispatch costs host→device
round-trip overhead (microseconds on a local PCIe host, milliseconds through
a tunneled backend), which dominates the microsecond-scale counter kernels.

``accumulate`` folds the kernel and every state add into ONE jitted program:
the per-update cost becomes a single dispatch regardless of how many states
the metric owns.  Input validation stays on the host, before the call (it
must raise eagerly — reference semantics, e.g. reference
``torcheval/metrics/functional/classification/confusion_matrix.py:245-280``).

Hot-path extensions (see ``_bucket.py`` / ``ops/_flags.py``):

* ``mask=`` threads a ragged-batch validity mask into mask-aware kernels
  (it rides as a trailing ``mask=`` keyword after the statics), so padded
  bucket rows add exact zeros to every counter.
* When :func:`torcheval_tpu.ops._flags.donation_enabled`, the state
  operand is donated (``donate_argnums=(0,)``): XLA aliases old→new state
  in place, halving HBM traffic on the add and peak memory for large
  states.  The caller's old state arrays are DELETED after execution —
  the metric base class copies registry defaults and checkpoint
  snapshots so no live reference ever dangles.
"""

import time
from contextlib import nullcontext
from functools import partial
from typing import Tuple

import jax

from torcheval_tpu._stats import bump_trace
from torcheval_tpu.telemetry import events as _telemetry

# Donated-program signatures whose first compile has already happened in
# this process.  The first donated compile per signature runs under
# ops._flags.cache_bypass (donated executables must not enter the JAX
# persistent compilation cache — ROADMAP item 6); steady-state calls hit
# the in-memory jit cache and never re-enter the bypass.
_donated_seen = set()


def _arr_sig(x):
    return (getattr(x, "shape", None), str(getattr(x, "dtype", "")))


def _maybe_bypass(kernel, states, args, statics, grow, fold, mask):
    """The persistent-cache bypass context for one donated call: active
    only the first time this process sees the (kernel, statics, shapes)
    signature — i.e. exactly around the compile."""
    from torcheval_tpu.ops._flags import cache_bypass

    key = (
        kernel,
        statics,
        grow,
        fold,
        tuple(_arr_sig(s) for s in states),
        tuple(_arr_sig(a) for a in args),
        _arr_sig(mask) if mask is not None else None,
    )
    if key in _donated_seen:
        return nullcontext()
    _donated_seen.add(key)
    return cache_bypass()


def _accumulate_impl(states, args, kernel, statics, grow, fold, mask=None):
    bump_trace("accumulate")
    if mask is None:
        deltas = kernel(*args, *statics)
    else:
        deltas = kernel(*args, *statics, mask=mask)
    if not isinstance(deltas, tuple):
        deltas = (deltas,)
    out = []
    for i, (s, d) in enumerate(zip(states, deltas)):
        if grow and s.ndim == 0 and d.ndim == 1:
            # Per-output regression states replace the scalar default on the
            # first 2-D update instead of broadcasting into it (reference
            # ``regression/mean_squared_error.py`` state-growth behavior).
            out.append(d)
        else:
            f = fold[i] if isinstance(fold, tuple) else fold
            out.append(s + d if f is None else f(s, d))
    return tuple(out)


_accumulate_jit = partial(jax.jit, static_argnames=("kernel", "statics", "grow", "fold"))(
    _accumulate_impl
)
_accumulate_jit_donated = partial(
    jax.jit,
    static_argnames=("kernel", "statics", "grow", "fold"),
    donate_argnums=(0,),
)(_accumulate_impl)


def accumulate(
    kernel,
    states: Tuple[jax.Array, ...],
    *args,
    statics: tuple = (),
    grow: bool = False,
    fold=None,
    mask=None,
) -> Tuple[jax.Array, ...]:
    """Run ``kernel(*args, *statics)`` and fold its delta(s) onto ``states``
    in one fused dispatch.

    ``kernel`` must be a module-level (jitted or plain) pure function — its
    identity is part of the jit cache key.  ``statics`` are hashable
    trace-time constants appended positionally after ``args``.  ``fold``
    combines ``(state, delta)`` and defaults to addition; pass e.g.
    ``jnp.minimum`` for extremum states (Min/Max), or a per-state tuple
    (``None`` entries mean addition) — give the tuple a stable module-level
    identity, since ``fold`` is part of the jit cache key.  ``grow=True``
    replicates the scalar→vector replace-on-first-2-D-update semantics of
    per-output regression states.  ``mask`` (a validity array, or ``None``)
    is forwarded to the kernel as a trailing ``mask=`` keyword — only pass
    it to mask-aware kernels.  Returns the new state tuple.

    Under :func:`~torcheval_tpu.ops._flags.donation_enabled` the ``states``
    buffers are donated to XLA and unusable afterwards; callers must (and
    the class metrics do) rebind their state attributes to the return
    value immediately.
    """
    from torcheval_tpu.ops._flags import donation_enabled

    states, args, statics = tuple(states), tuple(args), tuple(statics)
    if donation_enabled():
        fn = _accumulate_jit_donated
        ctx = _maybe_bypass(kernel, states, args, statics, grow, fold, mask)
    else:
        fn = _accumulate_jit
        ctx = nullcontext()
    if not _telemetry.ENABLED:
        with ctx:
            out = fn(states, args, kernel, statics, grow, fold, mask)
        return out
    # Telemetry on: the fused dispatch becomes a "dispatch" span named
    # after the kernel (dispatch wall time, NOT device time — steady
    # state it measures the jit cache hit + launch).
    t0 = time.monotonic()
    with ctx:
        out = fn(states, args, kernel, statics, grow, fold, mask)
    _telemetry.record_span(
        "dispatch",
        getattr(kernel, "__name__", str(kernel)),
        time.monotonic() - t0,
        sum(getattr(s, "nbytes", 0) for s in out),
    )
    return out