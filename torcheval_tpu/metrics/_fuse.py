"""Fused update-accumulate dispatch for counter-state metrics.

A class-metric ``update()`` used to launch one device program for the
sufficient-statistic kernel and one more per state for the ``state + delta``
add — three or more dispatches per batch.  Each dispatch costs host→device
round-trip overhead (microseconds on a local PCIe host, milliseconds through
a tunneled backend), which dominates the microsecond-scale counter kernels.

``accumulate`` folds the kernel and every state add into ONE jitted program:
the per-update cost becomes a single dispatch regardless of how many states
the metric owns.  Input validation stays on the host, before the call (it
must raise eagerly — reference semantics, e.g. reference
``torcheval/metrics/functional/classification/confusion_matrix.py:245-280``).

Hot-path extensions (see ``_bucket.py`` / ``ops/_flags.py``):

* ``mask=`` threads a ragged-batch validity mask into mask-aware kernels
  (it rides as a trailing ``mask=`` keyword after the statics), so padded
  bucket rows add exact zeros to every counter.
* When :func:`torcheval_tpu.ops._flags.donation_enabled`, the state
  operand is donated (``donate_argnums=(0,)``): XLA aliases old→new state
  in place, halving HBM traffic on the add and peak memory for large
  states.  The caller's old state arrays are DELETED after execution —
  the metric base class copies registry defaults and checkpoint
  snapshots so no live reference ever dangles.
"""

import time
from functools import partial
from typing import Tuple

import jax

from torcheval_tpu._stats import bump_trace
from torcheval_tpu.telemetry import events as _telemetry


def _accumulate_impl(states, args, kernel, statics, grow, fold, mask=None):
    bump_trace("accumulate")
    if mask is None:
        deltas = kernel(*args, *statics)
    else:
        deltas = kernel(*args, *statics, mask=mask)
    if not isinstance(deltas, tuple):
        deltas = (deltas,)
    out = []
    for i, (s, d) in enumerate(zip(states, deltas)):
        if grow and s.ndim == 0 and d.ndim == 1:
            # Per-output regression states replace the scalar default on the
            # first 2-D update instead of broadcasting into it (reference
            # ``regression/mean_squared_error.py`` state-growth behavior).
            out.append(d)
        else:
            f = fold[i] if isinstance(fold, tuple) else fold
            out.append(s + d if f is None else f(s, d))
    return tuple(out)


_accumulate_jit = partial(jax.jit, static_argnames=("kernel", "statics", "grow", "fold"))(
    _accumulate_impl
)
_accumulate_jit_donated = partial(
    jax.jit,
    static_argnames=("kernel", "statics", "grow", "fold"),
    donate_argnums=(0,),
)(_accumulate_impl)


def accumulate(
    kernel,
    states: Tuple[jax.Array, ...],
    *args,
    statics: tuple = (),
    grow: bool = False,
    fold=None,
    mask=None,
) -> Tuple[jax.Array, ...]:
    """Run ``kernel(*args, *statics)`` and fold its delta(s) onto ``states``
    in one fused dispatch.

    ``kernel`` must be a module-level (jitted or plain) pure function — its
    identity is part of the jit cache key.  ``statics`` are hashable
    trace-time constants appended positionally after ``args``.  ``fold``
    combines ``(state, delta)`` and defaults to addition; pass e.g.
    ``jnp.minimum`` for extremum states (Min/Max), or a per-state tuple
    (``None`` entries mean addition) — give the tuple a stable module-level
    identity, since ``fold`` is part of the jit cache key.  ``grow=True``
    replicates the scalar→vector replace-on-first-2-D-update semantics of
    per-output regression states.  ``mask`` (a validity array, or ``None``)
    is forwarded to the kernel as a trailing ``mask=`` keyword — only pass
    it to mask-aware kernels.  Returns the new state tuple.

    Under :func:`~torcheval_tpu.ops._flags.donation_enabled` the ``states``
    buffers are donated to XLA and unusable afterwards; callers must (and
    the class metrics do) rebind their state attributes to the return
    value immediately.
    """
    from torcheval_tpu.ops._flags import donation_enabled

    fn = _accumulate_jit_donated if donation_enabled() else _accumulate_jit
    if not _telemetry.ENABLED:
        return fn(
            tuple(states), tuple(args), kernel, tuple(statics), grow, fold, mask
        )
    # Telemetry on: the fused dispatch becomes a "dispatch" span named
    # after the kernel (dispatch wall time, NOT device time — steady
    # state it measures the jit cache hit + launch).
    t0 = time.monotonic()
    out = fn(
        tuple(states), tuple(args), kernel, tuple(statics), grow, fold, mask
    )
    _telemetry.record_span(
        "dispatch",
        getattr(kernel, "__name__", str(kernel)),
        time.monotonic() - t0,
        sum(getattr(s, "nbytes", 0) for s in out),
    )
    return out