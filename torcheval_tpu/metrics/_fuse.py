"""Fused update-accumulate dispatch for counter-state metrics.

A class-metric ``update()`` used to launch one device program for the
sufficient-statistic kernel and one more per state for the ``state + delta``
add — three or more dispatches per batch.  Each dispatch costs host→device
round-trip overhead (microseconds on a local PCIe host, milliseconds through
a tunneled backend), which dominates the microsecond-scale counter kernels.

``accumulate`` folds the kernel and every state add into ONE jitted program:
the per-update cost becomes a single dispatch regardless of how many states
the metric owns.  Input validation stays on the host, before the call (it
must raise eagerly — reference semantics, e.g. reference
``torcheval/metrics/functional/classification/confusion_matrix.py:245-280``).
"""

from functools import partial
from typing import Tuple

import jax


@partial(jax.jit, static_argnames=("kernel", "statics", "grow", "fold"))
def _accumulate_jit(states, args, kernel, statics, grow, fold):
    deltas = kernel(*args, *statics)
    if not isinstance(deltas, tuple):
        deltas = (deltas,)
    out = []
    for i, (s, d) in enumerate(zip(states, deltas)):
        if grow and s.ndim == 0 and d.ndim == 1:
            # Per-output regression states replace the scalar default on the
            # first 2-D update instead of broadcasting into it (reference
            # ``regression/mean_squared_error.py`` state-growth behavior).
            out.append(d)
        else:
            f = fold[i] if isinstance(fold, tuple) else fold
            out.append(s + d if f is None else f(s, d))
    return tuple(out)


def accumulate(
    kernel,
    states: Tuple[jax.Array, ...],
    *args,
    statics: tuple = (),
    grow: bool = False,
    fold=None,
) -> Tuple[jax.Array, ...]:
    """Run ``kernel(*args, *statics)`` and fold its delta(s) onto ``states``
    in one fused dispatch.

    ``kernel`` must be a module-level (jitted or plain) pure function — its
    identity is part of the jit cache key.  ``statics`` are hashable
    trace-time constants appended positionally after ``args``.  ``fold``
    combines ``(state, delta)`` and defaults to addition; pass e.g.
    ``jnp.minimum`` for extremum states (Min/Max), or a per-state tuple
    (``None`` entries mean addition) — give the tuple a stable module-level
    identity, since ``fold`` is part of the jit cache key.  ``grow=True``
    replicates the scalar→vector replace-on-first-2-D-update semantics of
    per-output regression states.  Returns the new state tuple.
    """
    return _accumulate_jit(
        tuple(states), tuple(args), kernel, tuple(statics), grow, fold
    )
