"""Shared helpers for sample-buffer metrics (AUROC, PR curves, HitRate,
ReciprocalRank, Cat).

Buffer states are Python lists of device arrays; all math is deferred to
``compute()``, where one concatenation feeds a jit kernel.  Merge
concatenates; ``_prepare_for_merge_state`` pre-concatenates each buffer so
the sync wire ships a single array per state (reference
``classification/auroc.py:130-134``)."""

from contextlib import nullcontext as _nullcontext
from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.metric import Metric


def merge_concat_buffers(
    metric: Metric, metrics: Iterable[Metric], *state_names: str, dim: int = -1
) -> None:
    """Append each source metric's concatenated buffer (reference merge
    semantics: one pre-concatenated array per source,
    ``classification/auroc.py:121-128``)."""
    for other in metrics:
        first = getattr(other, state_names[0])
        if first:
            for name in state_names:
                buf = getattr(other, name)
                getattr(metric, name).append(
                    jax.device_put(jnp.concatenate(buf, axis=dim), metric.device)
                )


def prepare_concat_buffers(metric: Metric, *state_names: str, dim: int = -1) -> None:
    for name in state_names:
        buf = getattr(metric, name)
        if buf:
            setattr(metric, name, [jnp.concatenate(buf, axis=dim)])


class RingWindowMixin:
    """Shared machinery for windowed metrics whose state is a
    ``(num_tasks, capacity)`` ring buffer per state name (WindowedBinaryAUROC,
    WindowedBinaryNormalizedEntropy).

    Invariant: valid columns always form the prefix ``[:, :_num_valid]`` —
    in-order inserts extend it, wrapped inserts overwrite inside it, and
    merge re-packs into it — so compute never needs the reference's
    zero-suffix fill guess (reference ``window/auroc.py:158-164``).

    Subclasses set ``_window_states`` (the ring-buffer state names) and call
    ``_init_window`` from ``__init__``; the window capacity lives in
    ``_window_capacity`` (exposed under the reference attribute names via
    properties on each class).
    """

    _window_states: tuple = ()
    # Host-side lifetime counters each subclass also wants checkpointed
    # (e.g. "total_samples" / "total_updates").
    _window_counters: tuple = ()

    def _init_window(self, capacity: int) -> None:
        self._window_capacity = capacity
        self._init_window_capacity = capacity
        self.next_inserted = 0
        self._num_valid = 0

    # ----------------------------------------------------------- checkpoint
    # The ring bookkeeping is host-side Python ints, not registered array
    # state, so it must ride state_dict explicitly or a checkpoint restore
    # would silently drop the window fill level.  (The reference gets away
    # without this because its compute *guesses* fill from the buffer.)
    _WINDOW_META_KEY = "window_bookkeeping"

    def state_dict(self):
        out = super().state_dict()
        meta = [self._window_capacity, self.next_inserted, self._num_valid]
        meta += [getattr(self, name) for name in self._window_counters]
        out[self._WINDOW_META_KEY] = np.asarray(meta, dtype=np.int64)
        return out

    def load_state_dict(self, state_dict, strict: bool = True) -> None:
        state_dict = dict(state_dict)
        meta = state_dict.pop(self._WINDOW_META_KEY, None)
        if meta is not None:
            values = [int(v) for v in jax.device_get(meta)]
            self._window_capacity, self.next_inserted, self._num_valid = values[:3]
            for name, value in zip(self._window_counters, values[3:]):
                setattr(self, name, value)
        super().load_state_dict(state_dict, strict=strict)

    def _window_advance(self, n: int) -> None:
        """Host-side bookkeeping after inserting ``n`` columns at
        ``next_inserted`` (mod capacity)."""
        self.next_inserted = (self.next_inserted + n) % self._window_capacity
        self._num_valid = min(self._num_valid + n, self._window_capacity)

    @staticmethod
    def _valid_window(metric: "RingWindowMixin", name: str) -> jax.Array:
        return getattr(metric, name)[:, : metric._num_valid]

    def _window_merge(self, metrics) -> None:
        """Pack every metric's valid columns into an enlarged window whose
        capacity is the sum of all capacities (reference merge semantics,
        ``window/auroc.py:166-207`` / ``window/normalized_entropy.py:232-296``
        — with the capacity actually updated, which the reference's NE merge
        forgets to do)."""
        merged_w = self._window_capacity + sum(
            m._window_capacity for m in metrics
        )
        idx = 0
        for name in self._window_states:
            pieces = [self._valid_window(self, name)] + [
                jax.device_put(self._valid_window(m, name), self.device)
                for m in metrics
            ]
            # A never-updated metric may still hold its initial row count
            # (e.g. WindowedMeanSquaredError before its output dim is
            # known); its zero-column slice carries no data, so conform it
            # to the sized metrics' rows instead of failing the concat.
            rows = max(p.shape[0] for p in pieces)
            pieces = [
                p
                if p.shape[1] or p.shape[0] == rows
                else jnp.zeros((rows, 0), p.dtype)
                for p in pieces
            ]
            valid = jnp.concatenate(pieces, axis=1)
            idx = valid.shape[1]
            setattr(self, name, jnp.pad(valid, ((0, 0), (0, merged_w - idx))))
        self._window_capacity = merged_w
        self.next_inserted = idx % merged_w
        self._num_valid = idx

    def _window_reset(self) -> None:
        """Restore the pre-merge capacity and zero the host counters
        (divergence: the reference base-class reset leaves them stale)."""
        self._window_capacity = self._init_window_capacity
        self.next_inserted = 0
        self._num_valid = 0


_EMPTY = np.zeros(0, dtype=np.float32)


def _windowed_pair_update_fused_impl(
    w_a, w_b, life_a, life_b, col, kernel, lifetime, *args
):
    """Two-statistic kernel + window-column writes (+ lifetime adds) in ONE
    dispatch — the fused update shared by every two-sum windowed metric
    (CTR, weighted calibration, MSE)."""
    from torcheval_tpu._stats import bump_trace

    bump_trace("windowed")
    a, b = kernel(*args)
    w_a = w_a.at[:, col].set(jnp.atleast_1d(a))
    w_b = w_b.at[:, col].set(b)
    if lifetime:
        life_a, life_b = life_a + a, life_b + b
    return w_a, w_b, life_a, life_b


_windowed_pair_update_fused = jax.jit(
    _windowed_pair_update_fused_impl, static_argnames=("kernel", "lifetime")
)
# Donated variant: the ring windows (and lifetime sums, when enabled) are
# the library's largest states (1M-capacity windowed AUROC); in-place
# aliasing halves their update HBM traffic and peak memory.  The caller
# must pass FRESH lifetime placeholders when lifetime is off — donating
# the module-level ``_EMPTY`` would delete it for every later caller.
_windowed_pair_update_fused_donated = jax.jit(
    _windowed_pair_update_fused_impl,
    static_argnames=("kernel", "lifetime"),
    donate_argnums=(0, 1, 2, 3),
)

# Donated signatures already compiled in this process: the first donated
# call per signature runs under ops._flags.cache_bypass so the donated
# executable stays out of the JAX persistent compilation cache (ROADMAP
# item 6); later calls hit the in-memory jit cache.
_donated_seen = set()


def _windowed_donated_bypass(kernel, lifetime, operands):
    from torcheval_tpu.ops._flags import cache_bypass

    key = (
        kernel,
        lifetime,
        tuple(
            (getattr(x, "shape", None), str(getattr(x, "dtype", "")))
            for x in operands
        ),
    )
    if key in _donated_seen:
        return _nullcontext()
    _donated_seen.add(key)
    return cache_bypass()


class WindowedLifetimeMixin(RingWindowMixin):
    """RingWindowMixin plus the shared lifecycle of every windowed metric
    that also keeps optional lifetime sums (`enable_lifetime`): merge packs
    window columns AND adds the lifetime states; reset restores the window
    bookkeeping and the update counter.

    Subclasses set ``_lifetime_states`` (added on merge when lifetime is
    enabled) in addition to the RingWindowMixin attributes, keep an
    ``enable_lifetime`` flag and a ``total_updates`` counter, and call
    ``_merge_windowed`` from ``merge_state``.  Two-sum metrics get their
    whole update/compute path from ``_update_windowed_pair`` /
    ``_ratio_compute``."""

    _lifetime_states: tuple = ()
    # Lifetime names fed through the fused pair update, when they differ
    # from the merge-added ``_lifetime_states`` (WindowedMeanSquaredError
    # adds one of its lifetime states grow-aware, outside the mixin).
    @property
    def _fused_lifetime(self) -> tuple:
        return self._lifetime_states

    @property
    def max_num_updates(self) -> int:
        """Window capacity (grows on merge)."""
        return self._window_capacity

    def _init_task_window(
        self,
        num_tasks: int,
        max_num_updates: int,
        enable_lifetime: bool,
        dtype,
    ) -> None:
        """Validate and allocate the standard per-task window layout:
        lifetime vectors ``(num_tasks,)`` and window rings
        ``(num_tasks, max_num_updates)``."""
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        if max_num_updates < 1:
            raise ValueError(
                "`max_num_updates` value should be greater than and equal to 1, "
                f"but received {max_num_updates}. "
            )
        self.num_tasks = num_tasks
        self.enable_lifetime = enable_lifetime
        self._init_window(max_num_updates)
        self.total_updates = 0
        if enable_lifetime:
            for name in self._lifetime_states:
                self._add_state(name, jnp.zeros(num_tasks, dtype=dtype))
        for name in self._window_states:
            self._add_state(
                name, jnp.zeros((num_tasks, max_num_updates), dtype=dtype)
            )

    def _update_windowed_pair(self, kernel, args) -> None:
        """Run the fused two-statistic update and advance the window."""
        from torcheval_tpu.ops._flags import donation_enabled

        donate = donation_enabled()
        fn = (
            _windowed_pair_update_fused_donated
            if donate
            else _windowed_pair_update_fused
        )
        wa, wb = self._window_states
        la, lb = self._fused_lifetime
        if self.enable_lifetime:
            lifetime_in = (getattr(self, la), getattr(self, lb))
        elif donate:
            # Fresh zero-size placeholders: the donated variant deletes
            # its lifetime operands, and _EMPTY is a shared module global.
            lifetime_in = (jnp.zeros(0, jnp.float32), jnp.zeros(0, jnp.float32))
        else:
            lifetime_in = (_EMPTY, _EMPTY)
        operands = (
            getattr(self, wa),
            getattr(self, wb),
            *lifetime_in,
            self.next_inserted,
            *args,
        )
        bypass = (
            _windowed_donated_bypass(kernel, self.enable_lifetime, operands)
            if donate
            else _nullcontext()
        )
        with bypass:
            new_wa, new_wb, a, b = fn(
                *operands[:5], kernel, self.enable_lifetime, *operands[5:]
            )
        setattr(self, wa, new_wa)
        setattr(self, wb, new_wb)
        if self.enable_lifetime:
            setattr(self, la, a)
            setattr(self, lb, b)
        self._window_advance(1)
        self.total_updates += 1

    def _ratio_compute(self):
        """``windowed = Σa / Σb`` over the valid columns, plus the lifetime
        ratio when enabled; empty array(s) before any update."""
        if self._num_valid == 0:
            empty = jnp.zeros(0)
            return (empty, empty) if self.enable_lifetime else empty
        wa, wb = self._window_states
        n = self._num_valid
        windowed = getattr(self, wa)[:, :n].sum(axis=1) / getattr(self, wb)[
            :, :n
        ].sum(axis=1)
        if self.enable_lifetime:
            la, lb = self._lifetime_states
            return getattr(self, la) / getattr(self, lb), windowed
        return windowed

    def _merge_windowed(self, metrics):
        metrics = list(metrics)
        for m in metrics:
            if m.enable_lifetime != self.enable_lifetime:
                raise ValueError(
                    "Merged metrics must all have the same `enable_lifetime` "
                    f"setting; got {self.enable_lifetime} vs {m.enable_lifetime}."
                )
        self._window_merge(metrics)
        for m in metrics:
            if self.enable_lifetime:
                for name in self._lifetime_states:
                    setattr(
                        self,
                        name,
                        getattr(self, name)
                        + jax.device_put(getattr(m, name), self.device),
                    )
            self.total_updates += m.total_updates
        return self

    def reset(self):
        """Reset states AND the host-side window bookkeeping, including the
        window size a previous merge may have grown."""
        super().reset()
        self._window_reset()
        self.total_updates = 0
        return self
