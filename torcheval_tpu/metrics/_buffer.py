"""Shared helpers for sample-buffer metrics (AUROC, PR curves, HitRate,
ReciprocalRank, Cat).

Buffer states are Python lists of device arrays; all math is deferred to
``compute()``, where one concatenation feeds a jit kernel.  Merge
concatenates; ``_prepare_for_merge_state`` pre-concatenates each buffer so
the sync wire ships a single array per state (reference
``classification/auroc.py:130-134``)."""

from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.metric import Metric


def merge_concat_buffers(
    metric: Metric, metrics: Iterable[Metric], *state_names: str, dim: int = -1
) -> None:
    """Append each source metric's concatenated buffer (reference merge
    semantics: one pre-concatenated array per source,
    ``classification/auroc.py:121-128``)."""
    for other in metrics:
        first = getattr(other, state_names[0])
        if first:
            for name in state_names:
                buf = getattr(other, name)
                getattr(metric, name).append(
                    jax.device_put(jnp.concatenate(buf, axis=dim), metric.device)
                )


def prepare_concat_buffers(metric: Metric, *state_names: str, dim: int = -1) -> None:
    for name in state_names:
        buf = getattr(metric, name)
        if buf:
            setattr(metric, name, [jnp.concatenate(buf, axis=dim)])


class RingWindowMixin:
    """Shared machinery for windowed metrics whose state is a
    ``(num_tasks, capacity)`` ring buffer per state name (WindowedBinaryAUROC,
    WindowedBinaryNormalizedEntropy).

    Invariant: valid columns always form the prefix ``[:, :_num_valid]`` —
    in-order inserts extend it, wrapped inserts overwrite inside it, and
    merge re-packs into it — so compute never needs the reference's
    zero-suffix fill guess (reference ``window/auroc.py:158-164``).

    Subclasses set ``_window_states`` (the ring-buffer state names) and call
    ``_init_window`` from ``__init__``; the window capacity lives in
    ``_window_capacity`` (exposed under the reference attribute names via
    properties on each class).
    """

    _window_states: tuple = ()
    # Host-side lifetime counters each subclass also wants checkpointed
    # (e.g. "total_samples" / "total_updates").
    _window_counters: tuple = ()

    def _init_window(self, capacity: int) -> None:
        self._window_capacity = capacity
        self._init_window_capacity = capacity
        self.next_inserted = 0
        self._num_valid = 0

    # ----------------------------------------------------------- checkpoint
    # The ring bookkeeping is host-side Python ints, not registered array
    # state, so it must ride state_dict explicitly or a checkpoint restore
    # would silently drop the window fill level.  (The reference gets away
    # without this because its compute *guesses* fill from the buffer.)
    _WINDOW_META_KEY = "window_bookkeeping"

    def state_dict(self):
        out = super().state_dict()
        meta = [self._window_capacity, self.next_inserted, self._num_valid]
        meta += [getattr(self, name) for name in self._window_counters]
        out[self._WINDOW_META_KEY] = np.asarray(meta, dtype=np.int64)
        return out

    def load_state_dict(self, state_dict, strict: bool = True) -> None:
        state_dict = dict(state_dict)
        meta = state_dict.pop(self._WINDOW_META_KEY, None)
        if meta is not None:
            values = [int(v) for v in jax.device_get(meta)]
            self._window_capacity, self.next_inserted, self._num_valid = values[:3]
            for name, value in zip(self._window_counters, values[3:]):
                setattr(self, name, value)
        super().load_state_dict(state_dict, strict=strict)

    def _window_advance(self, n: int) -> None:
        """Host-side bookkeeping after inserting ``n`` columns at
        ``next_inserted`` (mod capacity)."""
        self.next_inserted = (self.next_inserted + n) % self._window_capacity
        self._num_valid = min(self._num_valid + n, self._window_capacity)

    @staticmethod
    def _valid_window(metric: "RingWindowMixin", name: str) -> jax.Array:
        return getattr(metric, name)[:, : metric._num_valid]

    def _window_merge(self, metrics) -> None:
        """Pack every metric's valid columns into an enlarged window whose
        capacity is the sum of all capacities (reference merge semantics,
        ``window/auroc.py:166-207`` / ``window/normalized_entropy.py:232-296``
        — with the capacity actually updated, which the reference's NE merge
        forgets to do)."""
        merged_w = self._window_capacity + sum(
            m._window_capacity for m in metrics
        )
        idx = 0
        for name in self._window_states:
            pieces = [self._valid_window(self, name)] + [
                jax.device_put(self._valid_window(m, name), self.device)
                for m in metrics
            ]
            valid = jnp.concatenate(pieces, axis=1)
            idx = valid.shape[1]
            setattr(self, name, jnp.pad(valid, ((0, 0), (0, merged_w - idx))))
        self._window_capacity = merged_w
        self.next_inserted = idx % merged_w
        self._num_valid = idx

    def _window_reset(self) -> None:
        """Restore the pre-merge capacity and zero the host counters
        (divergence: the reference base-class reset leaves them stale)."""
        self._window_capacity = self._init_window_capacity
        self.next_inserted = 0
        self._num_valid = 0
