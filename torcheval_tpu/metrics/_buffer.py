"""Shared helpers for sample-buffer metrics (AUROC, PR curves, HitRate,
ReciprocalRank, Cat).

Buffer states are Python lists of device arrays; all math is deferred to
``compute()``, where one concatenation feeds a jit kernel.  Merge
concatenates; ``_prepare_for_merge_state`` pre-concatenates each buffer so
the sync wire ships a single array per state (reference
``classification/auroc.py:130-134``)."""

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.metric import Metric


def merge_concat_buffers(
    metric: Metric, metrics: Iterable[Metric], *state_names: str, dim: int = -1
) -> None:
    """Append each source metric's concatenated buffer (reference merge
    semantics: one pre-concatenated array per source,
    ``classification/auroc.py:121-128``)."""
    for other in metrics:
        first = getattr(other, state_names[0])
        if first:
            for name in state_names:
                buf = getattr(other, name)
                getattr(metric, name).append(
                    jax.device_put(jnp.concatenate(buf, axis=dim), metric.device)
                )


def prepare_concat_buffers(metric: Metric, *state_names: str, dim: int = -1) -> None:
    for name in state_names:
        buf = getattr(metric, name)
        if buf:
            setattr(metric, name, [jnp.concatenate(buf, axis=dim)])
