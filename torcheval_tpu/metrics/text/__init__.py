from torcheval_tpu.metrics.text.bleu import BLEUScore
from torcheval_tpu.metrics.text.perplexity import Perplexity
from torcheval_tpu.metrics.text.word_error_rate import (
    WordErrorRate,
    WordInformationLost,
    WordInformationPreserved,
)

__all__ = [
    "BLEUScore",
    "Perplexity",
    "WordErrorRate",
    "WordInformationLost",
    "WordInformationPreserved",
]
