"""BLEUScore class metric — four add-mergeable counters over host-side
n-gram statistics.

Beyond the v0.0.4 snapshot (upstream torcheval added ``BLEUScore``
later)."""

from typing import Iterable, Optional, Sequence

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.classification.binary_normalized_entropy import (
    _accum_dtype,
)
from torcheval_tpu.metrics.functional.text.bleu import (
    TBleuInput,
    TBleuTarget,
    _bleu_compute,
    _bleu_param_check,
    _bleu_update,
)
from torcheval_tpu.metrics.metric import Metric

_STATES = (
    "input_len",
    "target_len",
    "matches_by_order",
    "possible_matches_by_order",
)


class BLEUScore(Metric[jax.Array]):
    """Corpus BLEU accumulated over updates; 0 before any update."""

    def __init__(
        self,
        *,
        n_gram: int = 4,
        weights: Optional[Sequence[float]] = None,
        device=None,
    ) -> None:
        super().__init__(device=device)
        self.weights = _bleu_param_check(n_gram, weights)
        self.n_gram = n_gram
        dtype = _accum_dtype()
        self._add_state("input_len", jnp.asarray(0.0, dtype=dtype))
        self._add_state("target_len", jnp.asarray(0.0, dtype=dtype))
        self._add_state("matches_by_order", jnp.zeros(n_gram, dtype=dtype))
        self._add_state("possible_matches_by_order", jnp.zeros(n_gram, dtype=dtype))

    def update(self, input: TBleuInput, target: TBleuTarget) -> "BLEUScore":
        input_len, target_len, matches, possible = _bleu_update(
            input, target, self.n_gram
        )
        # Host-computed statistics fold into the states in one tiny dispatch.
        self.input_len = self.input_len + input_len
        self.target_len = self.target_len + target_len
        self.matches_by_order = self.matches_by_order + matches
        self.possible_matches_by_order = self.possible_matches_by_order + possible
        return self

    def compute(self) -> jax.Array:
        """Corpus BLEU over everything seen so far."""
        return _bleu_compute(
            self.input_len,
            self.target_len,
            self.matches_by_order,
            self.possible_matches_by_order,
            self.weights,
        )

    def merge_state(self, metrics: Iterable["BLEUScore"]) -> "BLEUScore":
        merge_add(self, metrics, *_STATES)
        return self
