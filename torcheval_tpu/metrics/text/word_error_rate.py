"""Word error rate / word information class metrics — scalar counter
states fed by the native batched edit-distance kernel (string inputs)
or the anti-diagonal wavefront routes (tokenized device inputs).

Beyond the v0.0.4 snapshot (upstream torcheval added the text metrics
later)."""

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.classification.binary_normalized_entropy import (
    _accum_dtype,
)
from torcheval_tpu.metrics.functional.text.word_error_rate import (
    TText,
    _is_tokens,
    _wip_compute,
    _word_stats_device_kernel,
    _word_stats_tokens_check,
    _word_stats_update,
)
from torcheval_tpu.metrics.metric import Metric

_STATES = ("errors", "target_total", "input_total")


class _WordStatsMetric(Metric[jax.Array]):
    """Shared state machine: the three word-alignment counters.

    ``update`` is polymorphic over the input flavor:

    * strings → the host path (interning + native C++ DP, scalar folds);
    * ``(n, len)`` int token ids (``metrics/text/_tokens.tokenize_pairs``
      pads, negative and trailing) → one fused device dispatch through
      the wavefront edit-distance routes — ``_check_fusable``-clean, so
      the family rides collection/engine-scan programs;
    * ``(n, seq, vocab)`` float logits + id targets → greedy-argmax
      token error rate, same device dispatch — the shared signature that
      lets WER/WIP/WIL and ``Perplexity`` share ONE engine-scan program.
    """

    # The tokenized device path accepts update(..., mask=) for bucketed
    # ragged batches (_bucket.py); the string path predates masks.
    _supports_mask = True

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        for name in _STATES:
            self._add_state(name, jnp.asarray(0.0, dtype=_accum_dtype()))

    def update(self, input, target, *, mask=None):
        if not _is_tokens(input):
            if mask is not None:
                raise ValueError(
                    "mask= requires tokenized array inputs; string "
                    "batches are never padded."
                )
            errors, target_total, input_total = _word_stats_update(
                input, target
            )
            # Host-computed scalars fold into the states in one tiny
            # dispatch.
            self.errors = self.errors + errors
            self.target_total = self.target_total + target_total
            self.input_total = self.input_total + input_total
            return self
        from torcheval_tpu.ops.pallas_wavefront import wavefront_route

        input, target = jnp.asarray(input), jnp.asarray(target)
        _word_stats_tokens_check(input, target)
        # Kernel + all three state adds fused into one dispatch
        # (_fuse.py); the route string rides the jit cache key.
        self.errors, self.target_total, self.input_total = accumulate(
            _word_stats_device_kernel,
            (self.errors, self.target_total, self.input_total),
            input,
            target,
            statics=(wavefront_route(False),),
            mask=mask,
        )
        return self

    def merge_state(self, metrics: Iterable["_WordStatsMetric"]):
        merge_add(self, metrics, *_STATES)
        return self


class WordErrorRate(_WordStatsMetric):
    """WER = edit errors / reference words; NaN before any update (0/0)."""

    def compute(self) -> jax.Array:
        return self.errors / self.target_total


class WordInformationPreserved(_WordStatsMetric):
    """WIP over all pairs seen; NaN before any update (0/0)."""

    def compute(self) -> jax.Array:
        return _wip_compute(self.errors, self.target_total, self.input_total)


class WordInformationLost(_WordStatsMetric):
    """WIL = 1 − WIP; NaN before any update (0/0)."""

    def compute(self) -> jax.Array:
        return 1.0 - _wip_compute(
            self.errors, self.target_total, self.input_total
        )
