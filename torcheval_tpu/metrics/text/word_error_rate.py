"""Word error rate / word information class metrics — scalar counter
states fed by the native batched edit-distance kernel.

Beyond the v0.0.4 snapshot (upstream torcheval added the text metrics
later)."""

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.classification.binary_normalized_entropy import (
    _accum_dtype,
)
from torcheval_tpu.metrics.functional.text.word_error_rate import (
    TText,
    _wip_compute,
    _word_stats_update,
)
from torcheval_tpu.metrics.metric import Metric

_STATES = ("errors", "target_total", "input_total")


class _WordStatsMetric(Metric[jax.Array]):
    """Shared state machine: the three word-alignment counters."""

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        for name in _STATES:
            self._add_state(name, jnp.asarray(0.0, dtype=_accum_dtype()))

    def update(self, input: TText, target: TText):
        errors, target_total, input_total = _word_stats_update(input, target)
        # Host-computed scalars fold into the states in one tiny dispatch.
        self.errors = self.errors + errors
        self.target_total = self.target_total + target_total
        self.input_total = self.input_total + input_total
        return self

    def merge_state(self, metrics: Iterable["_WordStatsMetric"]):
        merge_add(self, metrics, *_STATES)
        return self


class WordErrorRate(_WordStatsMetric):
    """WER = edit errors / reference words; NaN before any update (0/0)."""

    def compute(self) -> jax.Array:
        return self.errors / self.target_total


class WordInformationPreserved(_WordStatsMetric):
    """WIP over all pairs seen; NaN before any update (0/0)."""

    def compute(self) -> jax.Array:
        return _wip_compute(self.errors, self.target_total, self.input_total)


class WordInformationLost(_WordStatsMetric):
    """WIL = 1 − WIP; NaN before any update (0/0)."""

    def compute(self) -> jax.Array:
        return 1.0 - _wip_compute(
            self.errors, self.target_total, self.input_total
        )
