"""Host-side word→id interning: the bridge from string pairs to the
device-resident text family.

Edit distance only compares tokens for *equality*, so any injective
word→int map preserves it exactly — interning is the one step that must
stay on the host, and everything after (the wavefront DP, the WER/WIP/
WIL counter folds) runs on device.  :func:`tokenize_pairs` turns a
(hypothesis, reference) string batch into two padded ``(n, len)`` int32
id arrays under the negative-pad convention the device kernels consume
(``ops.pallas_wavefront.lens_from_ids``): real tokens ``>= 0``, pads
``PAD_ID`` and strictly trailing (prefix-packed rows).

Sequence lengths are bucketed to powers of two via the same policy as
batch rows (``metrics/_bucket.py``), floored at ``DEFAULT_MIN_TOKENS``
— a ragged sentence stream then costs O(log max_len) compiled programs,
and the leading dim stays raw for the collection's own ``bucket=True``
row bucketing to handle.
"""

from typing import List, Optional, Tuple

import numpy as np

from torcheval_tpu.metrics._bucket import bucket_size
from torcheval_tpu.metrics.functional.text.word_error_rate import (
    TText,
    _as_list,
)

# The padding sentinel: any negative id works for the kernels (lengths
# mask every comparison); -1 keeps dumps readable.
PAD_ID = -1

# Sequence-length bucket floor: sentences up to this many words all
# share one shape, so typical ASR/LLM transcript streams compile once.
DEFAULT_MIN_TOKENS = 16


class WordInterner:
    """A persistent word→id vocabulary.  Per-batch correctness never
    needs one (equality is within-pair), but a shared interner keeps ids
    stable across a stream so pre-tokenized batches from different steps
    remain comparable and dumpable."""

    def __init__(self) -> None:
        self._vocab: dict = {}

    def __len__(self) -> int:
        return len(self._vocab)

    def ids(self, sentence: str) -> List[int]:
        vocab = self._vocab
        return [vocab.setdefault(w, len(vocab)) for w in sentence.split()]


def tokenize_pairs(
    input: TText,
    target: TText,
    *,
    interner: Optional[WordInterner] = None,
    min_tokens: int = DEFAULT_MIN_TOKENS,
) -> Tuple[np.ndarray, np.ndarray]:
    """Intern a (hypothesis, reference) string batch into padded id
    arrays: ``(hyp_ids, ref_ids)``, each ``(n, bucketed_len) int32``
    with ``PAD_ID`` trailing pads.

    Validation matches the host path (same error strings); each array's
    width is the power-of-two bucket of its own longest sentence, so
    hypothesis and reference widths bucket independently.
    """
    hyp_s = _as_list(input, "input")
    ref_s = _as_list(target, "target")
    if len(hyp_s) != len(ref_s):
        raise ValueError(
            "`input` and `target` should have the same number of sequences, "
            f"got {len(hyp_s)} and {len(ref_s)}."
        )
    it = interner if interner is not None else WordInterner()
    hyp = [it.ids(s) for s in hyp_s]
    ref = [it.ids(s) for s in ref_s]
    return _pack(hyp, min_tokens), _pack(ref, min_tokens)


def _pack(seqs: List[List[int]], min_tokens: int) -> np.ndarray:
    width = bucket_size(
        max((len(s) for s in seqs), default=0), min_bucket=min_tokens
    )
    out = np.full((len(seqs), width), PAD_ID, np.int32)
    for row, seq in enumerate(seqs):
        out[row, : len(seq)] = seq
    return out
