"""Perplexity class metric — two scalar counters (summed NLL + token
count), add-mergeable, ``psum``-syncable.

Beyond the v0.0.4 snapshot (upstream torcheval added ``Perplexity``
later)."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.classification.binary_normalized_entropy import (
    _accum_dtype,
)
from torcheval_tpu.metrics.functional.text.perplexity import (
    _perplexity_compute,
    _perplexity_input_check,
    _perplexity_update_kernel,
)
from torcheval_tpu.metrics.metric import Metric


class Perplexity(Metric[jax.Array]):
    """``exp(mean NLL)`` over all tokens seen, excluding ``ignore_index``."""

    # Accepts update(..., mask=) for bucketed ragged batches (_bucket.py):
    # a zero mask row zeroes every token of that sequence.
    _supports_mask = True

    def __init__(self, *, ignore_index: Optional[int] = None, device=None) -> None:
        super().__init__(device=device)
        self.ignore_index = ignore_index
        # Accumulator dtype: token counts past 2^24 would stop advancing in
        # float32 — exactly the corpus sizes a streaming LM eval reaches.
        dtype = _accum_dtype()
        self._add_state("sum_log_probs", jnp.asarray(0.0, dtype=dtype))
        self._add_state("num_total", jnp.asarray(0.0, dtype=dtype))

    def update(self, input, target, *, mask=None) -> "Perplexity":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _perplexity_input_check(input, target)
        # Kernel + both state adds fused into one dispatch (_fuse.py).
        self.sum_log_probs, self.num_total = accumulate(
            _perplexity_update_kernel,
            (self.sum_log_probs, self.num_total),
            input,
            target,
            statics=(self.ignore_index,),
            mask=mask,
        )
        return self

    def compute(self) -> jax.Array:
        """Perplexity; NaN before any update (exp(0/0))."""
        return _perplexity_compute(self.sum_log_probs, self.num_total)

    def merge_state(self, metrics: Iterable["Perplexity"]) -> "Perplexity":
        merge_add(self, metrics, "sum_log_probs", "num_total")
        return self
