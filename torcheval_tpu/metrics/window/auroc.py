"""WindowedBinaryAUROC — parity with reference
``torcheval/metrics/window/auroc.py`` (207 LoC).

AUROC over the last ``max_num_samples`` samples.  State is a pre-allocated
``(num_tasks, max_num_samples)`` ring buffer pair; the ring bookkeeping is
host-side ints kept outside jit (SURVEY §7 hard part 6), shared with the
windowed NE metric via :class:`~torcheval_tpu.metrics._buffer.RingWindowMixin`.

TPU-first design notes
----------------------
* Insertion: the reference's three-branch wrap-around copy (reference
  ``window/auroc.py:102-144``) collapses into ONE scatter with mod indices —
  ``buf.at[:, (start + arange(n)) % W].set(batch)`` — which produces the
  identical buffer layout and is a single fused XLA program.
* Partial-fill detection: the reference guesses fill level from a zero
  suffix (``window/auroc.py:158-164``), which misfires when genuine 0.0
  scores land past the insertion point.  Here the valid-prefix length is
  tracked explicitly (``_num_valid`` — documented divergence; observable
  behavior matches whenever the heuristic is right).
* Merge concatenates the valid samples of each window and **grows**
  ``max_num_samples`` to the summed window size (reference
  ``window/auroc.py:166-207``).  AUROC is order-invariant, so copying the
  (possibly rotated) valid buffer region without unrotating is exact.
"""

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import RingWindowMixin
from torcheval_tpu.metrics.functional.classification.auroc import (
    _binary_auroc_compute,
    _binary_auroc_update_input_check,
)
from torcheval_tpu.metrics.metric import Metric


@jax.jit
def _ring_insert(inputs_buf, targets_buf, input, target, start):
    """Insert both batches at ring position ``start`` in ONE dispatch.
    ``start`` is traced, so successive updates reuse one compiled program
    per batch shape instead of recompiling per insert position."""
    w = inputs_buf.shape[1]
    idx = (start + jnp.arange(input.shape[1])) % w
    return (
        inputs_buf.at[:, idx].set(input.astype(inputs_buf.dtype)),
        targets_buf.at[:, idx].set(target.astype(targets_buf.dtype)),
    )


class WindowedBinaryAUROC(RingWindowMixin, Metric[jax.Array]):
    """The windowed version of BinaryAUROC: computed from the input and
    target of the last ``max_num_samples`` samples
    (reference ``window/auroc.py:23-54``)."""

    _window_states = ("inputs", "targets")
    _window_counters = ("total_samples",)

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        max_num_samples: int = 100,
        device=None,
    ) -> None:
        super().__init__(device=device)
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        if max_num_samples < 1:
            raise ValueError(
                "`max_num_samples` value should be greater than and equal to 1, "
                f"but received {max_num_samples}. "
            )
        self.num_tasks = num_tasks
        self._init_window(max_num_samples)
        self.total_samples = 0
        self._add_state("inputs", jnp.zeros((num_tasks, max_num_samples)))
        self._add_state("targets", jnp.zeros((num_tasks, max_num_samples)))

    @property
    def max_num_samples(self) -> int:
        """Window capacity (grows on merge, reference attribute name)."""
        return self._window_capacity

    def update(self, input, target) -> "WindowedBinaryAUROC":
        """Insert a batch of predictions/labels into the ring buffer
        (reference ``window/auroc.py:85-144``)."""
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_auroc_update_input_check(input, target, self.num_tasks)
        if input.ndim == 1:
            input = input.reshape(1, -1)
            target = target.reshape(1, -1)
        n = input.shape[1]
        w = self.max_num_samples
        if n >= w:
            # Oversized batch: the window is exactly its last w samples.
            self.inputs = jax.device_put(
                jnp.asarray(input[:, -w:], dtype=self.inputs.dtype), self.device
            )
            self.targets = jax.device_put(
                jnp.asarray(target[:, -w:], dtype=self.targets.dtype), self.device
            )
            self.next_inserted = 0
            self._num_valid = w
        else:
            self.inputs, self.targets = _ring_insert(
                self.inputs, self.targets, input, target, self.next_inserted
            )
            self._window_advance(n)
        self.total_samples += n
        return self

    def compute(self) -> jax.Array:
        """AUROC of the current window; empty array before any update
        (reference ``window/auroc.py:146-164``)."""
        if self._num_valid == 0:
            return jnp.zeros(0)
        inputs = self.inputs[:, : self._num_valid]
        targets = self.targets[:, : self._num_valid]
        if self.num_tasks == 1:
            inputs, targets = inputs[0], targets[0]
        return _binary_auroc_compute(inputs, targets)

    def merge_state(
        self, metrics: Iterable["WindowedBinaryAUROC"]
    ) -> "WindowedBinaryAUROC":
        """Concatenate each window's valid samples into an enlarged window
        whose size is the sum of all window sizes
        (reference ``window/auroc.py:166-207``)."""
        metrics = list(metrics)
        self._window_merge(metrics)
        for m in metrics:
            self.total_samples += m.total_samples
        return self

    def reset(self) -> "WindowedBinaryAUROC":
        """Reset states AND the host-side ring bookkeeping, including the
        window size a previous merge may have grown (divergence: the
        reference base-class reset leaves all of these stale)."""
        super().reset()
        self._window_reset()
        self.total_samples = 0
        return self
