"""WindowedClickThroughRate — CTR over the last ``max_num_updates`` update
calls, plus optional lifetime values.

Beyond the v0.0.4 snapshot (upstream torcheval added
``WindowedClickThroughRate`` later).  All machinery — per-task ring
columns, fused two-sum update, ratio compute, merge-grows-window — comes
from :class:`~torcheval_tpu.metrics._buffer.WindowedLifetimeMixin`."""

from typing import Iterable, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import WindowedLifetimeMixin
from torcheval_tpu.metrics.functional.aggregation.click_through_rate import (
    _ctr_select_kernel,
)
from torcheval_tpu.metrics.functional.classification.binary_normalized_entropy import (
    _accum_dtype,
)
from torcheval_tpu.metrics.metric import Metric


class WindowedClickThroughRate(
    WindowedLifetimeMixin, Metric[Union[jax.Array, Tuple[jax.Array, jax.Array]]]
):
    """Windowed (and optionally lifetime) click-through rate."""

    _window_states = ("windowed_click_total", "windowed_weight_total")
    _window_counters = ("total_updates",)
    _lifetime_states = ("click_total", "weight_total")

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        max_num_updates: int = 100,
        enable_lifetime: bool = True,
        device=None,
    ) -> None:
        super().__init__(device=device)
        self._init_task_window(
            num_tasks, max_num_updates, enable_lifetime, _accum_dtype()
        )

    def update(
        self, input, weights: Union[float, int, "jax.Array"] = 1.0
    ) -> "WindowedClickThroughRate":
        input = jnp.asarray(input)
        kernel, args = _ctr_select_kernel(input, weights, num_tasks=self.num_tasks)
        self._update_windowed_pair(kernel, args)
        return self

    def compute(self) -> Union[jax.Array, Tuple[jax.Array, jax.Array]]:
        """``(lifetime, windowed)`` CTR when ``enable_lifetime`` else the
        windowed CTR; empty array(s) before any update."""
        return self._ratio_compute()

    def merge_state(
        self, metrics: Iterable["WindowedClickThroughRate"]
    ) -> "WindowedClickThroughRate":
        """Pack valid window columns into an enlarged window and add
        lifetime vectors (WindowedLifetimeMixin)."""
        return self._merge_windowed(metrics)
