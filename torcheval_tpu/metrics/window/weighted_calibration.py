"""WindowedWeightedCalibration — weighted calibration over the last
``max_num_updates`` update calls, plus optional lifetime values.

Beyond the v0.0.4 snapshot (upstream torcheval added
``WindowedWeightedCalibration`` later).  Same shared machinery as
``WindowedClickThroughRate`` (WindowedLifetimeMixin)."""

from typing import Iterable, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import WindowedLifetimeMixin
from torcheval_tpu.metrics.functional.classification.binary_normalized_entropy import (
    _accum_dtype,
)
from torcheval_tpu.metrics.functional.ranking.weighted_calibration import (
    _weighted_calibration_select_kernel,
)
from torcheval_tpu.metrics.metric import Metric


class WindowedWeightedCalibration(
    WindowedLifetimeMixin, Metric[Union[jax.Array, Tuple[jax.Array, jax.Array]]]
):
    """Windowed (and optionally lifetime) weighted calibration
    Σw·input / Σw·target per task."""

    _window_states = ("windowed_weighted_input_sum", "windowed_weighted_target_sum")
    _window_counters = ("total_updates",)
    _lifetime_states = ("weighted_input_sum", "weighted_target_sum")

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        max_num_updates: int = 100,
        enable_lifetime: bool = True,
        device=None,
    ) -> None:
        super().__init__(device=device)
        self._init_task_window(
            num_tasks, max_num_updates, enable_lifetime, _accum_dtype()
        )

    def update(
        self, input, target, weight: Union[float, int, "jax.Array"] = 1.0
    ) -> "WindowedWeightedCalibration":
        input, target = jnp.asarray(input), jnp.asarray(target)
        kernel, args = _weighted_calibration_select_kernel(
            input, target, weight, num_tasks=self.num_tasks
        )
        self._update_windowed_pair(kernel, args)
        return self

    def compute(self) -> Union[jax.Array, Tuple[jax.Array, jax.Array]]:
        """``(lifetime, windowed)`` calibration when ``enable_lifetime``
        else the windowed calibration; empty array(s) before any update."""
        return self._ratio_compute()

    def merge_state(
        self, metrics: Iterable["WindowedWeightedCalibration"]
    ) -> "WindowedWeightedCalibration":
        """Pack valid window columns into an enlarged window and add
        lifetime vectors (WindowedLifetimeMixin)."""
        return self._merge_windowed(metrics)
