"""WindowedBinaryNormalizedEntropy — parity with reference
``torcheval/metrics/window/normalized_entropy.py`` (296 LoC).

NE over the last ``max_num_updates`` *update calls* (the window counts
updates, not samples — reference ``window/normalized_entropy.py:27``), plus
optional lifetime values.  State: per-update windowed sufficient statistics
``(num_tasks, max_num_updates)`` ×3 and, when ``enable_lifetime``, lifetime
vectors ×3 (reference ``:104-144``; float64 there — see the accumulator
dtype note in the functional NE module).  Ring bookkeeping is shared via
:class:`~torcheval_tpu.metrics._buffer.RingWindowMixin`.

Divergences (documented, both in favor of correctness):

* merge updates ``max_num_updates`` to the enlarged size — the reference
  forgets to (``window/normalized_entropy.py:245-295`` never assigns it),
  leaving the modulo on the *old* size; the compute result is unaffected
  (both branches of compute sum exactly the valid columns) but subsequent
  updates would clobber merged columns mid-buffer.
* ``reset()`` also restores the capacity and zeroes the host-side counters.
"""

from functools import partial
from typing import Iterable, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics._buffer import WindowedLifetimeMixin
from torcheval_tpu.metrics.functional.classification.binary_normalized_entropy import (
    _accum_dtype,
    _baseline_update,
    _ne_input_check,
    _ne_update_kernel,
    _ne_update_kernel_unweighted,
)
from torcheval_tpu.metrics.metric import Metric

_LIFETIME_STATES = ("total_entropy", "num_examples", "num_positive")

# Inert stand-in for absent weight / disabled-lifetime slots in the fused
# update: a host numpy constant costs no eager device op per update (its
# dtype is irrelevant — every use is traced out).
_EMPTY = np.zeros(0, dtype=np.float32)


@partial(jax.jit, static_argnames=("from_logits", "lifetime", "weighted"))
def _windowed_ne_update_fused(
    w_ent,
    w_ex,
    w_pos,
    ent,
    ex,
    pos,
    input,
    target,
    weight,
    col,
    from_logits,
    lifetime,
    weighted,
):
    """NE sufficient statistics + window-column write (+ lifetime adds) in
    ONE dispatch.  ``col`` is traced so inserts reuse one compiled program
    per batch shape."""
    if weighted:
        ce, npos, nex = _ne_update_kernel(input, target, weight, from_logits)
    else:
        ce, npos, nex = _ne_update_kernel_unweighted(input, target, from_logits)
    w_ent = w_ent.at[:, col].set(ce)
    w_ex = w_ex.at[:, col].set(nex)
    w_pos = w_pos.at[:, col].set(npos)
    if lifetime:
        ent, ex, pos = ent + ce, ex + nex, pos + npos
    return w_ent, w_ex, w_pos, ent, ex, pos


class WindowedBinaryNormalizedEntropy(
    WindowedLifetimeMixin, Metric[Union[jax.Array, Tuple[jax.Array, jax.Array]]]
):
    """Windowed (and optionally lifetime) normalized binary cross entropy
    (reference ``window/normalized_entropy.py:22-77``)."""

    _window_states = (
        "windowed_total_entropy",
        "windowed_num_examples",
        "windowed_num_positive",
    )
    _window_counters = ("total_updates",)
    _lifetime_states = _LIFETIME_STATES

    def __init__(
        self,
        *,
        from_logits: bool = False,
        num_tasks: int = 1,
        max_num_updates: int = 100,
        enable_lifetime: bool = True,
        device=None,
    ) -> None:
        super().__init__(device=device)
        self.from_logits = from_logits
        self._init_task_window(
            num_tasks, max_num_updates, enable_lifetime, _accum_dtype()
        )

    def update(
        self, input, target, *, weight=None
    ) -> "WindowedBinaryNormalizedEntropy":
        """Write this update's sufficient statistics into the next window
        column (reference ``window/normalized_entropy.py:146-179``)."""
        input, target = jnp.asarray(input), jnp.asarray(target)
        if weight is not None:
            weight = jnp.asarray(weight)
        _ne_input_check(input, target, self.from_logits, self.num_tasks, weight)
        # Kernel + column write + lifetime adds in one dispatch.  The
        # lifetime states only exist when enabled; the inert _EMPTY rides
        # through the fused call otherwise (its adds are traced out).
        lifetime_in = (
            (self.total_entropy, self.num_examples, self.num_positive)
            if self.enable_lifetime
            else (_EMPTY, _EMPTY, _EMPTY)
        )
        (
            self.windowed_total_entropy,
            self.windowed_num_examples,
            self.windowed_num_positive,
            ent,
            ex,
            pos,
        ) = _windowed_ne_update_fused(
            self.windowed_total_entropy,
            self.windowed_num_examples,
            self.windowed_num_positive,
            *lifetime_in,
            input,
            target,
            weight if weight is not None else _EMPTY,
            self.next_inserted,
            self.from_logits,
            self.enable_lifetime,
            weight is not None,
        )
        if self.enable_lifetime:
            self.total_entropy, self.num_examples, self.num_positive = ent, ex, pos
        self._window_advance(1)
        self.total_updates += 1
        return self

    def compute(self) -> Union[jax.Array, Tuple[jax.Array, jax.Array]]:
        """``(lifetime, windowed)`` NE when ``enable_lifetime`` else the
        windowed NE; empty array(s) before any update
        (reference ``window/normalized_entropy.py:181-230``)."""
        if self._num_valid == 0:
            empty = jnp.zeros(0)
            return (empty, empty) if self.enable_lifetime else empty

        ncols = self._num_valid
        w_entropy = self.windowed_total_entropy[:, :ncols].sum(axis=1)
        w_examples = self.windowed_num_examples[:, :ncols].sum(axis=1)
        w_positive = self.windowed_num_positive[:, :ncols].sum(axis=1)
        windowed_ne = (w_entropy / w_examples) / _baseline_update(
            w_positive, w_examples
        )
        if self.enable_lifetime:
            lifetime_ne = (self.total_entropy / self.num_examples) / _baseline_update(
                self.num_positive, self.num_examples
            )
            return lifetime_ne, windowed_ne
        return windowed_ne

    def merge_state(
        self, metrics: Iterable["WindowedBinaryNormalizedEntropy"]
    ) -> "WindowedBinaryNormalizedEntropy":
        """Pack every metric's valid window columns into an enlarged window
        (size = sum of window sizes) and add lifetime vectors
        (reference ``window/normalized_entropy.py:232-296``;
        WindowedLifetimeMixin)."""
        return self._merge_windowed(metrics)
