"""Windowed class metrics (reference ``torcheval/metrics/window/``):
ring-buffer states over the last N samples / update calls."""

from torcheval_tpu.metrics.window.auroc import WindowedBinaryAUROC
from torcheval_tpu.metrics.window.normalized_entropy import (
    WindowedBinaryNormalizedEntropy,
)

__all__ = [
    "WindowedBinaryAUROC",
    "WindowedBinaryNormalizedEntropy",
]
