"""Windowed class metrics (reference ``torcheval/metrics/window/``):
ring-buffer states over the last N samples / update calls."""

from torcheval_tpu.metrics.window.auroc import WindowedBinaryAUROC
from torcheval_tpu.metrics.window.click_through_rate import WindowedClickThroughRate
from torcheval_tpu.metrics.window.mean_squared_error import WindowedMeanSquaredError
from torcheval_tpu.metrics.window.normalized_entropy import (
    WindowedBinaryNormalizedEntropy,
)
from torcheval_tpu.metrics.window.weighted_calibration import (
    WindowedWeightedCalibration,
)

__all__ = [
    "WindowedBinaryAUROC",
    "WindowedBinaryNormalizedEntropy",
    "WindowedClickThroughRate",
    "WindowedMeanSquaredError",
    "WindowedWeightedCalibration",
]
