"""WindowedMeanSquaredError — MSE over the last ``max_num_updates`` update
calls, plus optional lifetime values.

Beyond the v0.0.4 snapshot (upstream torcheval added
``WindowedMeanSquaredError`` later).  Window design follows
``WindowedBinaryNormalizedEntropy`` (per-update sufficient statistics in
ring columns, valid-prefix invariant via ``RingWindowMixin``); the row
dimension is the output dimension, sized lazily on the first update the
way ``MeanSquaredError``'s per-output state grows on its first 2-D
update."""

from typing import Iterable, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import WindowedLifetimeMixin
from torcheval_tpu.metrics.functional.regression.mean_squared_error import (
    _mean_squared_error_compute,
    _mean_squared_error_param_check,
    _mean_squared_error_update_input_check,
    _update_unweighted,
    _update_weighted,
)
from torcheval_tpu.metrics.metric import Metric


class WindowedMeanSquaredError(
    WindowedLifetimeMixin, Metric[Union[jax.Array, Tuple[jax.Array, jax.Array]]]
):
    """Windowed (and optionally lifetime) mean squared error with
    ``uniform_average`` / ``raw_values`` multioutput."""

    _window_states = ("windowed_sum_squared_error", "windowed_sum_weight")
    _window_counters = ("total_updates", "_num_outputs")
    # sum_squared_error needs grow-aware addition, handled in merge_state;
    # only sum_weight rides the mixin's plain lifetime add.
    _lifetime_states = ("sum_weight",)

    @property
    def _fused_lifetime(self) -> tuple:
        return ("sum_squared_error", "sum_weight")

    def __init__(
        self,
        *,
        multioutput: str = "uniform_average",
        max_num_updates: int = 100,
        enable_lifetime: bool = True,
        device=None,
    ) -> None:
        super().__init__(device=device)
        _mean_squared_error_param_check(multioutput)
        if max_num_updates < 1:
            raise ValueError(
                "`max_num_updates` value should be greater than and equal to 1, "
                f"but received {max_num_updates}. "
            )
        self.multioutput = multioutput
        self.enable_lifetime = enable_lifetime
        self._init_window(max_num_updates)
        self.total_updates = 0
        # 0 = undecided, 1 with 1-D updates seen = scalar outputs, else the
        # output dimension of the 2-D updates.  Rides state_dict via
        # _window_counters.
        self._num_outputs = 0
        if enable_lifetime:
            self._add_state("sum_squared_error", jnp.asarray(0.0))
            self._add_state("sum_weight", jnp.asarray(0.0))
        self._add_state(
            "windowed_sum_squared_error", jnp.zeros((1, max_num_updates))
        )
        self._add_state("windowed_sum_weight", jnp.zeros((1, max_num_updates)))

    def _ensure_rows(self, input: jax.Array) -> None:
        """Decide/verify the output dimension; grow the window row dim (and
        the lifetime state, like MeanSquaredError) on the first 2-D update."""
        num_outputs = 1 if input.ndim == 1 else input.shape[1]
        if self._num_outputs == 0:
            self._num_outputs = num_outputs
            if num_outputs > 1:
                self.windowed_sum_squared_error = jnp.zeros(
                    (num_outputs, self._window_capacity)
                )
                if self.enable_lifetime:
                    self.sum_squared_error = jnp.zeros(num_outputs)
        elif num_outputs != self._num_outputs:
            raise ValueError(
                "The number of outputs must stay fixed across updates, got "
                f"{num_outputs} after {self._num_outputs}."
            )

    def update(
        self, input, target, *, sample_weight=None
    ) -> "WindowedMeanSquaredError":
        input, target = jnp.asarray(input), jnp.asarray(target)
        if sample_weight is not None:
            sample_weight = jnp.asarray(sample_weight)
        _mean_squared_error_update_input_check(input, target, sample_weight)
        self._ensure_rows(input)
        if sample_weight is None:
            kernel, args = _update_unweighted, (input, target)
        else:
            kernel, args = _update_weighted, (input, target, sample_weight)
        self._update_windowed_pair(kernel, args)
        return self

    def _finalize(self, sse: jax.Array, weight: jax.Array) -> jax.Array:
        if self._num_outputs <= 1:
            sse = jnp.squeeze(sse)
        return _mean_squared_error_compute(sse, self.multioutput, weight)

    def compute(self) -> Union[jax.Array, Tuple[jax.Array, jax.Array]]:
        """``(lifetime, windowed)`` MSE when ``enable_lifetime`` else the
        windowed MSE; empty array(s) before any update."""
        if self._num_valid == 0:
            empty = jnp.zeros(0)
            return (empty, empty) if self.enable_lifetime else empty
        ncols = self._num_valid
        windowed = self._finalize(
            self.windowed_sum_squared_error[:, :ncols].sum(axis=1),
            self.windowed_sum_weight[0, :ncols].sum(),
        )
        if self.enable_lifetime:
            lifetime = _mean_squared_error_compute(
                self.sum_squared_error, self.multioutput, self.sum_weight
            )
            return lifetime, windowed
        return windowed

    def merge_state(
        self, metrics: Iterable["WindowedMeanSquaredError"]
    ) -> "WindowedMeanSquaredError":
        """Pack every metric's valid window columns into an enlarged window
        and add lifetime values."""
        metrics = list(metrics)
        for m in metrics:
            if (
                m._num_outputs
                and self._num_outputs
                and m._num_outputs != self._num_outputs
            ):
                raise ValueError(
                    "Merged metrics must have the same number of outputs; "
                    f"got {self._num_outputs} vs {m._num_outputs}."
                )
        # Adopt the output dimension of the first sized metric so an
        # un-updated recipient can absorb vector-output sources.
        for m in metrics:
            if self._num_outputs == 0 and m._num_outputs:
                self._ensure_rows(
                    jnp.zeros((0, m._num_outputs))
                    if m._num_outputs > 1
                    else jnp.zeros(0)
                )
        self._merge_windowed(metrics)
        if self.enable_lifetime:
            for m in metrics:
                # Grow-aware add (scalar state absorbs a vector source),
                # like MeanSquaredError.merge_state.
                other = jax.device_put(m.sum_squared_error, self.device)
                if self.sum_squared_error.ndim == 0 and other.ndim == 1:
                    self.sum_squared_error = other
                else:
                    self.sum_squared_error = self.sum_squared_error + other
        return self

    def reset(self) -> "WindowedMeanSquaredError":
        """Reset states AND the host-side window bookkeeping."""
        super().reset()
        self._num_outputs = 0
        return self
