"""MeanSquaredError metric — parity with reference
``torcheval/metrics/regression/mean_squared_error.py`` (138 LoC).

States: ``sum_squared_error`` + ``sum_weight``; per-output state grows from
scalar to vector on the first 2-D update (reference behavior); merge: add."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics.functional.regression.mean_squared_error import (
    _mean_squared_error_compute,
    _mean_squared_error_param_check,
    _mean_squared_error_update_input_check,
    _update_unweighted,
    _update_weighted,
)
from torcheval_tpu.metrics.metric import Metric


class MeanSquaredError(Metric[jax.Array]):
    def __init__(
        self,
        *,
        multioutput: str = "uniform_average",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _mean_squared_error_param_check(multioutput)
        self.multioutput = multioutput
        self._add_state("sum_squared_error", jnp.asarray(0.0))
        self._add_state("sum_weight", jnp.asarray(0.0))

    def update(
        self,
        input,
        target,
        *,
        sample_weight=None,
    ) -> "MeanSquaredError":
        input, target = jnp.asarray(input), jnp.asarray(target)
        if sample_weight is not None:
            sample_weight = jnp.asarray(sample_weight)
        _mean_squared_error_update_input_check(input, target, sample_weight)
        # Kernel + state adds fused into one dispatch; ``grow`` replicates
        # the scalar→vector replace-on-first-2-D-update state semantics.
        if sample_weight is None:
            kernel, args = _update_unweighted, (input, target)
        else:
            kernel, args = _update_weighted, (input, target, sample_weight)
        self.sum_squared_error, self.sum_weight = accumulate(
            kernel,
            (self.sum_squared_error, self.sum_weight),
            *args,
            grow=True,
        )
        return self

    def compute(self) -> jax.Array:
        """MSE; NaN before any update (0/0)."""
        return _mean_squared_error_compute(
            self.sum_squared_error, self.multioutput, self.sum_weight
        )

    def merge_state(self, metrics: Iterable["MeanSquaredError"]) -> "MeanSquaredError":
        for metric in metrics:
            other = jax.device_put(metric.sum_squared_error, self.device)
            if self.sum_squared_error.ndim == 0 and other.ndim == 1:
                self.sum_squared_error = other
            else:
                self.sum_squared_error = self.sum_squared_error + other
            self.sum_weight = self.sum_weight + jax.device_put(
                metric.sum_weight, self.device
            )
        return self
