"""R2Score metric — parity with reference
``torcheval/metrics/regression/r2_score.py`` (162 LoC).

States: ``sum_squared_obs`` / ``sum_obs`` / ``sum_squared_residual`` /
``num_obs`` (streaming TSS/RSS); per-output states grow from scalar to
vector on the first 2-D update; merge: add."""

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics.functional.regression.r2_score import (
    _r2_score_compute,
    _r2_score_param_check,
    _r2_score_update_input_check,
    _update as _r2_update_kernel,
)
from torcheval_tpu.metrics.metric import Metric

_GROWABLE = ("sum_squared_obs", "sum_obs", "sum_squared_residual")


class R2Score(Metric[jax.Array]):
    def __init__(
        self,
        *,
        multioutput: str = "uniform_average",
        num_regressors: int = 0,
        device=None,
    ) -> None:
        super().__init__(device=device)
        _r2_score_param_check(multioutput, num_regressors)
        self.multioutput = multioutput
        self.num_regressors = num_regressors
        self._add_state("sum_squared_obs", jnp.asarray(0.0))
        self._add_state("sum_obs", jnp.asarray(0.0))
        self._add_state("sum_squared_residual", jnp.asarray(0.0))
        self._add_state("num_obs", jnp.asarray(0.0))

    def update(self, input, target) -> "R2Score":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _r2_score_update_input_check(input, target)
        # Kernel + all four state adds fused into one dispatch; ``grow``
        # replicates the scalar→vector replace-on-first-2-D-update state
        # semantics (``num_obs`` stays scalar, so it always adds).
        (
            self.sum_squared_obs,
            self.sum_obs,
            self.sum_squared_residual,
            self.num_obs,
        ) = accumulate(
            _r2_update_kernel,
            (
                self.sum_squared_obs,
                self.sum_obs,
                self.sum_squared_residual,
                self.num_obs,
            ),
            input,
            target,
            grow=True,
        )
        return self

    def compute(self) -> jax.Array:
        """R²; raises before enough data (n < 2) like the reference
        (``r2_score.py:117-125``)."""
        return _r2_score_compute(
            self.sum_squared_obs,
            self.sum_obs,
            self.sum_squared_residual,
            self.num_obs,
            self.multioutput,
            self.num_regressors,
        )

    def merge_state(self, metrics: Iterable["R2Score"]) -> "R2Score":
        for metric in metrics:
            if self.sum_squared_obs.ndim == 0 and metric.sum_squared_obs.ndim == 1:
                for name in _GROWABLE:
                    setattr(
                        self, name, jax.device_put(getattr(metric, name), self.device)
                    )
            else:
                for name in _GROWABLE:
                    setattr(
                        self,
                        name,
                        getattr(self, name)
                        + jax.device_put(getattr(metric, name), self.device),
                    )
            self.num_obs = self.num_obs + jax.device_put(metric.num_obs, self.device)
        return self
