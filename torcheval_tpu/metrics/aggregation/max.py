"""Max metric — parity with reference ``torcheval/metrics/aggregation/max.py``
(63 LoC). State: scalar initialized to -inf; merge: pairwise max."""

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics.metric import Metric


class Max(Metric[jax.Array]):
    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("max", jnp.asarray(float("-inf")))

    def update(self, input) -> "Max":
        # Reduction + state fold in one dispatch (_fuse.py).
        (self.max,) = accumulate(
            jnp.max, (self.max,), jnp.asarray(input), fold=jnp.maximum
        )
        return self

    def compute(self) -> jax.Array:
        return self.max

    def merge_state(self, metrics: Iterable["Max"]) -> "Max":
        for metric in metrics:
            self.max = jnp.maximum(self.max, jax.device_put(metric.max, self.device))
        return self
