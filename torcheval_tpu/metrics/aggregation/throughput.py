"""Throughput metric — parity with reference
``torcheval/metrics/aggregation/throughput.py`` (108 LoC).

States: ``num_total`` + ``elapsed_time_sec``; merge adds counts but takes the
**max** elapsed time — in distributed synchronous training the slowest rank
gates the pipeline (reference ``throughput.py:97-107``; distributed caveat
documented at ``throughput.py:25-28``).  Update takes Python numbers
(host wall-clock), not arrays (reference ``throughput.py:59-87``)."""

import logging
from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.metric import Metric

_logger: logging.Logger = logging.getLogger(__name__)


class Throughput(Metric[jax.Array]):
    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("num_total", jnp.asarray(0.0))
        self._add_state("elapsed_time_sec", jnp.asarray(0.0))

    def update(self, num_processed: int, elapsed_time_sec: float) -> "Throughput":
        if num_processed < 0:
            raise ValueError(
                "Expected num_processed to be a non-negative number, but "
                f"received {num_processed}."
            )
        if elapsed_time_sec <= 0:
            raise ValueError(
                "Expected elapsed_time_sec to be a positive number, but "
                f"received {elapsed_time_sec}."
            )
        self.elapsed_time_sec = self.elapsed_time_sec + elapsed_time_sec
        self.num_total = self.num_total + num_processed
        return self

    def compute(self) -> jax.Array:
        """Items/sec; warns and returns 0.0 before any update
        (reference ``throughput.py:90-95``)."""
        if not float(self.elapsed_time_sec):
            _logger.warning("No calls to update() have been made - returning 0.0")
            return jnp.asarray(0.0)
        return self.num_total / self.elapsed_time_sec

    def merge_state(self, metrics: Iterable["Throughput"]) -> "Throughput":
        for metric in metrics:
            self.num_total = self.num_total + jax.device_put(
                metric.num_total, self.device
            )
            self.elapsed_time_sec = jnp.maximum(
                self.elapsed_time_sec,
                jax.device_put(metric.elapsed_time_sec, self.device),
            )
        return self
