"""Cat metric — parity with reference ``torcheval/metrics/aggregation/cat.py``
(96 LoC). Buffer state: list of arrays concatenated along ``dim`` at compute;
``_prepare_for_merge_state`` pre-concatenates so the sync wire carries one
buffer (reference ``cat.py:93-96``)."""

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import merge_concat_buffers, prepare_concat_buffers
from torcheval_tpu.metrics.metric import Metric


class Cat(Metric[jax.Array]):
    """Concatenate all input arrays. Functional version is ``jnp.concatenate``
    (reference ``cat.py:21-22``)."""

    def __init__(self, *, dim: int = 0, device=None) -> None:
        super().__init__(device=device)
        self.dim = dim
        self._add_state("inputs", [])

    def update(self, input) -> "Cat":
        self.inputs.append(jax.device_put(jnp.asarray(input), self.device))
        return self

    def compute(self) -> jax.Array:
        """Concatenated inputs; ``jnp.zeros(0)`` when no update has been made
        (reference ``cat.py:77-82``)."""
        if not self.inputs:
            return jnp.zeros(0)
        return jnp.concatenate(self.inputs, axis=self.dim)

    def merge_state(self, metrics: Iterable["Cat"]) -> "Cat":
        for metric in metrics:
            merge_concat_buffers(self, [metric], "inputs", dim=metric.dim)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "inputs", dim=self.dim)
