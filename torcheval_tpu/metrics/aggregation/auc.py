"""AUC metric — buffered (x, y) curve samples, trapezoid at compute.

Beyond the v0.0.4 snapshot (upstream torcheval added ``AUC`` later).
Buffer states like the exact curve metrics: points accumulate across
updates (and across ranks via concat merge) and the area is integrated
once over the full, optionally re-sorted, curve."""

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import merge_concat_buffers, prepare_concat_buffers
from torcheval_tpu.metrics.functional.aggregation.auc import (
    _auc_compute_kernel,
    _auc_input_check,
)
from torcheval_tpu.metrics.metric import Metric


class AUC(Metric[jax.Array]):
    """Area under the curve sampled by all (x, y) updates so far."""

    def __init__(
        self, *, reorder: bool = True, num_tasks: int = 1, device=None
    ) -> None:
        super().__init__(device=device)
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        self.reorder = reorder
        self.num_tasks = num_tasks
        self._add_state("x", [])
        self._add_state("y", [])

    def update(self, x, y) -> "AUC":
        x, y = jnp.asarray(x), jnp.asarray(y)
        _auc_input_check(x, y, self.num_tasks)
        self.x.append(jax.device_put(x, self.device))
        self.y.append(jax.device_put(y, self.device))
        return self

    def compute(self) -> jax.Array:
        """Trapezoidal area per task; zeros before any update."""
        if not self.x:
            return jnp.zeros(()) if self.num_tasks == 1 else jnp.zeros(
                self.num_tasks
            )
        return _auc_compute_kernel(
            jnp.concatenate(self.x, axis=-1),
            jnp.concatenate(self.y, axis=-1),
            self.reorder,
        )

    def merge_state(self, metrics: Iterable["AUC"]) -> "AUC":
        merge_concat_buffers(self, metrics, "x", "y", dim=-1)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "x", "y", dim=-1)
