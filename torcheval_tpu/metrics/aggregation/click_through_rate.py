"""ClickThroughRate metric — per-task counter states.

Beyond the v0.0.4 snapshot (upstream torcheval added ``ClickThroughRate``
later).  Same counter-state shape as ``WeightedCalibration``: two per-task
sums, add-mergeable, ``psum``-syncable."""

from typing import Iterable, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.aggregation.click_through_rate import (
    _ctr_select_kernel,
)
from torcheval_tpu.metrics.functional.classification.binary_normalized_entropy import (
    _accum_dtype,
)
from torcheval_tpu.metrics.metric import Metric


class ClickThroughRate(Metric[jax.Array]):
    """Weighted click fraction Σw·click / Σw per task; NaN before any
    weighted update (0/0), like ``WeightedCalibration``."""

    def __init__(self, *, num_tasks: int = 1, device=None) -> None:
        super().__init__(device=device)
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        self.num_tasks = num_tasks
        self._add_state("click_total", jnp.zeros(num_tasks, dtype=_accum_dtype()))
        self._add_state("weight_total", jnp.zeros(num_tasks, dtype=_accum_dtype()))

    def update(
        self, input, weights: Union[float, int, "jax.Array"] = 1.0
    ) -> "ClickThroughRate":
        input = jnp.asarray(input)
        kernel, args = _ctr_select_kernel(input, weights, num_tasks=self.num_tasks)
        # Kernel + both state adds fused into one dispatch (_fuse.py).
        self.click_total, self.weight_total = accumulate(
            kernel, (self.click_total, self.weight_total), *args
        )
        return self

    def compute(self) -> jax.Array:
        """CTR per task (scalar when ``num_tasks == 1``)."""
        ctr = self.click_total / self.weight_total
        return ctr[0] if self.num_tasks == 1 else ctr

    def merge_state(self, metrics: Iterable["ClickThroughRate"]):
        merge_add(self, metrics, "click_total", "weight_total")
        return self
