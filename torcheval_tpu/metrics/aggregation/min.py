"""Min metric — parity with reference ``torcheval/metrics/aggregation/min.py``
(63 LoC). State: scalar initialized to +inf; merge: pairwise min."""

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics.metric import Metric


class Min(Metric[jax.Array]):
    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("min", jnp.asarray(float("inf")))

    def update(self, input) -> "Min":
        # Reduction + state fold in one dispatch (_fuse.py).
        (self.min,) = accumulate(
            jnp.min, (self.min,), jnp.asarray(input), fold=jnp.minimum
        )
        return self

    def compute(self) -> jax.Array:
        return self.min

    def merge_state(self, metrics: Iterable["Min"]) -> "Min":
        for metric in metrics:
            self.min = jnp.minimum(self.min, jax.device_put(metric.min, self.device))
        return self
