from torcheval_tpu.metrics.aggregation.auc import AUC
from torcheval_tpu.metrics.aggregation.cat import Cat
from torcheval_tpu.metrics.aggregation.click_through_rate import ClickThroughRate
from torcheval_tpu.metrics.aggregation.max import Max
from torcheval_tpu.metrics.aggregation.mean import Mean
from torcheval_tpu.metrics.aggregation.min import Min
from torcheval_tpu.metrics.aggregation.sum import Sum
from torcheval_tpu.metrics.aggregation.throughput import Throughput

__all__ = ["AUC", "Cat", "ClickThroughRate", "Max", "Mean", "Min", "Sum", "Throughput"]
