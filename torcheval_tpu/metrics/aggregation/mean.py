"""Mean metric — parity with reference ``torcheval/metrics/aggregation/mean.py``
(102 LoC). State: ``weighted_sum`` + ``weights``; merge: add."""

import logging
from typing import Iterable, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics.functional.aggregation.mean import _mean_select_kernel
from torcheval_tpu.metrics.metric import Metric

_logger: logging.Logger = logging.getLogger(__name__)


class Mean(Metric[jax.Array]):
    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("weighted_sum", jnp.asarray(0.0))
        self._add_state("weights", jnp.asarray(0.0))

    def update(self, input, weight: Union[float, int, "jax.Array"] = 1.0) -> "Mean":
        kernel, args = _mean_select_kernel(jnp.asarray(input), weight)
        # Kernel + both state adds fused into one dispatch (_fuse.py).
        self.weighted_sum, self.weights = accumulate(
            kernel, (self.weighted_sum, self.weights), *args
        )
        return self

    def compute(self) -> jax.Array:
        """Weighted mean; warns and returns 0.0 when no update has
        contributed (reference ``mean.py:63-71``)."""
        if not float(self.weighted_sum):
            _logger.warning("No calls to update() have been made - returning 0.0")
            return jnp.asarray(0.0)
        return self.weighted_sum / self.weights

    def merge_state(self, metrics: Iterable["Mean"]) -> "Mean":
        for metric in metrics:
            self.weighted_sum = self.weighted_sum + jax.device_put(
                metric.weighted_sum, self.device
            )
            self.weights = self.weights + jax.device_put(metric.weights, self.device)
        return self
