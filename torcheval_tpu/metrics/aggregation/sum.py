"""Sum metric — parity with reference ``torcheval/metrics/aggregation/sum.py``
(86 LoC). State: scalar ``weighted_sum``; merge: add (→ ``psum`` on a mesh)."""

from typing import Iterable, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics.functional.aggregation.sum import (
    _sum_validate,
    _weighted_sum,
)
from torcheval_tpu.metrics.metric import Metric


class Sum(Metric[jax.Array]):
    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("weighted_sum", jnp.asarray(0.0))

    def update(self, input, weight: Union[float, int, "jax.Array"] = 1.0) -> "Sum":
        input = jnp.asarray(input)
        _sum_validate(input, weight)
        # Kernel + state add fused into one dispatch (_fuse.py).
        (self.weighted_sum,) = accumulate(
            _weighted_sum, (self.weighted_sum,), input, weight
        )
        return self

    def compute(self) -> jax.Array:
        return self.weighted_sum

    def merge_state(self, metrics: Iterable["Sum"]) -> "Sum":
        for metric in metrics:
            self.weighted_sum = self.weighted_sum + jax.device_put(
                metric.weighted_sum, self.device
            )
        return self
