from torcheval_tpu.metrics.image.fid import FrechetInceptionDistance
from torcheval_tpu.metrics.image.psnr import PeakSignalNoiseRatio
from torcheval_tpu.metrics.image.ssim import StructuralSimilarity

__all__ = [
    "FrechetInceptionDistance",
    "PeakSignalNoiseRatio",
    "StructuralSimilarity",
]
