"""StructuralSimilarity metric — counter states over per-image SSIM.

Beyond the v0.0.4 snapshot (upstream torcheval added image metrics
later)."""

from typing import Iterable, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.image.ssim import (
    _ssim_input_check,
    _ssim_per_image,
)
from torcheval_tpu.metrics.metric import Metric


def _ssim_class_update_kernel(
    input: jax.Array,
    target: jax.Array,
    data_range: float,
    kernel_size: int,
    sigma: float,
    k1: float,
    k2: float,
) -> Tuple[jax.Array, jax.Array]:
    per_image = _ssim_per_image(
        input, target, data_range, kernel_size, sigma, k1, k2
    )
    return per_image.sum(), jnp.asarray(per_image.shape[0], jnp.float32)


class StructuralSimilarity(Metric[jax.Array]):
    """Mean SSIM over all images seen; NaN before any update (0/0)."""

    def __init__(
        self,
        *,
        data_range: float = 1.0,
        kernel_size: int = 11,
        sigma: float = 1.5,
        k1: float = 0.01,
        k2: float = 0.03,
        device=None,
    ) -> None:
        super().__init__(device=device)
        self.data_range = float(data_range)
        self.kernel_size = kernel_size
        self.sigma = float(sigma)
        self.k1 = float(k1)
        self.k2 = float(k2)
        self._add_state("mssim_sum", jnp.asarray(0.0))
        self._add_state("num_images", jnp.asarray(0.0))

    def update(self, input, target) -> "StructuralSimilarity":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _ssim_input_check(input, target, self.kernel_size)
        # Kernel + both state adds fused into one dispatch (_fuse.py).
        self.mssim_sum, self.num_images = accumulate(
            _ssim_class_update_kernel,
            (self.mssim_sum, self.num_images),
            input,
            target,
            statics=(
                self.data_range,
                self.kernel_size,
                self.sigma,
                self.k1,
                self.k2,
            ),
        )
        return self

    def compute(self) -> jax.Array:
        return self.mssim_sum / self.num_images

    def merge_state(
        self, metrics: Iterable["StructuralSimilarity"]
    ) -> "StructuralSimilarity":
        merge_add(self, metrics, "mssim_sum", "num_images")
        return self
