"""PeakSignalNoiseRatio metric — counter states.

Beyond the v0.0.4 snapshot (upstream torcheval added image metrics
later).  States: summed squared error + element count (add merge) and,
when ``data_range`` is unset, the observed target min/max (extremum
merge, like Min/Max)."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.image.psnr import (
    _psnr_compute,
    _psnr_input_check,
    _psnr_param_check,
    _psnr_update_kernel,
)
from torcheval_tpu.metrics.metric import Metric


@jax.jit
def _psnr_class_update_kernel(input: jax.Array, target: jax.Array):
    sum_se, n, _ = _psnr_update_kernel(input, target)
    return sum_se, n, target.min(), target.max()


# Module-level identity: part of the fused-update jit cache key.
_PSNR_FOLDS = (None, None, jnp.minimum, jnp.maximum)


class PeakSignalNoiseRatio(Metric[jax.Array]):
    """PSNR over everything seen; NaN before any update (0/0)."""

    def __init__(self, *, data_range: Optional[float] = None, device=None) -> None:
        super().__init__(device=device)
        _psnr_param_check(data_range)
        self.data_range = data_range
        self._add_state("sum_squared_error", jnp.asarray(0.0))
        self._add_state("num_observations", jnp.asarray(0.0))
        self._add_state("target_min", jnp.asarray(jnp.inf))
        self._add_state("target_max", jnp.asarray(-jnp.inf))

    def update(self, input, target) -> "PeakSignalNoiseRatio":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _psnr_input_check(input, target)
        # Kernel + all four state folds in one dispatch; the extremum
        # states fold with min/max instead of addition.
        (
            self.sum_squared_error,
            self.num_observations,
            self.target_min,
            self.target_max,
        ) = accumulate(
            _psnr_class_update_kernel,
            (
                self.sum_squared_error,
                self.num_observations,
                self.target_min,
                self.target_max,
            ),
            input,
            target,
            fold=_PSNR_FOLDS,
        )
        return self

    def compute(self) -> jax.Array:
        data_range = (
            jnp.asarray(float(self.data_range))
            if self.data_range is not None
            else self.target_max - self.target_min
        )
        return _psnr_compute(
            self.sum_squared_error, self.num_observations, data_range
        )

    def merge_state(
        self, metrics: Iterable["PeakSignalNoiseRatio"]
    ) -> "PeakSignalNoiseRatio":
        merge_add(self, metrics, "sum_squared_error", "num_observations")
        for other in metrics:
            self.target_min = jnp.minimum(
                self.target_min, jax.device_put(other.target_min, self.device)
            )
            self.target_max = jnp.maximum(
                self.target_max, jax.device_put(other.target_max, self.device)
            )
        return self
