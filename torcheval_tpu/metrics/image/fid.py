"""FrechetInceptionDistance — streaming feature mean/covariance per
distribution + the eigendecomposition Fréchet distance.

Beyond the v0.0.4 snapshot (upstream torcheval added FID later).

Documented divergence: upstream downloads InceptionV3 weights on first
use.  This environment is offline, so the feature extractor is an
explicit constructor argument — any callable mapping an image batch to
``(N, feature_dim)`` embeddings (a flax/haiku apply fn, a jitted
function, anything).  The streaming statistics are add-mergeable
(per-distribution sum, outer-product sum, count), so the metric syncs
like every counter metric."""

from typing import Callable, Iterable, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.image.fid import (
    _gaussian_frechet_distance_kernel,
)
from torcheval_tpu.metrics.metric import Metric

_STATES = (
    "real_sum",
    "real_cov_sum",
    "num_real_images",
    "fake_sum",
    "fake_cov_sum",
    "num_fake_images",
)


@jax.jit
def _feature_stats(feats: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    feats = feats.astype(jnp.float32)
    return feats.sum(axis=0), feats.T @ feats, jnp.asarray(
        feats.shape[0], jnp.float32
    )


@jax.jit
def _mean_cov(
    total: jax.Array, cov_sum: jax.Array, n: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    mu = total / n
    cov = (cov_sum - n * jnp.outer(mu, mu)) / (n - 1.0)
    return mu, cov


class FrechetInceptionDistance(Metric[jax.Array]):
    """FID between the real and generated feature distributions seen."""

    def __init__(
        self,
        model: Callable[[jax.Array], jax.Array],
        *,
        feature_dim: int,
        device=None,
    ) -> None:
        super().__init__(device=device)
        if not callable(model):
            raise ValueError(
                "`model` must be a callable mapping an image batch to "
                "(N, feature_dim) embeddings; this offline build has no "
                "downloadable InceptionV3 default."
            )
        if feature_dim < 1:
            raise ValueError(
                f"`feature_dim` should be positive, got {feature_dim}."
            )
        self.model = model
        self.feature_dim = feature_dim
        for prefix in ("real", "fake"):
            self._add_state(f"{prefix}_sum", jnp.zeros(feature_dim))
            self._add_state(
                f"{prefix}_cov_sum", jnp.zeros((feature_dim, feature_dim))
            )
            self._add_state(f"num_{prefix}_images", jnp.asarray(0.0))

    # The feature extractor is only needed by update(); compute/merge work
    # from the accumulated statistics alone.  Dropping it from pickles lets
    # the object-sync toolkit ship FID metrics regardless of whether the
    # extractor itself is picklable (closures, bound apply fns, ...).
    def __getstate__(self):
        state = super().__getstate__()
        state["model"] = None
        return state

    # In-process cloning (clone_metric / deepcopy-per-rank test patterns)
    # must keep the extractor: share the callable, the device handle, and
    # the immutable array buffers; deep-copy the rest.  Only the
    # cross-process pickle drops the model.
    def __copy__(self):
        clone = self.__class__.__new__(self.__class__)
        clone.__dict__.update(self.__dict__)
        return clone

    def __deepcopy__(self, memo):
        import copy

        clone = self.__class__.__new__(self.__class__)
        memo[id(self)] = clone
        for key, value in self.__dict__.items():
            if key in ("model", "_device") or isinstance(value, jax.Array):
                clone.__dict__[key] = value
            else:
                clone.__dict__[key] = copy.deepcopy(value, memo)
        return clone

    def update(self, images, *, is_real: bool) -> "FrechetInceptionDistance":
        if self.model is None:
            raise RuntimeError(
                "This FrechetInceptionDistance was deserialized without its "
                "feature extractor (extractors do not ride pickles); assign "
                "`metric.model` before calling update()."
            )
        feats = jnp.asarray(self.model(images))
        if feats.ndim != 2 or feats.shape[1] != self.feature_dim:
            raise ValueError(
                "the feature extractor should return shape "
                f"(num_images, {self.feature_dim}), got {feats.shape}."
            )
        total, cov_sum, n = _feature_stats(feats)
        prefix = "real" if is_real else "fake"
        setattr(self, f"{prefix}_sum", getattr(self, f"{prefix}_sum") + total)
        setattr(
            self,
            f"{prefix}_cov_sum",
            getattr(self, f"{prefix}_cov_sum") + cov_sum,
        )
        setattr(
            self,
            f"num_{prefix}_images",
            getattr(self, f"num_{prefix}_images") + n,
        )
        return self

    def compute(self) -> jax.Array:
        """FID over everything seen.  Each side needs at least two images
        for an unbiased covariance."""
        for name, n in (
            ("real", self.num_real_images),
            ("fake", self.num_fake_images),
        ):
            if float(n) < 2:
                raise RuntimeError(
                    f"computing FID requires at least 2 {name} images, got "
                    f"{int(float(n))}."
                )
        mu_r, cov_r = _mean_cov(
            self.real_sum, self.real_cov_sum, self.num_real_images
        )
        mu_f, cov_f = _mean_cov(
            self.fake_sum, self.fake_cov_sum, self.num_fake_images
        )
        return _gaussian_frechet_distance_kernel(mu_r, cov_r, mu_f, cov_f)

    def merge_state(
        self, metrics: Iterable["FrechetInceptionDistance"]
    ) -> "FrechetInceptionDistance":
        merge_add(self, metrics, *_STATES)
        return self
