"""Rank-sketch state machine for the exact-rank curve metrics.

``BinaryAUROC`` / ``BinaryAUPRC`` / ``MulticlassAUROC`` constructed with
``sketch=True`` (or under ``TORCHEVAL_TPU_RANK_SKETCH=1``) swap their
unbounded sample buffers for the fixed-size rank sketch of
:mod:`torcheval_tpu.ops.rank_sketch`: a ``threshold`` edge vector plus
four int32 count arrays over ``(rows, bins)`` — deliberately the *same*
state names and shapes as the binned-AUC family, because those are the
sufficient statistics the collection megakernel already knows how to
fold in one HBM pass (``ops/pallas_mega.py`` kind ``"binned"``).  The
update is a single fused :func:`~torcheval_tpu.metrics._fuse.accumulate`
dispatch; the merge is integer addition (associative, commutative,
bit-deterministic across merge orders — see ``docs/source/sketch.rst``);
the compute reuses the binned trapezoid / step-sum estimators with the
documented ε = ``rank_error_bound(bins)`` rank error.

This module holds the pieces both metric files share: the module-level
kernels (module-level so their identity is stable in the jit cache key),
state installation, the fused accumulate, and the geometry-checked
merge.  The metric classes stay in their reference-parity files and
branch on ``self._sketch_mode``.
"""

from typing import Iterable, Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.classification._sort_scan import class_hits
from torcheval_tpu.ops.rank_sketch import (
    DEFAULT_BINS,
    _select_rank_route,
    rank_counts_rows,
    rank_error_bound,
    uniform_edges,
)

RANK_COUNTS = ("num_tp", "num_fp", "num_pos", "num_total")

# Process-level census of sketch-mode constructions — the sketch-vs-sort
# crossover stamp telemetry.explain_perf()/report() render next to the
# megakernel verdict (bins histogram + the worst predicted ε among live
# configurations).
_CENSUS: dict = {"constructed": 0, "bins": {}}


def sketch_census() -> dict:
    """What the rank-sketch tier looks like in this process: how many
    sketch-mode members were constructed, at which bin capacities, and
    the worst documented ε among them.  Empty dict when the tier never
    engaged (so report sections can be gated on truthiness)."""
    if not _CENSUS["constructed"]:
        return {}
    return {
        "members_constructed": _CENSUS["constructed"],
        "bins": dict(sorted(_CENSUS["bins"].items())),
        "predicted_eps_max": max(
            rank_error_bound(b) for b in _CENSUS["bins"]
        ),
    }


def _rank_binary_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: jax.Array,
    route: str,
    mask=None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    # Traced inside the fused accumulate; ``route`` is a call-time static
    # so the formulation (and the kill-switch) is re-evaluated per update.
    if input.ndim == 1:
        input, target = input[None], target[None]
    return rank_counts_rows(input, target == 1, threshold, route=route, mask=mask)


def _rank_multiclass_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: jax.Array,
    num_classes: int,
    route: str,
    mask=None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    # One-vs-rest rows: scores (N, C) -> (C, N), hits from the label column.
    return rank_counts_rows(
        input.T,
        class_hits(target, num_classes),
        threshold,
        route=route,
        mask=mask,
    )


def install_rank_states(metric, num_rows: int, bins: Optional[int]) -> None:
    """Install the sketch-mode state layout on ``metric``: ``threshold``
    (the ``bins`` uniform edges) plus the four zeroed count arrays, and
    flip the instance to masked-update eligibility (sketch updates fold
    ``mask=`` exactly, so sketch-mode members join ``bucket=``/``slices=``
    collections the buffer states cannot)."""
    bins = DEFAULT_BINS if bins is None else int(bins)
    threshold = uniform_edges(bins)
    _CENSUS["constructed"] += 1
    _CENSUS["bins"][bins] = _CENSUS["bins"].get(bins, 0) + 1
    metric._sketch_bins = bins
    metric._supports_mask = True
    metric._add_state("threshold", threshold)
    metric._add_state("num_tp", jnp.zeros((num_rows, bins), jnp.int32))
    metric._add_state("num_fp", jnp.zeros((num_rows, bins), jnp.int32))
    metric._add_state("num_pos", jnp.zeros(num_rows, jnp.int32))
    metric._add_state("num_total", jnp.zeros(num_rows, jnp.int32))


def rank_accumulate(metric, kernel, input, target, statics=(), mask=None) -> None:
    """One fused dispatch: kernel + all four count adds (``_fuse.py``)."""
    metric.num_tp, metric.num_fp, metric.num_pos, metric.num_total = accumulate(
        kernel,
        (metric.num_tp, metric.num_fp, metric.num_pos, metric.num_total),
        input,
        target,
        metric.threshold,
        statics=statics,
        mask=mask,
    )


def rank_route(metric, num_samples: int) -> str:
    """Call-time (outside-jit) formulation choice for one update."""
    return _select_rank_route(
        metric.num_tp.shape[0], num_samples, metric.threshold
    )


def rank_merge_state(metric, metrics: Iterable) -> None:
    """Geometry-checked integer-add merge: every operand must be a
    sketch-mode metric over the same edge vector.  Addition is
    associative and bit-deterministic, so any merge order (fleet tree,
    flat gather, checkpoint resume) yields identical counts."""
    metrics = list(metrics)
    for m in metrics:
        if not getattr(m, "_sketch_mode", False):
            raise ValueError(
                "cannot merge a sketch-mode metric with a sample-buffer "
                f"metric ({type(m).__name__} constructed without sketch=True)"
            )
        if m.threshold.shape != metric.threshold.shape:
            raise ValueError(
                "sketch merge requires identical edge geometry: "
                f"{m.threshold.shape[0]} bins vs {metric.threshold.shape[0]}"
            )
    merge_add(metric, metrics, *RANK_COUNTS)


def rank_sketch_state(metric, metric_kind: str, kind: str, **options):
    """``Metric.sketch_state`` for a sketch-mode metric: the count
    arrays *are* the O(compactors) mergeable summary, so ``"rank"`` (and
    ``"exact"``, which is lossless here — no sample buffer exists to be
    more exact than the counts) wrap them directly in a
    :class:`~torcheval_tpu.metrics._sketch.RankSketch`; no other kind
    applies to a bufferless state."""
    from torcheval_tpu.metrics._sketch import RankSketch

    if kind not in ("rank", "exact"):
        raise ValueError(
            f"sketch-mode {type(metric).__name__} supports only "
            f"kind='rank' (its state is already a rank sketch); got {kind!r}"
        )
    if options:
        raise ValueError(
            f"kind='rank' on a sketch-mode metric takes no options; "
            f"got {sorted(options)} (bins are fixed at construction)"
        )
    import numpy as np

    return RankSketch(
        metric_kind=metric_kind,
        edges=np.asarray(metric.threshold),
        num_tp=np.asarray(metric.num_tp),
        num_fp=np.asarray(metric.num_fp),
        num_pos=np.asarray(metric.num_pos),
        num_total=np.asarray(metric.num_total),
        average=getattr(metric, "average", None),
    )


def predicted_epsilon(metric) -> float:
    """Documented rank-error bound for one sketch-mode metric."""
    return rank_error_bound(metric._sketch_bins)
