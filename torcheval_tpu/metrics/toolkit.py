"""Distributed sync toolkit — capability parity with reference
``torcheval/metrics/toolkit.py`` (311 LoC): ``sync_and_compute``,
``get_synced_state_dict``, ``get_synced_metric``, ``clone_metric(s)``,
``reset_metrics``, ``to_device``.

TPU-native design
-----------------
The reference pickles whole Metric objects through ``dist.gather_object`` /
``all_gather_object`` and broadcasts the small compute result for
``recipient_rank="all"`` (reference ``toolkit.py:69-76,247-255``).  Here the
collective layer is :mod:`torcheval_tpu.distributed`: object payloads ride
fixed-shape ``uint8`` array all-gathers over ICI/DCN (XLA collectives), and
under SPMD every rank receives the gathered states, so the ``"all"`` case
needs no second broadcast — each rank merges the identical gathered list and
computes the identical result.  ``recipient_rank=i`` keeps reference parity:
non-recipient ranks still enter the collective but return ``None``.

``recipient_rank=i`` honors the reference's memory rationale with a TRUE
gather (``CollectiveGroup.gather_object``): non-recipient ranks ship their
payload and never materialize their peers' states, so their peak memory
stays O(own state) as the world grows.  ``recipient_rank="all"`` keeps the
SPMD all-gather (every rank needs the merged result anyway), which costs
``world_size × state`` host bytes per rank.  For large buffer-state metrics
prefer the sharded in-jit path (``psum`` of counter states / sharded buffer
compute) over object sync either way.

The single-metric entry points (``sync_and_compute``,
``get_synced_metric``, ``get_synced_state_dict``, ``clone_metric``) also
accept a ``MetricCollection``: the collection implements the whole sync
protocol (``merge_state``, ``_prepare_for_merge_state``, ``state_dict``,
``to``, ``device``), so it gathers and merges as one object and
``sync_and_compute`` returns its result dict on the recipient rank.  The
iterable entry points (``reset_metrics``, ``to_device``,
``clone_metrics``) take iterables *of metrics* — a collection iterates
its member *names*, so call its own ``reset()``/``to()`` instead.
"""

from __future__ import annotations

import logging
import warnings
from copy import deepcopy
from typing import Any, Dict, Iterable, List, Optional, TypeVar, Union

try:
    from typing import Literal
except ImportError:  # pragma: no cover
    from typing_extensions import Literal

from torcheval_tpu.distributed import (
    CollectiveGroup,
    default_group,
)
from torcheval_tpu.metrics.metric import Metric, canonicalize_device

log: logging.Logger = logging.getLogger(__name__)

_TMetrics = TypeVar("_TMetrics", bound=Iterable[Metric])


def sync_and_compute(
    metric: Metric,
    process_group: Optional[CollectiveGroup] = None,
    recipient_rank: Union[int, Literal["all"]] = 0,
    *,
    topology: Literal["flat", "tree", "ring"] = "flat",
    sketch: Optional[str] = None,
    sketch_options: Optional[Dict[str, Any]] = None,
    merge_policy: Optional[Any] = None,
    membership: Optional[Any] = None,
) -> Optional[Any]:
    """Sync metric states and return ``metric.compute()`` of the synced metric
    on the recipient rank; ``None`` on other ranks
    (reference ``toolkit.py:24-78``).

    ``topology`` selects the reduction shape.  ``"flat"`` (default) is
    the reference-parity single gather.  ``"tree"`` / ``"ring"`` run
    the elastic hierarchical merge
    (:func:`torcheval_tpu.parallel.fleet_merge.fleet_merge`): per-level
    retry deadlines, live membership with excision of unresponsive
    hosts, and a :class:`~torcheval_tpu.parallel.fleet_merge
    .MergeOutcome` **return value on every rank** — ``outcome.value``
    holds the computed result on the recipient rank(s) and
    ``outcome.partial`` / ``outcome.world_effective`` label host-loss
    degradation instead of the call raising.  On a clean run the
    tree/ring value is bit-identical to the flat one.  A group without
    point-to-point transport falls back to flat with a warning.

    ``sketch`` (``"reservoir"`` / ``"histogram"`` / ``"count"``, or
    ``"rank"`` for sketch-mode curve metrics whose state already *is* a
    rank sketch) ships O(bins) mergeable summaries instead of raw
    sample buffers — see
    :meth:`BinaryAUROC.sketch_state` for kinds and error bounds; with
    ``topology="flat"`` the sketches ride the ordinary gather and the
    recipient returns the merged sketch's value directly.
    """
    if topology not in ("flat", "tree", "ring"):
        raise ValueError(
            f"topology must be 'flat', 'tree' or 'ring', got {topology!r}"
        )
    group = process_group if process_group is not None else default_group()
    if topology != "flat":
        if group.world_size > 1 and not group.supports_p2p:
            warnings.warn(
                f"collective group {type(group).__name__} has no "
                "point-to-point transport; falling back to topology='flat'",
                RuntimeWarning,
                stacklevel=2,
            )
        else:
            from torcheval_tpu.parallel.fleet_merge import fleet_merge

            dst = 0 if recipient_rank == "all" else recipient_rank
            return fleet_merge(
                metric,
                group,
                topology=topology,
                sketch=sketch,
                sketch_options=sketch_options,
                dst=dst,
                recipient=recipient_rank,
                policy=merge_policy,
                membership=membership,
            )
    if sketch is not None and sketch != "exact":
        return _flat_sketch_compute(
            metric, group, recipient_rank, sketch, sketch_options
        )
    synced_metric = get_synced_metric(metric, process_group, recipient_rank)
    return synced_metric.compute() if synced_metric is not None else None


def _flat_sketch_compute(
    metric: Metric,
    group: CollectiveGroup,
    recipient_rank: Union[int, Literal["all"]],
    kind: str,
    sketch_options: Optional[Dict[str, Any]],
) -> Optional[Any]:
    """Flat-gather variant of the sketch path: every rank builds its
    O(bins) sketch, the sketches ride the ordinary object collective,
    and the recipient merges them in rank order and computes."""
    world_size = group.world_size
    opts = dict(sketch_options or {})
    if kind == "reservoir":
        opts.setdefault("salt", group.rank if world_size > 1 else 0)
    local = metric.sketch_state(kind, **opts)
    if world_size == 1:
        return local.compute()
    if world_size == -1:
        log.warning(
            "collective group reports world size -1 (this process appears "
            "to be outside the group); sync_and_compute() yields None."
        )
        return None
    if recipient_rank == "all":
        gathered = group.all_gather_object(local)
    else:
        gathered = group.gather_object(local, dst=recipient_rank)
    if gathered is None:
        return None
    base = gathered[0]
    for other in gathered[1:]:
        base.merge(other)
    return base.compute()


def get_synced_state_dict(
    metric: Metric,
    process_group: Optional[CollectiveGroup] = None,
    recipient_rank: Union[int, Literal["all"]] = 0,
) -> Dict[str, Any]:
    """State dict of the synced metric on the recipient rank; ``{}`` elsewhere
    (reference ``toolkit.py:81-118``)."""
    synced_metric = get_synced_metric(metric, process_group, recipient_rank)
    return synced_metric.state_dict() if synced_metric is not None else {}


def clone_metric(metric: Metric) -> Metric:
    """A new metric instance cloned from the input (reference
    ``toolkit.py:121-130``).  States are immutable arrays, so the deep copy
    shares device buffers where possible."""
    return deepcopy(metric)


def clone_metrics(metrics: _TMetrics) -> List[Metric]:
    """Clone a collection of metrics (reference ``toolkit.py:133-142``)."""
    return [clone_metric(metric) for metric in metrics]


def get_synced_metric(
    metric: Metric,
    process_group: Optional[CollectiveGroup] = None,
    recipient_rank: Union[int, Literal["all"]] = 0,
) -> Optional[Metric]:
    """Gather every rank's states, merge them into a fresh clone, and return
    it on the recipient rank(s); ``None`` elsewhere
    (reference ``toolkit.py:145-232``)."""
    if not (isinstance(recipient_rank, int) or recipient_rank == "all"):
        raise ValueError(
            "recipient_rank accepts a rank index or the string 'all'; "
            f"got {recipient_rank!r}."
        )

    group = process_group if process_group is not None else default_group()
    world_size = group.world_size
    if (
        isinstance(recipient_rank, int)
        and world_size > 1
        and not 0 <= recipient_rank < world_size
    ):
        raise ValueError(
            f"``recipient_rank`` must be a rank in [0, {world_size}), "
            f"got {recipient_rank}."
        )
    if world_size == 1:
        log.warning(
            "single-process collective group: there are no peer states to "
            "merge, so get_synced_metric() hands back the metric unchanged."
        )
        return metric
    elif world_size == -1:
        log.warning(
            "collective group reports world size -1 (this process appears "
            "to be outside the group); get_synced_metric() yields None."
        )
        return None
    if world_size <= 1:
        raise RuntimeError(
            f"cannot sync metric states over a collective group of "
            f"reported size {world_size}."
        )

    gathered_metric_list = _sync_metric_object(metric, group, recipient_rank)

    if gathered_metric_list is None:
        return None
    return (
        clone_metric(gathered_metric_list[0])
        .to(metric.device)
        .merge_state(gathered_metric_list[1:])
    )


def _sync_metric_object(
    metric: Metric,
    group: CollectiveGroup,
    recipient_rank: Union[int, Literal["all"]],
) -> Optional[List[Metric]]:
    """The process-boundary crossing (reference ``toolkit.py:235-257``):
    pre-canonicalize list states, then move the pickled metrics — a true
    gather to the recipient for an integer ``recipient_rank`` (non-
    recipients never hold peers' states), an all-gather for ``"all"``
    (every rank merges the identical list; no second broadcast needed)."""
    metric._prepare_for_merge_state()
    if recipient_rank == "all":
        return group.all_gather_object(metric)
    return group.gather_object(metric, dst=recipient_rank)


def reset_metrics(metrics: _TMetrics) -> _TMetrics:
    """Reset the input metrics (reference ``toolkit.py:260-283``)."""
    for metric in metrics:
        metric.reset()
    return metrics


def to_device(metrics: _TMetrics, device, *args: Any, **kwargs: Any) -> _TMetrics:
    """Move the input metrics to ``device`` (reference ``toolkit.py:286-311``)."""
    device = canonicalize_device(device)
    for metric in metrics:
        metric.to(device, *args, **kwargs)
    return metrics
