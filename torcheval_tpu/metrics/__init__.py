"""Stateful class metrics (reference ``torcheval/metrics/__init__.py:38-76``
— 30 classes + ``Metric`` + the ``functional`` namespace)."""

from torcheval_tpu.metrics import functional
from torcheval_tpu.metrics.aggregation import Cat, Max, Mean, Min, Sum, Throughput
from torcheval_tpu.metrics.classification import (
    BinaryAccuracy,
    BinaryAUPRC,
    BinaryAUROC,
    BinaryPrecisionRecallCurve,
    MulticlassAUPRC,
    MulticlassAUROC,
    MulticlassPrecisionRecallCurve,
    BinaryBinnedPrecisionRecallCurve,
    BinaryConfusionMatrix,
    BinaryF1Score,
    BinaryNormalizedEntropy,
    BinaryPrecision,
    BinaryRecall,
    MulticlassAccuracy,
    MulticlassBinnedPrecisionRecallCurve,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelAccuracy,
    MultilabelAUPRC,
    MultilabelPrecisionRecallCurve,
    TopKMultilabelAccuracy,
)
from torcheval_tpu.metrics.collection import MetricCollection
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.ranking import HitRate, ReciprocalRank, WeightedCalibration
from torcheval_tpu.metrics.regression import MeanSquaredError, R2Score
from torcheval_tpu.metrics.window import (
    WindowedBinaryAUROC,
    WindowedBinaryNormalizedEntropy,
)

__all__ = [
    "BinaryAccuracy",
    "BinaryAUPRC",
    "BinaryAUROC",
    "BinaryPrecisionRecallCurve",
    "HitRate",
    "MulticlassAUPRC",
    "MulticlassAUROC",
    "MulticlassPrecisionRecallCurve",
    "ReciprocalRank",
    "BinaryBinnedPrecisionRecallCurve",
    "BinaryConfusionMatrix",
    "BinaryF1Score",
    "BinaryNormalizedEntropy",
    "BinaryPrecision",
    "BinaryRecall",
    "Cat",
    "functional",
    "Max",
    "Mean",
    "MeanSquaredError",
    "Metric",
    "MetricCollection",
    "Min",
    "MulticlassAccuracy",
    "MulticlassBinnedPrecisionRecallCurve",
    "MulticlassConfusionMatrix",
    "MulticlassF1Score",
    "MulticlassPrecision",
    "MulticlassRecall",
    "MultilabelAccuracy",
    "MultilabelAUPRC",
    "MultilabelPrecisionRecallCurve",
    "R2Score",
    "Sum",
    "Throughput",
    "TopKMultilabelAccuracy",
    "WeightedCalibration",
    "WindowedBinaryAUROC",
    "WindowedBinaryNormalizedEntropy",
]
