"""Stateful class metrics (reference ``torcheval/metrics/__init__.py:38-76``
— 30 classes + ``Metric`` + the ``functional`` namespace)."""

from torcheval_tpu.metrics import functional
from torcheval_tpu.metrics.classification import (
    BinaryAccuracy,
    MulticlassAccuracy,
    MultilabelAccuracy,
    TopKMultilabelAccuracy,
)
from torcheval_tpu.metrics.metric import Metric

__all__ = [
    "functional",
    "Metric",
    "BinaryAccuracy",
    "MulticlassAccuracy",
    "MultilabelAccuracy",
    "TopKMultilabelAccuracy",
]
