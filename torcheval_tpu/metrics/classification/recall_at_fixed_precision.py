"""Recall-at-fixed-precision class metrics — buffered samples, like the
PR-curve classes they are built on.

Beyond the v0.0.4 snapshot (upstream torcheval added
``BinaryRecallAtFixedPrecision`` / ``MultilabelRecallAtFixedPrecision``
later)."""

from typing import Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import merge_concat_buffers, prepare_concat_buffers
from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_update_input_check,
    _multilabel_precision_recall_curve_update_input_check,
)
from torcheval_tpu.metrics.functional.classification.recall_at_fixed_precision import (
    _binary_recall_at_fixed_precision_compute,
    _multilabel_recall_at_fixed_precision_compute,
    _recall_at_fixed_precision_param_check,
)
from torcheval_tpu.metrics.metric import Metric


class BinaryRecallAtFixedPrecision(Metric[Tuple[jax.Array, jax.Array]]):
    """Best recall (and its threshold) with precision >= ``min_precision``."""

    def __init__(self, *, min_precision: float, device=None) -> None:
        super().__init__(device=device)
        _recall_at_fixed_precision_param_check(min_precision)
        self.min_precision = min_precision
        self._add_state("inputs", [])
        self._add_state("targets", [])

    def update(self, input, target) -> "BinaryRecallAtFixedPrecision":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_precision_recall_curve_update_input_check(input, target)
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(self) -> Tuple[jax.Array, jax.Array]:
        if not self.inputs:
            return (jnp.asarray(0.0), jnp.asarray(1e6))
        return _binary_recall_at_fixed_precision_compute(
            jnp.concatenate(self.inputs),
            jnp.concatenate(self.targets),
            self.min_precision,
        )

    def merge_state(
        self, metrics: Iterable["BinaryRecallAtFixedPrecision"]
    ) -> "BinaryRecallAtFixedPrecision":
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=0)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "inputs", "targets", dim=0)


class MultilabelRecallAtFixedPrecision(
    Metric[Tuple[List[jax.Array], List[jax.Array]]]
):
    """Per-label best recalls (and thresholds) with precision >=
    ``min_precision``."""

    def __init__(
        self,
        *,
        num_labels: Optional[int] = None,
        min_precision: float,
        device=None,
    ) -> None:
        super().__init__(device=device)
        _recall_at_fixed_precision_param_check(min_precision)
        self.num_labels = num_labels
        self.min_precision = min_precision
        self._add_state("inputs", [])
        self._add_state("targets", [])

    def update(self, input, target) -> "MultilabelRecallAtFixedPrecision":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multilabel_precision_recall_curve_update_input_check(
            input, target, self.num_labels
        )
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(self) -> Tuple[List[jax.Array], List[jax.Array]]:
        if not self.inputs:
            return ([], [])
        return _multilabel_recall_at_fixed_precision_compute(
            jnp.concatenate(self.inputs, axis=0),
            jnp.concatenate(self.targets, axis=0),
            self.num_labels,
            self.min_precision,
        )

    def merge_state(
        self, metrics: Iterable["MultilabelRecallAtFixedPrecision"]
    ) -> "MultilabelRecallAtFixedPrecision":
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=0)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "inputs", "targets", dim=0)
