"""Binned AUROC / AUPRC class metrics — fixed-threshold counter states.

Beyond the v0.0.4 snapshot (upstream torcheval added the binned AUC
classes later).  Unlike the exact AUROC/AUPRC classes (unbounded sample
buffers, concat merge), these keep O(rows × thresholds) count states —
add-mergeable, ``psum``-syncable, constant memory over the stream.

Every class shares one state machine (``_BinnedCountsBase``); the
binary/multiclass/multilabel input flavors each specialize it once, and
the AUROC/AUPRC twins differ only in their ``_score_fn``."""

from typing import Iterable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.classification.auroc import (
    _binary_auroc_update_input_check,
)
from torcheval_tpu.metrics.functional.classification.binned_auc import (
    _binned_auc_average_param_check,
    _binned_auprc_from_counts,
    _binned_auroc_from_counts,
    _binned_counts_rows,
    _binned_curves_from_counts,
    _multiclass_binned_auc_validate,
    _multiclass_binned_counts_kernel,
    _multilabel_binned_counts_kernel,
    _select_binned_route,
)
from torcheval_tpu.metrics.functional.classification.binned_precision_recall_curve import (
    _binned_precision_recall_curve_param_check,
    _create_threshold_tensor,
)
from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _multilabel_precision_recall_curve_update_input_check,
)
from torcheval_tpu.metrics.metric import Metric

_COUNTS = ("num_tp", "num_fp", "num_pos", "num_total")


def _binary_binned_counts_kernel(
    input: jax.Array,
    target: jax.Array,
    threshold: jax.Array,
    route: str,
    mask=None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    # Runs inside the fused accumulate trace; ``route`` arrives as a
    # call-time static so the formulation choice (and the kill-switch env
    # var) is re-evaluated per update, not frozen at first compile.
    if input.ndim == 1:
        input, target = input[None], target[None]
    return _binned_counts_rows(input, target == 1, threshold, route=route, mask=mask)


class _BinnedCountsBase(Metric):
    """Shared state machine: ``threshold`` + the four add-mergeable count
    arrays over (rows, thresholds).  ``_score_fn`` (set per concrete
    class) maps the counts to the per-row AUROC/AUPRC scores."""

    # Every concrete update() below takes mask= (and _binned_counts_rows
    # folds it exactly: masked rows contribute zeros), so the binned
    # family is eligible for bucket=/slices= collections.
    _supports_mask = True
    _score_fn = None

    def __init__(self, num_rows: int, threshold, device=None) -> None:
        super().__init__(device=device)
        threshold = _create_threshold_tensor(threshold)
        _binned_precision_recall_curve_param_check(threshold)
        self._add_state("threshold", threshold)
        num_t = threshold.shape[0]
        self._add_state("num_tp", jnp.zeros((num_rows, num_t), jnp.int32))
        self._add_state("num_fp", jnp.zeros((num_rows, num_t), jnp.int32))
        self._add_state("num_pos", jnp.zeros(num_rows, jnp.int32))
        self._add_state("num_total", jnp.zeros(num_rows, jnp.int32))

    def _accumulate(self, kernel, input, target, statics=(), mask=None) -> None:
        # Kernel + all four state adds fused into one dispatch (_fuse.py).
        self.num_tp, self.num_fp, self.num_pos, self.num_total = accumulate(
            kernel,
            (self.num_tp, self.num_fp, self.num_pos, self.num_total),
            input,
            target,
            self.threshold,
            statics=statics,
            mask=mask,
        )

    def _row_scores(self) -> jax.Array:
        return type(self)._score_fn(
            self.num_tp, self.num_fp, self.num_pos, self.num_total
        )

    def merge_state(self, metrics: Iterable["_BinnedCountsBase"]):
        merge_add(self, metrics, *_COUNTS)
        return self


class _BinaryBinnedAUC(_BinnedCountsBase):
    """Binary flavor: rows = tasks; compute returns ``(score, thresholds)``
    with the scalar squeezed for ``num_tasks == 1``."""

    def __init__(self, num_tasks: int, threshold, device=None) -> None:
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        self.num_tasks = num_tasks
        super().__init__(num_tasks, threshold, device)

    def update(self, input, target, *, mask=None):
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_auroc_update_input_check(input, target, self.num_tasks)
        route = _select_binned_route(
            self.num_tasks, input.shape[-1], self.threshold
        )
        self._accumulate(
            _binary_binned_counts_kernel, input, target, statics=(route,),
            mask=mask,
        )
        return self

    def compute(self) -> Tuple[jax.Array, jax.Array]:
        score = self._row_scores()
        return (score[0] if self.num_tasks == 1 else score), self.threshold


class _MulticlassBinnedAUC(_BinnedCountsBase):
    """Multiclass flavor: rows = one-vs-rest classes, macro/None average."""

    def __init__(
        self, num_classes: int, average: Optional[str], threshold, device=None
    ) -> None:
        _binned_auc_average_param_check(num_classes, average, "num_classes")
        self.num_classes = num_classes
        self.average = average
        super().__init__(num_classes, threshold, device)

    def update(self, input, target, *, mask=None):
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multiclass_binned_auc_validate(input, target, self.num_classes)
        route = _select_binned_route(
            self.num_classes, input.shape[0], self.threshold
        )
        self._accumulate(
            _multiclass_binned_counts_kernel, input, target,
            statics=(self.num_classes, route),
            mask=mask,
        )
        return self

    def compute(self) -> Tuple[jax.Array, jax.Array]:
        score = self._row_scores()
        return (score.mean() if self.average == "macro" else score), self.threshold


class _MultilabelBinned(_BinnedCountsBase):
    """Multilabel flavor: rows = label columns of a 0/1 target matrix."""

    def __init__(self, num_labels: int, threshold, device=None) -> None:
        if num_labels < 2:
            raise ValueError("`num_labels` has to be at least 2.")
        self.num_labels = num_labels
        super().__init__(num_labels, threshold, device)

    def update(self, input, target, *, mask=None):
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multilabel_precision_recall_curve_update_input_check(
            input, target, self.num_labels
        )
        route = _select_binned_route(
            self.num_labels, input.shape[0], self.threshold
        )
        self._accumulate(
            _multilabel_binned_counts_kernel, input, target, statics=(route,),
            mask=mask,
        )
        return self


class BinaryBinnedAUROC(_BinaryBinnedAUC):
    """Binned AUROC with multi-task support; compute returns
    ``(auroc, thresholds)``."""

    _score_fn = staticmethod(_binned_auroc_from_counts)

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        threshold: Union[int, List[float], "jax.Array"] = 200,
        device=None,
    ) -> None:
        super().__init__(num_tasks, threshold, device)


class BinaryBinnedAUPRC(_BinaryBinnedAUC):
    """Binned average precision with multi-task support; compute returns
    ``(auprc, thresholds)``."""

    _score_fn = staticmethod(_binned_auprc_from_counts)

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        threshold: Union[int, List[float], "jax.Array"] = 100,
        device=None,
    ) -> None:
        super().__init__(num_tasks, threshold, device)


class MulticlassBinnedAUROC(_MulticlassBinnedAUC):
    """One-vs-rest binned AUROC with macro/None averaging."""

    _score_fn = staticmethod(_binned_auroc_from_counts)

    def __init__(
        self,
        *,
        num_classes: int,
        average: Optional[str] = "macro",
        threshold: Union[int, List[float], "jax.Array"] = 200,
        device=None,
    ) -> None:
        super().__init__(num_classes, average, threshold, device)


class MulticlassBinnedAUPRC(_MulticlassBinnedAUC):
    """One-vs-rest binned average precision with macro/None averaging."""

    _score_fn = staticmethod(_binned_auprc_from_counts)

    def __init__(
        self,
        *,
        num_classes: int,
        average: Optional[str] = "macro",
        threshold: Union[int, List[float], "jax.Array"] = 100,
        device=None,
    ) -> None:
        super().__init__(num_classes, average, threshold, device)


class MultilabelBinnedAUPRC(_MultilabelBinned):
    """Per-label binned average precision with macro/None averaging."""

    _score_fn = staticmethod(_binned_auprc_from_counts)

    def __init__(
        self,
        *,
        num_labels: int,
        average: Optional[str] = "macro",
        threshold: Union[int, List[float], "jax.Array"] = 100,
        device=None,
    ) -> None:
        # num_labels itself is validated once, by _MultilabelBinned below.
        _binned_auc_average_param_check(None, average, "num_labels")
        self.average = average
        super().__init__(num_labels, threshold, device)

    def compute(self) -> Tuple[jax.Array, jax.Array]:
        score = self._row_scores()
        return (score.mean() if self.average == "macro" else score), self.threshold


class MultilabelBinnedPrecisionRecallCurve(_MultilabelBinned):
    """Per-label binned PR curves; compute returns
    ``(precisions, recalls, thresholds)`` with per-label lists."""

    def __init__(
        self,
        *,
        num_labels: int,
        threshold: Union[int, List[float], "jax.Array"] = 100,
        device=None,
    ) -> None:
        super().__init__(num_labels, threshold, device)

    def compute(self) -> Tuple[List[jax.Array], List[jax.Array], jax.Array]:
        return _binned_curves_from_counts(
            self.num_tp, self.num_fp, self.num_pos, self.threshold
        )
