"""PR-curve metrics — parity with reference
``torcheval/metrics/classification/precision_recall_curve.py`` (221 LoC).

Sample-buffer states; all curve math happens at compute
(reference ``precision_recall_curve.py:27-221``)."""

from typing import Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import merge_concat_buffers, prepare_concat_buffers
from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_update_input_check,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_update_input_check,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_update_input_check,
)
from torcheval_tpu.metrics.metric import Metric


class BinaryPrecisionRecallCurve(Metric[Tuple[jax.Array, jax.Array, jax.Array]]):
    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("inputs", [])
        self._add_state("targets", [])

    def update(self, input, target) -> "BinaryPrecisionRecallCurve":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_precision_recall_curve_update_input_check(input, target)
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(self) -> Tuple[jax.Array, jax.Array, jax.Array]:
        if not self.inputs:
            return (jnp.zeros(0), jnp.zeros(0), jnp.zeros(0))
        return _binary_precision_recall_curve_compute(
            jnp.concatenate(self.inputs), jnp.concatenate(self.targets)
        )

    def merge_state(
        self, metrics: Iterable["BinaryPrecisionRecallCurve"]
    ) -> "BinaryPrecisionRecallCurve":
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=0)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "inputs", "targets", dim=0)


class MulticlassPrecisionRecallCurve(
    Metric[Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]]
):
    def __init__(self, *, num_classes: Optional[int] = None, device=None) -> None:
        super().__init__(device=device)
        self.num_classes = num_classes
        self._add_state("inputs", [])
        self._add_state("targets", [])

    def update(self, input, target) -> "MulticlassPrecisionRecallCurve":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multiclass_precision_recall_curve_update_input_check(
            input, target, self.num_classes
        )
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(
        self,
    ) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
        if not self.inputs:
            return ([], [], [])
        return _multiclass_precision_recall_curve_compute(
            jnp.concatenate(self.inputs, axis=0),
            jnp.concatenate(self.targets, axis=0),
            self.num_classes,
        )

    def merge_state(
        self, metrics: Iterable["MulticlassPrecisionRecallCurve"]
    ) -> "MulticlassPrecisionRecallCurve":
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=0)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "inputs", "targets", dim=0)


class MultilabelPrecisionRecallCurve(
    Metric[Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]]
):
    """Per-label PR curves over a 0/1 label matrix.  Beyond the v0.0.4
    snapshot (upstream torcheval added ``MultilabelPrecisionRecallCurve``
    later)."""

    def __init__(self, *, num_labels: Optional[int] = None, device=None) -> None:
        super().__init__(device=device)
        self.num_labels = num_labels
        self._add_state("inputs", [])
        self._add_state("targets", [])

    def update(self, input, target) -> "MultilabelPrecisionRecallCurve":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multilabel_precision_recall_curve_update_input_check(
            input, target, self.num_labels
        )
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(
        self,
    ) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
        if not self.inputs:
            return ([], [], [])
        return _multilabel_precision_recall_curve_compute(
            jnp.concatenate(self.inputs, axis=0),
            jnp.concatenate(self.targets, axis=0),
            self.num_labels,
        )

    def merge_state(
        self, metrics: Iterable["MultilabelPrecisionRecallCurve"]
    ) -> "MultilabelPrecisionRecallCurve":
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=0)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "inputs", "targets", dim=0)
