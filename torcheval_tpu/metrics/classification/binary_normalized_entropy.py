"""BinaryNormalizedEntropy metric — parity with reference
``torcheval/metrics/classification/binary_normalized_entropy.py`` (147 LoC).

States: per-task ``total_entropy`` / ``num_examples`` / ``num_positive``
(reference ``:76-87``, float64 there — see the dtype note in the functional
module); merge: add (reference ``:134``)."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.classification.binary_normalized_entropy import (
    _accum_dtype,
    _baseline_update,
    _ne_input_check,
    _ne_update_kernel,
    _ne_update_kernel_unweighted,
)
from torcheval_tpu.metrics.metric import Metric

_STATES = ("total_entropy", "num_examples", "num_positive")


class BinaryNormalizedEntropy(Metric[jax.Array]):
    def __init__(
        self,
        *,
        from_logits: bool = False,
        num_tasks: int = 1,
        device=None,
    ) -> None:
        super().__init__(device=device)
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        self.from_logits = from_logits
        self.num_tasks = num_tasks
        for name in _STATES:
            self._add_state(name, jnp.zeros(num_tasks, dtype=_accum_dtype()))

    def update(self, input, target, *, weight=None) -> "BinaryNormalizedEntropy":
        input, target = jnp.asarray(input), jnp.asarray(target)
        if weight is not None:
            weight = jnp.asarray(weight)
        _ne_input_check(input, target, self.from_logits, self.num_tasks, weight)
        # Kernel + all three state adds fused into one dispatch (_fuse.py);
        # state order follows the kernel's (entropy, positive, examples).
        if weight is None:
            kernel, args = _ne_update_kernel_unweighted, (input, target)
        else:
            kernel, args = _ne_update_kernel, (input, target, weight)
        self.total_entropy, self.num_positive, self.num_examples = accumulate(
            kernel,
            (self.total_entropy, self.num_positive, self.num_examples),
            *args,
            statics=(self.from_logits,),
        )
        return self

    def compute(self) -> jax.Array:
        """Per-task NE, or an empty array when any task saw no examples
        (reference ``binary_normalized_entropy.py:~115-130``)."""
        if bool(jnp.any(self.num_examples == 0.0)):
            return jnp.zeros(0)
        baseline_entropy = _baseline_update(self.num_positive, self.num_examples)
        cross_entropy = self.total_entropy / self.num_examples
        return cross_entropy / baseline_entropy

    def merge_state(self, metrics: Iterable["BinaryNormalizedEntropy"]):
        merge_add(self, metrics, *_STATES)
        return self
