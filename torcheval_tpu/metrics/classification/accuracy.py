"""Accuracy family — stateful class metrics.

Capability parity with reference ``torcheval/metrics/classification/accuracy.py``
(394 LoC): ``MulticlassAccuracy`` plus subclasses ``BinaryAccuracy``,
``MultilabelAccuracy``, ``TopKMultilabelAccuracy``.  Counter states
(``num_correct`` / ``num_total``) merge by addition, so distributed sync is a
single fused ``psum`` over the mesh axis.
"""

from typing import Iterable, Optional, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics.functional.classification.accuracy import (
    _accuracy_compute,
    _accuracy_param_check,
    _binary_accuracy_update_input_check,
    _binary_accuracy_update_kernel,
    _multiclass_accuracy_update_kernel,
    _multiclass_accuracy_validate,
    _multilabel_accuracy_param_check,
    _multilabel_accuracy_update_input_check,
    _multilabel_accuracy_update_kernel,
    _topk_multilabel_accuracy_param_check,
    _topk_multilabel_accuracy_update_input_check,
    _topk_multilabel_accuracy_update_kernel,
)
from torcheval_tpu.metrics.metric import Metric

TAccuracy = TypeVar("TAccuracy")


class MulticlassAccuracy(Metric[jax.Array]):
    """Multiclass accuracy (reference ``classification/accuracy.py:32-160``).

    States: micro → scalar ``num_correct``/``num_total``; macro/None →
    per-class vectors (reference ``classification/accuracy.py:96-108``).
    Merge: elementwise add.
    """

    # Accepts update(..., mask=) for bucketed ragged batches (_bucket.py).
    _supports_mask = True

    def __init__(
        self,
        *,
        average: Optional[str] = "micro",
        num_classes: Optional[int] = None,
        k: int = 1,
        device=None,
    ) -> None:
        super().__init__(device=device)
        _accuracy_param_check(average, num_classes, k)
        self.average = average
        self.num_classes = num_classes
        self.k = k
        if average == "micro":
            self._add_state("num_correct", jnp.asarray(0.0))
            self._add_state("num_total", jnp.asarray(0.0))
        else:
            self._add_state("num_correct", jnp.zeros(num_classes or 0))
            self._add_state("num_total", jnp.zeros(num_classes or 0))

    def update(self, input, target, *, mask=None):
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multiclass_accuracy_validate(
            input, target, self.average, self.num_classes, self.k
        )
        # Kernel + both state adds fused into one dispatch (_fuse.py).
        self.num_correct, self.num_total = accumulate(
            _multiclass_accuracy_update_kernel,
            (self.num_correct, self.num_total),
            input,
            target,
            statics=(self.average, self.num_classes, self.k),
            mask=mask,
        )
        return self

    def compute(self) -> jax.Array:
        """Return the accuracy; 0/0 yields NaN before any update
        (reference behavior)."""
        return _accuracy_compute(self.num_correct, self.num_total, self.average)

    def merge_state(self, metrics: Iterable["MulticlassAccuracy"]):
        for metric in metrics:
            self.num_correct = self.num_correct + jax.device_put(
                metric.num_correct, self.device
            )
            self.num_total = self.num_total + jax.device_put(
                metric.num_total, self.device
            )
        return self


class BinaryAccuracy(MulticlassAccuracy):
    """Binary accuracy over thresholded predictions
    (reference ``classification/accuracy.py:~220``)."""

    def __init__(
        self,
        *,
        threshold: float = 0.5,
        device=None,
    ) -> None:
        super().__init__(device=device)
        self.threshold = threshold

    def update(self, input, target, *, mask=None):
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_accuracy_update_input_check(input, target)
        self.num_correct, self.num_total = accumulate(
            _binary_accuracy_update_kernel,
            (self.num_correct, self.num_total),
            input,
            target,
            statics=(self.threshold,),
            mask=mask,
        )
        return self


class MultilabelAccuracy(MulticlassAccuracy):
    """Multilabel accuracy under exact_match/hamming/overlap/contain/belong
    criteria (reference ``classification/accuracy.py``)."""

    def __init__(
        self,
        *,
        threshold: float = 0.5,
        criteria: str = "exact_match",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _multilabel_accuracy_param_check(criteria)
        self.threshold = threshold
        self.criteria = criteria

    def update(self, input, target, *, mask=None):
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multilabel_accuracy_update_input_check(input, target)
        self.num_correct, self.num_total = accumulate(
            _multilabel_accuracy_update_kernel,
            (self.num_correct, self.num_total),
            input,
            target,
            statics=(self.threshold, self.criteria),
            mask=mask,
        )
        return self


class TopKMultilabelAccuracy(MulticlassAccuracy):
    """Top-k multilabel accuracy (reference ``classification/accuracy.py``).

    Divergence from reference (documented): honors ``k`` instead of the
    reference's hardcoded ``topk(k=2)`` (reference functional
    ``accuracy.py:393-395``).
    """

    def __init__(
        self,
        *,
        criteria: str = "exact_match",
        k: int = 2,
        device=None,
    ) -> None:
        super().__init__(device=device)
        _topk_multilabel_accuracy_param_check(criteria, k)
        self.criteria = criteria
        self.k = k

    def update(self, input, target, *, mask=None):
        input, target = jnp.asarray(input), jnp.asarray(target)
        _topk_multilabel_accuracy_update_input_check(input, target, self.k)
        self.num_correct, self.num_total = accumulate(
            _topk_multilabel_accuracy_update_kernel,
            (self.num_correct, self.num_total),
            input,
            target,
            statics=(self.criteria, self.k),
            mask=mask,
        )
        return self
