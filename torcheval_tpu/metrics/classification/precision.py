"""Precision metrics — parity with reference
``torcheval/metrics/classification/precision.py`` (214 LoC)."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
    _counts_route,
)
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.classification.precision import (
    _binary_precision_update_input_check,
    _binary_precision_update_kernel,
    _precision_compute,
    _precision_param_check,
    _precision_update_kernel,
    _precision_validate,
)
from torcheval_tpu.metrics.metric import Metric

_STATES = ("num_tp", "num_fp", "num_label")


class MulticlassPrecision(Metric[jax.Array]):
    """States: ``num_tp`` / ``num_fp`` / ``num_label`` — scalars for micro,
    per-class vectors otherwise (reference ``precision.py:89-110``); merge:
    add (reference ``:147``)."""

    # Accepts update(..., mask=) for bucketed ragged batches (_bucket.py).
    _supports_mask = True

    def __init__(
        self,
        *,
        num_classes: Optional[int] = None,
        average: Optional[str] = "micro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _precision_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average
        if average == "micro":
            for name in _STATES:
                self._add_state(name, jnp.asarray(0.0))
        else:
            for name in _STATES:
                self._add_state(name, jnp.zeros(num_classes))

    def update(self, input, target, *, mask=None) -> "MulticlassPrecision":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _precision_validate(input, target, self.num_classes, self.average)
        # Kernel + all three state adds fused into one dispatch (_fuse.py).
        self.num_tp, self.num_fp, self.num_label = accumulate(
            _precision_update_kernel,
            (self.num_tp, self.num_fp, self.num_label),
            input,
            target,
            statics=(
                self.num_classes,
                self.average,
                _counts_route(input, self.num_classes, self.average),
            ),
            mask=mask,
        )
        return self

    def compute(self) -> jax.Array:
        return _precision_compute(
            self.num_tp, self.num_fp, self.num_label, self.average
        )

    def merge_state(self, metrics: Iterable["MulticlassPrecision"]):
        merge_add(self, metrics, *_STATES)
        return self


class BinaryPrecision(MulticlassPrecision):
    """Binary precision over thresholded predictions
    (reference ``precision.py:155-214``)."""

    def __init__(self, *, threshold: float = 0.5, device=None) -> None:
        super().__init__(num_classes=2, device=device)
        self.threshold = threshold

    def update(self, input, target, *, mask=None) -> "BinaryPrecision":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_precision_update_input_check(input, target)
        self.num_tp, self.num_fp, self.num_label = accumulate(
            _binary_precision_update_kernel,
            (self.num_tp, self.num_fp, self.num_label),
            input,
            target,
            statics=(self.threshold,),
            mask=mask,
        )
        return self
