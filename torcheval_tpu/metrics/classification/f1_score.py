"""F1 metrics — parity with reference
``torcheval/metrics/classification/f1_score.py`` (218 LoC)."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
    _counts_route,
)
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.classification.f1_score import (
    _binary_f1_score_update_input_check,
    _binary_f1_score_update_kernel,
    _f1_score_compute,
    _f1_score_param_check,
    _f1_score_update_kernel,
    _f1_score_validate,
)
from torcheval_tpu.metrics.metric import Metric

_STATES = ("num_tp", "num_label", "num_prediction")


class MulticlassF1Score(Metric[jax.Array]):
    """States: ``num_tp`` / ``num_label`` / ``num_prediction`` — scalars for
    micro, per-class vectors otherwise (reference ``f1_score.py:91-114``);
    merge: add (reference ``:149``)."""

    # Accepts update(..., mask=) for bucketed ragged batches (_bucket.py).
    _supports_mask = True

    def __init__(
        self,
        *,
        num_classes: Optional[int] = None,
        average: Optional[str] = "micro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _f1_score_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average
        if average == "micro":
            for name in _STATES:
                self._add_state(name, jnp.asarray(0.0))
        else:
            for name in _STATES:
                self._add_state(name, jnp.zeros(num_classes))

    def update(self, input, target, *, mask=None) -> "MulticlassF1Score":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _f1_score_validate(input, target, self.num_classes, self.average)
        # Kernel + all three state adds fused into one dispatch (_fuse.py).
        self.num_tp, self.num_label, self.num_prediction = accumulate(
            _f1_score_update_kernel,
            (self.num_tp, self.num_label, self.num_prediction),
            input,
            target,
            statics=(
                self.num_classes,
                self.average,
                _counts_route(input, self.num_classes, self.average),
            ),
            mask=mask,
        )
        return self

    def compute(self) -> jax.Array:
        return _f1_score_compute(
            self.num_tp, self.num_label, self.num_prediction, self.average
        )

    def merge_state(self, metrics: Iterable["MulticlassF1Score"]):
        merge_add(self, metrics, *_STATES)
        return self


class BinaryF1Score(MulticlassF1Score):
    """Binary F1 over thresholded predictions
    (reference ``f1_score.py:157-218``)."""

    def __init__(self, *, threshold: float = 0.5, device=None) -> None:
        super().__init__(average="micro", device=device)
        self.threshold = threshold

    def update(self, input, target, *, mask=None) -> "BinaryF1Score":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_f1_score_update_input_check(input, target)
        self.num_tp, self.num_label, self.num_prediction = accumulate(
            _binary_f1_score_update_kernel,
            (self.num_tp, self.num_label, self.num_prediction),
            input,
            target,
            statics=(self.threshold,),
            mask=mask,
        )
        return self
