"""Binned PR-curve metrics — parity with reference
``torcheval/metrics/classification/binned_precision_recall_curve.py``
(247 LoC).  Fixed-threshold per-bin counters: fully fixed-shape state,
mergeable by addition (→ ``psum`` on a mesh) — the TPU-preferred PR-curve
formulation versus unbounded sample buffers."""

from typing import Iterable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.classification.binned_auc import (
    _select_binned_route,
)
from torcheval_tpu.metrics.functional.classification.binned_precision_recall_curve import (
    _binary_binned_precision_recall_curve_compute,
    _binary_binned_update_input_check,
    _binary_binned_update_kernel,
    _binned_precision_recall_curve_param_check,
    _create_threshold_tensor,
    _multiclass_binned_precision_recall_curve_compute,
    _multiclass_binned_update_kernel,
    _multiclass_binned_validate,
)
from torcheval_tpu.metrics.metric import Metric

_COUNTS = ("num_tp", "num_fp", "num_fn")


class BinaryBinnedPrecisionRecallCurve(
    Metric[Tuple[jax.Array, jax.Array, jax.Array]]
):
    """States: ``threshold`` + per-bin ``num_tp``/``num_fp``/``num_fn``
    vectors (reference ``binned_precision_recall_curve.py:64-80``); merge:
    add counts (reference ``:121-133``)."""

    def __init__(
        self,
        *,
        threshold: Union[int, List[float], "jax.Array"] = 100,
        device=None,
    ) -> None:
        super().__init__(device=device)
        threshold = _create_threshold_tensor(threshold)
        _binned_precision_recall_curve_param_check(threshold)
        self._add_state("threshold", threshold)
        n = threshold.shape[0]
        for name in _COUNTS:
            self._add_state(name, jnp.zeros(n))

    def update(self, input, target) -> "BinaryBinnedPrecisionRecallCurve":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_binned_update_input_check(input, target)
        # Kernel + all three state adds fused into one dispatch (_fuse.py).
        route = _select_binned_route(1, input.shape[0], self.threshold)
        self.num_tp, self.num_fp, self.num_fn = accumulate(
            _binary_binned_update_kernel,
            (self.num_tp, self.num_fp, self.num_fn),
            input,
            target,
            self.threshold,
            statics=(route,),
        )
        return self

    def compute(self) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """(precision, recall, thresholds) — precision/recall carry the extra
        (1.0, 0.0) sentinel point."""
        return _binary_binned_precision_recall_curve_compute(
            self.num_tp, self.num_fp, self.num_fn, self.threshold
        )

    def merge_state(self, metrics: Iterable["BinaryBinnedPrecisionRecallCurve"]):
        merge_add(self, metrics, *_COUNTS)
        return self


class MulticlassBinnedPrecisionRecallCurve(
    Metric[Tuple[List[jax.Array], List[jax.Array], jax.Array]]
):
    """States: ``threshold`` + ``(n_thresholds, n_classes)`` count matrices
    (reference ``binned_precision_recall_curve.py:167-194``); merge: add."""

    def __init__(
        self,
        *,
        num_classes: int,
        threshold: Union[int, List[float], "jax.Array"] = 100,
        device=None,
    ) -> None:
        super().__init__(device=device)
        threshold = _create_threshold_tensor(threshold)
        _binned_precision_recall_curve_param_check(threshold)
        if not isinstance(num_classes, int) or num_classes < 2:
            raise ValueError(
                f"`num_classes` has to be at least 2, got {num_classes}."
            )
        self.num_classes = num_classes
        self._add_state("threshold", threshold)
        n = threshold.shape[0]
        for name in _COUNTS:
            self._add_state(name, jnp.zeros((n, num_classes)))

    def update(self, input, target) -> "MulticlassBinnedPrecisionRecallCurve":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multiclass_binned_validate(input, target, self.num_classes)
        route = _select_binned_route(
            self.num_classes, input.shape[0], self.threshold
        )
        self.num_tp, self.num_fp, self.num_fn = accumulate(
            _multiclass_binned_update_kernel,
            (self.num_tp, self.num_fp, self.num_fn),
            input,
            target,
            self.threshold,
            statics=(self.num_classes, route),
        )
        return self

    def compute(self) -> Tuple[List[jax.Array], List[jax.Array], jax.Array]:
        return _multiclass_binned_precision_recall_curve_compute(
            self.num_tp, self.num_fp, self.num_fn, self.num_classes, self.threshold
        )

    def merge_state(self, metrics: Iterable["MulticlassBinnedPrecisionRecallCurve"]):
        merge_add(self, metrics, *_COUNTS)
        return self
