from torcheval_tpu.metrics.classification.auprc import (
    BinaryAUPRC,
    MulticlassAUPRC,
    MultilabelAUPRC,
)
from torcheval_tpu.metrics.classification.auroc import (
    BinaryAUROC,
    MulticlassAUROC,
)
from torcheval_tpu.metrics.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torcheval_tpu.metrics.classification.accuracy import (
    BinaryAccuracy,
    MulticlassAccuracy,
    MultilabelAccuracy,
    TopKMultilabelAccuracy,
)
from torcheval_tpu.metrics.classification.binary_normalized_entropy import (
    BinaryNormalizedEntropy,
)
from torcheval_tpu.metrics.classification.binned_auc import (
    BinaryBinnedAUPRC,
    BinaryBinnedAUROC,
    MulticlassBinnedAUPRC,
    MulticlassBinnedAUROC,
    MultilabelBinnedAUPRC,
    MultilabelBinnedPrecisionRecallCurve,
)
from torcheval_tpu.metrics.classification.binned_precision_recall_curve import (
    BinaryBinnedPrecisionRecallCurve,
    MulticlassBinnedPrecisionRecallCurve,
)
from torcheval_tpu.metrics.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    MulticlassConfusionMatrix,
)
from torcheval_tpu.metrics.classification.f1_score import (
    BinaryF1Score,
    MulticlassF1Score,
)
from torcheval_tpu.metrics.classification.precision import (
    BinaryPrecision,
    MulticlassPrecision,
)
from torcheval_tpu.metrics.classification.recall import (
    BinaryRecall,
    MulticlassRecall,
)
from torcheval_tpu.metrics.classification.recall_at_fixed_precision import (
    BinaryRecallAtFixedPrecision,
    MultilabelRecallAtFixedPrecision,
)

__all__ = [
    "BinaryAccuracy",
    "BinaryAUPRC",
    "BinaryAUROC",
    "BinaryBinnedAUPRC",
    "BinaryBinnedAUROC",
    "BinaryBinnedPrecisionRecallCurve",
    "BinaryConfusionMatrix",
    "BinaryF1Score",
    "BinaryNormalizedEntropy",
    "BinaryPrecision",
    "BinaryPrecisionRecallCurve",
    "BinaryRecall",
    "BinaryRecallAtFixedPrecision",
    "MulticlassAccuracy",
    "MulticlassAUPRC",
    "MulticlassAUROC",
    "MulticlassBinnedAUPRC",
    "MulticlassBinnedAUROC",
    "MulticlassBinnedPrecisionRecallCurve",
    "MulticlassConfusionMatrix",
    "MulticlassF1Score",
    "MulticlassPrecision",
    "MulticlassPrecisionRecallCurve",
    "MulticlassRecall",
    "MultilabelAccuracy",
    "MultilabelAUPRC",
    "MultilabelBinnedAUPRC",
    "MultilabelBinnedPrecisionRecallCurve",
    "MultilabelPrecisionRecallCurve",
    "MultilabelRecallAtFixedPrecision",
    "TopKMultilabelAccuracy",
]
