from torcheval_tpu.metrics.classification.accuracy import (
    BinaryAccuracy,
    MulticlassAccuracy,
    MultilabelAccuracy,
    TopKMultilabelAccuracy,
)

__all__ = [
    "BinaryAccuracy",
    "MulticlassAccuracy",
    "MultilabelAccuracy",
    "TopKMultilabelAccuracy",
]
