"""Confusion-matrix metrics — parity with reference
``torcheval/metrics/classification/confusion_matrix.py`` (306 LoC)."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_update_kernel,
    _binary_confusion_matrix_validate,
    _confusion_matrix_compute,
    _confusion_matrix_param_check,
    _confusion_matrix_update_input_check,
    _confusion_matrix_update_kernel,
    _cm_route,
    _use_matmul_cm,
)
from torcheval_tpu.metrics.metric import Metric


class MulticlassConfusionMatrix(Metric[jax.Array]):
    """State: ``confusion_matrix`` (C, C) scatter-add counter
    (reference ``confusion_matrix.py:30-210``); merge: add (reference
    ``:203-209``).  Entry (i, j) counts true class i predicted as j."""

    # Accepts update(..., mask=) for bucketed ragged batches (_bucket.py).
    _supports_mask = True

    def __init__(
        self,
        num_classes: int,
        *,
        normalize: Optional[str] = None,
        device=None,
    ) -> None:
        super().__init__(device=device)
        _confusion_matrix_param_check(num_classes, normalize)
        self.num_classes = num_classes
        self.normalize = normalize
        self._add_state(
            "confusion_matrix", jnp.zeros((num_classes, num_classes), jnp.int32)
        )

    def update(self, input, target, *, mask=None) -> "MulticlassConfusionMatrix":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _confusion_matrix_update_input_check(input, target, self.num_classes)
        # Scatter kernel + state add fused into one dispatch (_fuse.py).
        # Route selection stays outside jit (honors the pallas kill-switch
        # at call time — _select_binned_route pattern).
        (self.confusion_matrix,) = accumulate(
            _confusion_matrix_update_kernel,
            (self.confusion_matrix,),
            input,
            target,
            statics=(
                self.num_classes,
                _cm_route(self.num_classes, input.shape[0]),
            ),
            mask=mask,
        )
        return self

    def compute(self) -> jax.Array:
        return _confusion_matrix_compute(self.confusion_matrix, self.normalize)

    def normalized(self, normalize: Optional[str] = None) -> jax.Array:
        """The confusion matrix under a different normalization without
        mutating state (reference ``confusion_matrix.py:183-201``)."""
        _confusion_matrix_param_check(self.num_classes, normalize)
        return _confusion_matrix_compute(self.confusion_matrix, normalize)

    def merge_state(self, metrics: Iterable["MulticlassConfusionMatrix"]):
        merge_add(self, metrics, "confusion_matrix")
        return self


class BinaryConfusionMatrix(MulticlassConfusionMatrix):
    """2×2 confusion matrix of thresholded predictions
    (reference ``confusion_matrix.py:212-306``)."""

    def __init__(
        self,
        *,
        threshold: float = 0.5,
        normalize: Optional[str] = None,
        device=None,
    ) -> None:
        super().__init__(num_classes=2, normalize=normalize, device=device)
        self.threshold = threshold

    def update(self, input, target, *, mask=None) -> "BinaryConfusionMatrix":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_confusion_matrix_validate(input, target)
        (self.confusion_matrix,) = accumulate(
            _binary_confusion_matrix_update_kernel,
            (self.confusion_matrix,),
            input,
            target,
            statics=(self.threshold, _use_matmul_cm(2, input.shape[0])),
            mask=mask,
        )
        return self
