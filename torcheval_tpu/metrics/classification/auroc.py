"""AUROC metrics — parity with reference
``torcheval/metrics/classification/auroc.py`` (229 LoC).

Sample-buffer states (``inputs``/``targets`` lists); merge concatenates;
``_prepare_for_merge_state`` pre-concats to one array per state for the
sync wire (reference ``auroc.py:89-92,130-134``).

Beyond the reference: ``sketch=True`` (or ``TORCHEVAL_TPU_RANK_SKETCH``)
swaps the unbounded buffers for the fixed-size mergeable rank sketch
(:mod:`torcheval_tpu.metrics._rank_state`): single-pass sort-free
updates, O(bins) merge payloads, AUROC within ε = 1/(bins-1)."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import merge_concat_buffers, prepare_concat_buffers
from torcheval_tpu.metrics._rank_state import (
    _rank_binary_kernel,
    _rank_multiclass_kernel,
    install_rank_states,
    rank_accumulate,
    rank_merge_state,
    rank_route,
    rank_sketch_state,
)
from torcheval_tpu.metrics.functional.classification.auroc import (
    _binary_auroc_compute,
    _binary_auroc_update_input_check,
    _multiclass_auroc_compute,
    _multiclass_auroc_param_check,
    _multiclass_auroc_update_input_check,
)
from torcheval_tpu.metrics.functional.classification.binned_auc import (
    _binned_auroc_from_counts,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.ops._flags import rank_sketch_enabled
from torcheval_tpu.ops.fused_auc import has_fused


class BinaryAUROC(Metric[jax.Array]):
    """Binary AUROC with multi-task support and the ``use_fused``
    approximate-kernel opt-in (the reference's ``use_fbgemm`` analog,
    reference ``auroc.py:27-48``).

    ``sketch=True`` (default: ``TORCHEVAL_TPU_RANK_SKETCH``, else off)
    replaces the exact sample buffers with the mergeable rank-sketch
    counts — see :doc:`/sketch` for the state layout and error bounds."""

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        device=None,
        use_fused: Optional[bool] = False,
        sketch: Optional[bool] = None,
        sketch_bins: Optional[int] = None,
    ) -> None:
        super().__init__(device=device)
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        self._sketch_mode = rank_sketch_enabled() if sketch is None else bool(sketch)
        if self._sketch_mode and use_fused:
            raise ValueError(
                "`use_fused` applies to the exact buffered compute; it "
                "cannot be combined with the rank-sketch state "
                "(sketch=True)."
            )
        if use_fused and not has_fused():
            raise ValueError(
                "`use_fused` requires the fused AUC kernel to be available."
            )
        self.num_tasks = num_tasks
        self.use_fused = use_fused
        if self._sketch_mode:
            install_rank_states(self, num_tasks, sketch_bins)
        else:
            self._add_state("inputs", [])
            self._add_state("targets", [])

    def update(self, input, target, *, mask=None) -> "BinaryAUROC":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_auroc_update_input_check(input, target, self.num_tasks)
        if self._sketch_mode:
            route = rank_route(self, input.shape[-1])
            rank_accumulate(
                self, _rank_binary_kernel, input, target, statics=(route,),
                mask=mask,
            )
            return self
        if mask is not None:
            raise ValueError(
                "mask= requires the rank-sketch state (sketch=True); the "
                "exact sample buffers do not fold masked updates."
            )
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(self) -> jax.Array:
        """AUROC per task; empty array before any update."""
        if self._sketch_mode:
            if int(self.num_total.sum()) == 0:
                return jnp.zeros(0)
            score = _binned_auroc_from_counts(
                self.num_tp, self.num_fp, self.num_pos, self.num_total
            )
            return score[0] if self.num_tasks == 1 else score
        if not self.inputs:
            return jnp.zeros(0)
        return _binary_auroc_compute(
            jnp.concatenate(self.inputs, axis=-1),
            jnp.concatenate(self.targets, axis=-1),
            self.use_fused,
        )

    def merge_state(self, metrics: Iterable["BinaryAUROC"]) -> "BinaryAUROC":
        if self._sketch_mode:
            rank_merge_state(self, metrics)
            return self
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=-1)
        return self

    def _prepare_for_merge_state(self) -> None:
        if self._sketch_mode:
            return  # counts are already flat arrays on the sync wire
        prepare_concat_buffers(self, "inputs", "targets", dim=-1)

    def sketch_state(self, kind: str = "exact", **options):
        """O(bins) mergeable summaries for the hierarchical fleet merge:
        ``"reservoir"`` (``capacity=``, error O(1/sqrt(capacity))),
        ``"histogram"`` (``bins=``, error O(1/bins)), ``"count"``
        (``width=``/``depth=``, per-bin count error n/sqrt(width)),
        ``"rank"`` (``bins=``, rank error ≤ 1/(bins-1), bit-deterministic
        add-merge — and the native payload of a ``sketch=True`` metric),
        or lossless ``"exact"``.  See
        :mod:`torcheval_tpu.metrics._sketch` and :doc:`/sketch`."""
        if self._sketch_mode:
            return rank_sketch_state(self, "binary_auroc", kind, **options)
        from torcheval_tpu.metrics._sketch import sketch_from_buffers

        return sketch_from_buffers(self, "binary_auroc", kind, **options)


class MulticlassAUROC(Metric[jax.Array]):
    """One-vs-rest multiclass AUROC (reference ``auroc.py:93-229``).

    ``sketch=True`` (default: ``TORCHEVAL_TPU_RANK_SKETCH``, else off)
    replaces the sample buffers with per-class rank-sketch counts; the
    one-vs-rest scores then come from the binned trapezoid estimator
    within ε = 1/(bins-1) per class."""

    def __init__(
        self,
        *,
        num_classes: int,
        average: Optional[str] = "macro",
        device=None,
        sketch: Optional[bool] = None,
        sketch_bins: Optional[int] = None,
    ) -> None:
        super().__init__(device=device)
        _multiclass_auroc_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average
        self._sketch_mode = rank_sketch_enabled() if sketch is None else bool(sketch)
        if self._sketch_mode:
            install_rank_states(self, num_classes, sketch_bins)
        else:
            self._add_state("inputs", [])
            self._add_state("targets", [])

    def update(self, input, target, *, mask=None) -> "MulticlassAUROC":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multiclass_auroc_update_input_check(input, target, self.num_classes)
        if self._sketch_mode:
            route = rank_route(self, input.shape[0])
            rank_accumulate(
                self, _rank_multiclass_kernel, input, target,
                statics=(self.num_classes, route),
                mask=mask,
            )
            return self
        if mask is not None:
            raise ValueError(
                "mask= requires the rank-sketch state (sketch=True); the "
                "exact sample buffers do not fold masked updates."
            )
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(self) -> jax.Array:
        """AUROC (macro scalar or per-class); empty array before any update."""
        if self._sketch_mode:
            if int(self.num_total.sum()) == 0:
                return jnp.zeros(0)
            score = _binned_auroc_from_counts(
                self.num_tp, self.num_fp, self.num_pos, self.num_total
            )
            return score.mean() if self.average == "macro" else score
        if not self.inputs:
            return jnp.zeros(0)
        return _multiclass_auroc_compute(
            jnp.concatenate(self.inputs, axis=0),
            jnp.concatenate(self.targets, axis=0),
            self.num_classes,
            self.average,
        )

    def merge_state(self, metrics: Iterable["MulticlassAUROC"]) -> "MulticlassAUROC":
        if self._sketch_mode:
            rank_merge_state(self, metrics)
            return self
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=0)
        return self

    def _prepare_for_merge_state(self) -> None:
        if self._sketch_mode:
            return
        prepare_concat_buffers(self, "inputs", "targets", dim=0)

    def sketch_state(self, kind: str = "exact", **options):
        """Mergeable summary for the fleet merge.  A ``sketch=True``
        metric ships its O(classes × bins) rank counts (``"rank"``);
        buffer-mode supports only the lossless ``"exact"`` gather (the
        compressed sample kinds are binary-only)."""
        if self._sketch_mode:
            return rank_sketch_state(self, "multiclass_auroc", kind, **options)
        return super().sketch_state(kind, **options)
