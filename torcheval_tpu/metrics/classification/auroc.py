"""AUROC metrics — parity with reference
``torcheval/metrics/classification/auroc.py`` (229 LoC).

Sample-buffer states (``inputs``/``targets`` lists); merge concatenates;
``_prepare_for_merge_state`` pre-concats to one array per state for the
sync wire (reference ``auroc.py:89-92,130-134``)."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import merge_concat_buffers, prepare_concat_buffers
from torcheval_tpu.metrics.functional.classification.auroc import (
    _binary_auroc_compute,
    _binary_auroc_update_input_check,
    _multiclass_auroc_compute,
    _multiclass_auroc_param_check,
    _multiclass_auroc_update_input_check,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.ops.fused_auc import has_fused


class BinaryAUROC(Metric[jax.Array]):
    """Binary AUROC with multi-task support and the ``use_fused``
    approximate-kernel opt-in (the reference's ``use_fbgemm`` analog,
    reference ``auroc.py:27-48``)."""

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        device=None,
        use_fused: Optional[bool] = False,
    ) -> None:
        super().__init__(device=device)
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        if use_fused and not has_fused():
            raise ValueError(
                "`use_fused` requires the fused AUC kernel to be available."
            )
        self.num_tasks = num_tasks
        self.use_fused = use_fused
        self._add_state("inputs", [])
        self._add_state("targets", [])

    def update(self, input, target) -> "BinaryAUROC":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_auroc_update_input_check(input, target, self.num_tasks)
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(self) -> jax.Array:
        """AUROC per task; empty array before any update."""
        if not self.inputs:
            return jnp.zeros(0)
        return _binary_auroc_compute(
            jnp.concatenate(self.inputs, axis=-1),
            jnp.concatenate(self.targets, axis=-1),
            self.use_fused,
        )

    def merge_state(self, metrics: Iterable["BinaryAUROC"]) -> "BinaryAUROC":
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=-1)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "inputs", "targets", dim=-1)

    def sketch_state(self, kind: str = "exact", **options):
        """O(bins) mergeable summaries of the sample buffers for the
        hierarchical fleet merge: ``"reservoir"`` (``capacity=``, error
        O(1/sqrt(capacity))), ``"histogram"`` (``bins=``, error
        O(1/bins)), ``"count"`` (``width=``/``depth=``, per-bin count
        error n/sqrt(width)), or lossless ``"exact"``.  See
        :mod:`torcheval_tpu.metrics._sketch`."""
        from torcheval_tpu.metrics._sketch import sketch_from_buffers

        return sketch_from_buffers(self, "binary_auroc", kind, **options)


class MulticlassAUROC(Metric[jax.Array]):
    """One-vs-rest multiclass AUROC (reference ``auroc.py:93-229``)."""

    def __init__(
        self,
        *,
        num_classes: int,
        average: Optional[str] = "macro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _multiclass_auroc_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average
        self._add_state("inputs", [])
        self._add_state("targets", [])

    def update(self, input, target) -> "MulticlassAUROC":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multiclass_auroc_update_input_check(input, target, self.num_classes)
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(self) -> jax.Array:
        """AUROC (macro scalar or per-class); empty array before any update."""
        if not self.inputs:
            return jnp.zeros(0)
        return _multiclass_auroc_compute(
            jnp.concatenate(self.inputs, axis=0),
            jnp.concatenate(self.targets, axis=0),
            self.num_classes,
            self.average,
        )

    def merge_state(self, metrics: Iterable["MulticlassAUROC"]) -> "MulticlassAUROC":
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=0)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "inputs", "targets", dim=0)
