"""AUPRC metrics — average precision over buffered samples.

Beyond the reference snapshot (upstream torcheval added AUPRC after
v0.0.4); same buffer-state design as the AUROC classes: ``inputs``/
``targets`` lists, concat merge, pre-concat for the sync wire."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import merge_concat_buffers, prepare_concat_buffers
from torcheval_tpu.metrics._rank_state import (
    _rank_binary_kernel,
    install_rank_states,
    rank_accumulate,
    rank_merge_state,
    rank_route,
    rank_sketch_state,
)
from torcheval_tpu.metrics.functional.classification.auprc import (
    _binary_auprc_compute,
    _multiclass_auprc_compute,
    _multiclass_auprc_param_check,
    _multilabel_auprc_compute,
    _multilabel_auprc_param_check,
    _multilabel_auprc_update_input_check,
)
from torcheval_tpu.metrics.functional.classification.auroc import (
    _binary_auroc_update_input_check,
    _multiclass_auroc_update_input_check,
)
from torcheval_tpu.metrics.functional.classification.binned_auc import (
    _binned_auprc_from_counts,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.ops._flags import rank_sketch_enabled


class BinaryAUPRC(Metric[jax.Array]):
    """Binary average precision with multi-task support (buffered, exact).

    ``sketch=True`` (default: ``TORCHEVAL_TPU_RANK_SKETCH``, else off)
    replaces the exact sample buffers with the mergeable rank-sketch
    counts — see :doc:`/sketch` for the state layout and error bounds."""

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        device=None,
        sketch: Optional[bool] = None,
        sketch_bins: Optional[int] = None,
    ) -> None:
        super().__init__(device=device)
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        self.num_tasks = num_tasks
        self._sketch_mode = rank_sketch_enabled() if sketch is None else bool(sketch)
        if self._sketch_mode:
            install_rank_states(self, num_tasks, sketch_bins)
        else:
            self._add_state("inputs", [])
            self._add_state("targets", [])

    def update(self, input, target, *, mask=None) -> "BinaryAUPRC":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_auroc_update_input_check(input, target, self.num_tasks)
        if self._sketch_mode:
            route = rank_route(self, input.shape[-1])
            rank_accumulate(
                self, _rank_binary_kernel, input, target, statics=(route,),
                mask=mask,
            )
            return self
        if mask is not None:
            raise ValueError(
                "mask= requires the rank-sketch state (sketch=True); the "
                "exact sample buffers do not fold masked updates."
            )
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(self) -> jax.Array:
        """Average precision per task; empty array before any update."""
        if self._sketch_mode:
            if int(self.num_total.sum()) == 0:
                return jnp.zeros(0)
            score = _binned_auprc_from_counts(
                self.num_tp, self.num_fp, self.num_pos, self.num_total
            )
            return score[0] if self.num_tasks == 1 else score
        if not self.inputs:
            return jnp.zeros(0)
        input = jnp.concatenate(self.inputs, axis=-1)
        if input.shape[-1] == 0:  # only zero-length updates buffered
            return jnp.zeros(input.shape[:-1])
        return _binary_auprc_compute(
            input, jnp.concatenate(self.targets, axis=-1)
        )

    def merge_state(self, metrics: Iterable["BinaryAUPRC"]) -> "BinaryAUPRC":
        if self._sketch_mode:
            rank_merge_state(self, metrics)
            return self
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=-1)
        return self

    def _prepare_for_merge_state(self) -> None:
        if self._sketch_mode:
            return  # counts are already flat arrays on the sync wire
        prepare_concat_buffers(self, "inputs", "targets", dim=-1)

    def sketch_state(self, kind: str = "exact", **options):
        """O(bins) mergeable summaries of the sample buffers for the
        hierarchical fleet merge — same kinds and bounds as
        :meth:`BinaryAUROC.sketch_state`
        (:mod:`torcheval_tpu.metrics._sketch`)."""
        if self._sketch_mode:
            return rank_sketch_state(self, "binary_auprc", kind, **options)
        from torcheval_tpu.metrics._sketch import sketch_from_buffers

        return sketch_from_buffers(self, "binary_auprc", kind, **options)


class MulticlassAUPRC(Metric[jax.Array]):
    """One-vs-rest average precision with macro/None averaging."""

    def __init__(
        self,
        *,
        num_classes: int,
        average: Optional[str] = "macro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _multiclass_auprc_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average
        self._add_state("inputs", [])
        self._add_state("targets", [])

    def update(self, input, target) -> "MulticlassAUPRC":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multiclass_auroc_update_input_check(input, target, self.num_classes)
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(self) -> jax.Array:
        """Macro or per-class average precision; empty array before any
        update."""
        if not self.inputs:
            return jnp.zeros(0)
        input = jnp.concatenate(self.inputs, axis=0)
        if input.shape[0] == 0:  # only zero-length updates buffered
            return (
                jnp.zeros(())
                if self.average == "macro"
                else jnp.zeros(self.num_classes)
            )
        return _multiclass_auprc_compute(
            input,
            jnp.concatenate(self.targets, axis=0),
            self.num_classes,
            self.average,
        )

    def merge_state(self, metrics: Iterable["MulticlassAUPRC"]) -> "MulticlassAUPRC":
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=0)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "inputs", "targets", dim=0)


class MultilabelAUPRC(Metric[jax.Array]):
    """Per-label average precision over a 0/1 label matrix, macro/None
    averaging.  Beyond the v0.0.4 snapshot (upstream torcheval added
    ``MultilabelAUPRC`` later)."""

    def __init__(
        self,
        *,
        num_labels: int,
        average: Optional[str] = "macro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _multilabel_auprc_param_check(num_labels, average)
        self.num_labels = num_labels
        self.average = average
        self._add_state("inputs", [])
        self._add_state("targets", [])

    def update(self, input, target) -> "MultilabelAUPRC":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multilabel_auprc_update_input_check(input, target, self.num_labels)
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(self) -> jax.Array:
        """Macro or per-label average precision; empty array before any
        update."""
        if not self.inputs:
            return jnp.zeros(0)
        input = jnp.concatenate(self.inputs, axis=0)
        if input.shape[0] == 0:  # only zero-length updates buffered
            return (
                jnp.zeros(())
                if self.average == "macro"
                else jnp.zeros(self.num_labels)
            )
        return _multilabel_auprc_compute(
            input,
            jnp.concatenate(self.targets, axis=0),
            self.average,
        )

    def merge_state(self, metrics: Iterable["MultilabelAUPRC"]) -> "MultilabelAUPRC":
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=0)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "inputs", "targets", dim=0)
