"""AUPRC metrics — average precision over buffered samples.

Beyond the reference snapshot (upstream torcheval added AUPRC after
v0.0.4); same buffer-state design as the AUROC classes: ``inputs``/
``targets`` lists, concat merge, pre-concat for the sync wire."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import merge_concat_buffers, prepare_concat_buffers
from torcheval_tpu.metrics.functional.classification.auprc import (
    _binary_auprc_compute,
    _multiclass_auprc_compute,
    _multiclass_auprc_param_check,
    _multilabel_auprc_compute,
    _multilabel_auprc_param_check,
    _multilabel_auprc_update_input_check,
)
from torcheval_tpu.metrics.functional.classification.auroc import (
    _binary_auroc_update_input_check,
    _multiclass_auroc_update_input_check,
)
from torcheval_tpu.metrics.metric import Metric


class BinaryAUPRC(Metric[jax.Array]):
    """Binary average precision with multi-task support (buffered, exact)."""

    def __init__(self, *, num_tasks: int = 1, device=None) -> None:
        super().__init__(device=device)
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        self.num_tasks = num_tasks
        self._add_state("inputs", [])
        self._add_state("targets", [])

    def update(self, input, target) -> "BinaryAUPRC":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_auroc_update_input_check(input, target, self.num_tasks)
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(self) -> jax.Array:
        """Average precision per task; empty array before any update."""
        if not self.inputs:
            return jnp.zeros(0)
        input = jnp.concatenate(self.inputs, axis=-1)
        if input.shape[-1] == 0:  # only zero-length updates buffered
            return jnp.zeros(input.shape[:-1])
        return _binary_auprc_compute(
            input, jnp.concatenate(self.targets, axis=-1)
        )

    def merge_state(self, metrics: Iterable["BinaryAUPRC"]) -> "BinaryAUPRC":
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=-1)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "inputs", "targets", dim=-1)

    def sketch_state(self, kind: str = "exact", **options):
        """O(bins) mergeable summaries of the sample buffers for the
        hierarchical fleet merge — same kinds and bounds as
        :meth:`BinaryAUROC.sketch_state`
        (:mod:`torcheval_tpu.metrics._sketch`)."""
        from torcheval_tpu.metrics._sketch import sketch_from_buffers

        return sketch_from_buffers(self, "binary_auprc", kind, **options)


class MulticlassAUPRC(Metric[jax.Array]):
    """One-vs-rest average precision with macro/None averaging."""

    def __init__(
        self,
        *,
        num_classes: int,
        average: Optional[str] = "macro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _multiclass_auprc_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average
        self._add_state("inputs", [])
        self._add_state("targets", [])

    def update(self, input, target) -> "MulticlassAUPRC":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multiclass_auroc_update_input_check(input, target, self.num_classes)
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(self) -> jax.Array:
        """Macro or per-class average precision; empty array before any
        update."""
        if not self.inputs:
            return jnp.zeros(0)
        input = jnp.concatenate(self.inputs, axis=0)
        if input.shape[0] == 0:  # only zero-length updates buffered
            return (
                jnp.zeros(())
                if self.average == "macro"
                else jnp.zeros(self.num_classes)
            )
        return _multiclass_auprc_compute(
            input,
            jnp.concatenate(self.targets, axis=0),
            self.num_classes,
            self.average,
        )

    def merge_state(self, metrics: Iterable["MulticlassAUPRC"]) -> "MulticlassAUPRC":
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=0)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "inputs", "targets", dim=0)


class MultilabelAUPRC(Metric[jax.Array]):
    """Per-label average precision over a 0/1 label matrix, macro/None
    averaging.  Beyond the v0.0.4 snapshot (upstream torcheval added
    ``MultilabelAUPRC`` later)."""

    def __init__(
        self,
        *,
        num_labels: int,
        average: Optional[str] = "macro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _multilabel_auprc_param_check(num_labels, average)
        self.num_labels = num_labels
        self.average = average
        self._add_state("inputs", [])
        self._add_state("targets", [])

    def update(self, input, target) -> "MultilabelAUPRC":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _multilabel_auprc_update_input_check(input, target, self.num_labels)
        self.inputs.append(jax.device_put(input, self.device))
        self.targets.append(jax.device_put(target, self.device))
        return self

    def compute(self) -> jax.Array:
        """Macro or per-label average precision; empty array before any
        update."""
        if not self.inputs:
            return jnp.zeros(0)
        input = jnp.concatenate(self.inputs, axis=0)
        if input.shape[0] == 0:  # only zero-length updates buffered
            return (
                jnp.zeros(())
                if self.average == "macro"
                else jnp.zeros(self.num_labels)
            )
        return _multilabel_auprc_compute(
            input,
            jnp.concatenate(self.targets, axis=0),
            self.average,
        )

    def merge_state(self, metrics: Iterable["MultilabelAUPRC"]) -> "MultilabelAUPRC":
        merge_concat_buffers(self, metrics, "inputs", "targets", dim=0)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "inputs", "targets", dim=0)
