"""Recall metrics — parity with reference
``torcheval/metrics/classification/recall.py`` (245 LoC)."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
    _counts_route,
)
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.classification.recall import (
    _binary_recall_compute,
    _binary_recall_update_input_check,
    _binary_recall_update_kernel,
    _recall_compute,
    _recall_param_check,
    _recall_update_kernel,
    _recall_validate,
)
from torcheval_tpu.metrics.metric import Metric


class BinaryRecall(Metric[jax.Array]):
    """States: ``num_tp`` / ``num_true_labels``
    (reference ``recall.py:26-110``); merge: add."""

    # Accepts update(..., mask=) for bucketed ragged batches (_bucket.py).
    _supports_mask = True

    def __init__(self, *, threshold: float = 0.5, device=None) -> None:
        super().__init__(device=device)
        self.threshold = threshold
        self._add_state("num_tp", jnp.asarray(0.0))
        self._add_state("num_true_labels", jnp.asarray(0.0))

    def update(self, input, target, *, mask=None) -> "BinaryRecall":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _binary_recall_update_input_check(input, target)
        # Kernel + state adds fused into one dispatch (_fuse.py).
        self.num_tp, self.num_true_labels = accumulate(
            _binary_recall_update_kernel,
            (self.num_tp, self.num_true_labels),
            input,
            target,
            statics=(self.threshold,),
            mask=mask,
        )
        return self

    def compute(self) -> jax.Array:
        return _binary_recall_compute(self.num_tp, self.num_true_labels)

    def merge_state(self, metrics: Iterable["BinaryRecall"]):
        merge_add(self, metrics, "num_tp", "num_true_labels")
        return self


class MulticlassRecall(Metric[jax.Array]):
    """States: ``num_tp`` / ``num_labels`` / ``num_predictions``
    (reference ``recall.py:113-245``); merge: add (reference ``:240``)."""

    # Accepts update(..., mask=) for bucketed ragged batches (_bucket.py).
    _supports_mask = True

    _STATES = ("num_tp", "num_labels", "num_predictions")

    def __init__(
        self,
        *,
        num_classes: Optional[int] = None,
        average: Optional[str] = "micro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _recall_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average
        if average == "micro":
            for name in self._STATES:
                self._add_state(name, jnp.asarray(0.0))
        else:
            for name in self._STATES:
                self._add_state(name, jnp.zeros(num_classes))

    def update(self, input, target, *, mask=None) -> "MulticlassRecall":
        input, target = jnp.asarray(input), jnp.asarray(target)
        _recall_validate(input, target, self.num_classes, self.average)
        self.num_tp, self.num_labels, self.num_predictions = accumulate(
            _recall_update_kernel,
            (self.num_tp, self.num_labels, self.num_predictions),
            input,
            target,
            statics=(
                self.num_classes,
                self.average,
                _counts_route(input, self.num_classes, self.average),
            ),
            mask=mask,
        )
        return self

    def compute(self) -> jax.Array:
        return _recall_compute(
            self.num_tp, self.num_labels, self.num_predictions, self.average
        )

    def merge_state(self, metrics: Iterable["MulticlassRecall"]):
        merge_add(self, metrics, *self._STATES)
        return self
