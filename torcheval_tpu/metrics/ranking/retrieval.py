"""Retrieval precision / recall class metrics — per-query score buffers,
like HitRate/ReciprocalRank: each update scores one query (or
``num_tasks`` of them) and appends; compute concatenates the per-query
values.

Beyond the v0.0.4 snapshot (upstream torcheval added the retrieval
metrics later)."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import merge_concat_buffers, prepare_concat_buffers
from torcheval_tpu.metrics.functional.ranking.retrieval import (
    retrieval_precision,
    retrieval_recall,
)
from torcheval_tpu.metrics.metric import Metric


class _RetrievalMetric(Metric[jax.Array]):
    """Shared buffer machinery; subclasses pick the per-query scorer."""

    _scorer = None

    def __init__(
        self,
        *,
        k: Optional[int] = None,
        limit_k_to_size: bool = False,
        num_tasks: int = 1,
        device=None,
    ) -> None:
        super().__init__(device=device)
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        self.k = k
        self.limit_k_to_size = limit_k_to_size
        self.num_tasks = num_tasks
        self._add_state("scores", [])

    def update(self, input, target):
        value = type(self)._scorer(
            input,
            target,
            self.k,
            limit_k_to_size=self.limit_k_to_size,
            num_tasks=self.num_tasks,
        )
        self.scores.append(
            jax.device_put(jnp.atleast_1d(value), self.device)
        )
        return self

    def compute(self) -> jax.Array:
        """Per-query values, concatenated over updates (shape
        ``(num_queries,)``, or ``(num_queries * num_tasks,)`` for
        multi-task); empty array before any update."""
        if not self.scores:
            return jnp.zeros(0)
        return jnp.concatenate(self.scores, axis=0)

    def merge_state(self, metrics: Iterable["_RetrievalMetric"]):
        merge_concat_buffers(self, metrics, "scores", dim=0)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "scores", dim=0)


class RetrievalPrecision(_RetrievalMetric):
    """precision@k per query seen."""

    _scorer = staticmethod(retrieval_precision)


class RetrievalRecall(_RetrievalMetric):
    """recall@k per query seen."""

    _scorer = staticmethod(retrieval_recall)
