"""HitRate metric — parity with reference
``torcheval/metrics/ranking/hit_rate.py`` (96 LoC).

Buffer state: per-sample scores appended each update; compute concatenates
(reference ``hit_rate.py:54-96``)."""

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._buffer import merge_concat_buffers, prepare_concat_buffers
from torcheval_tpu.metrics.functional.ranking.hit_rate import hit_rate
from torcheval_tpu.metrics.metric import Metric


class HitRate(Metric[jax.Array]):
    def __init__(self, *, k: Optional[int] = None, device=None) -> None:
        super().__init__(device=device)
        self.k = k
        self._add_state("scores", [])

    def update(self, input, target) -> "HitRate":
        self.scores.append(
            jax.device_put(hit_rate(input, target, k=self.k), self.device)
        )
        return self

    def compute(self) -> jax.Array:
        """Concatenated per-sample hit scores; empty array before any update."""
        if not self.scores:
            return jnp.zeros(0)
        return jnp.concatenate(self.scores, axis=0)

    def merge_state(self, metrics: Iterable["HitRate"]) -> "HitRate":
        merge_concat_buffers(self, metrics, "scores", dim=0)
        return self

    def _prepare_for_merge_state(self) -> None:
        prepare_concat_buffers(self, "scores", dim=0)
