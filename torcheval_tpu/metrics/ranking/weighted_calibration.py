"""WeightedCalibration metric — parity with reference
``torcheval/metrics/ranking/weighted_calibration.py`` (129 LoC).

States: per-task ``weighted_input_sum`` / ``weighted_target_sum``
(reference ``:67-74``); merge: add (reference ``:117``)."""

from typing import Iterable, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics._fuse import accumulate
from torcheval_tpu.metrics._merge import merge_add
from torcheval_tpu.metrics.functional.classification.binary_normalized_entropy import (
    _accum_dtype,
)
from torcheval_tpu.metrics.functional.ranking.weighted_calibration import (
    _weighted_calibration_select_kernel,
)
from torcheval_tpu.metrics.metric import Metric


class WeightedCalibration(Metric[jax.Array]):
    def __init__(self, *, num_tasks: int = 1, device=None) -> None:
        super().__init__(device=device)
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        self.num_tasks = num_tasks
        self._add_state("weighted_input_sum", jnp.zeros(num_tasks, dtype=_accum_dtype()))
        self._add_state(
            "weighted_target_sum", jnp.zeros(num_tasks, dtype=_accum_dtype())
        )

    def update(
        self, input, target, weight: Union[float, int, "jax.Array"] = 1.0
    ) -> "WeightedCalibration":
        input, target = jnp.asarray(input), jnp.asarray(target)
        kernel, args = _weighted_calibration_select_kernel(
            input, target, weight, num_tasks=self.num_tasks
        )
        # Kernel + both state adds fused into one dispatch (_fuse.py).
        self.weighted_input_sum, self.weighted_target_sum = accumulate(
            kernel, (self.weighted_input_sum, self.weighted_target_sum), *args
        )
        return self

    def compute(self) -> jax.Array:
        """Σw·input / Σw·target per task; NaN where no target weight has been
        seen (0/0)."""
        return self.weighted_input_sum / self.weighted_target_sum

    def merge_state(self, metrics: Iterable["WeightedCalibration"]):
        merge_add(self, metrics, "weighted_input_sum", "weighted_target_sum")
        return self
