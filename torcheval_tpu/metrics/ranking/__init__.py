from torcheval_tpu.metrics.ranking.hit_rate import HitRate
from torcheval_tpu.metrics.ranking.reciprocal_rank import ReciprocalRank
from torcheval_tpu.metrics.ranking.retrieval import RetrievalPrecision, RetrievalRecall
from torcheval_tpu.metrics.ranking.weighted_calibration import WeightedCalibration

__all__ = [
    "HitRate",
    "ReciprocalRank",
    "RetrievalPrecision",
    "RetrievalRecall",
    "WeightedCalibration",
]
