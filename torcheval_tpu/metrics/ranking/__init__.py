from torcheval_tpu.metrics.ranking.weighted_calibration import WeightedCalibration

__all__ = ["WeightedCalibration"]
