"""Exact pod-scale curve metrics over a device mesh — SURVEY §7 hard-part 4.

The reference is *exact* when distributed by gathering every raw sample to
one rank as pickled Python objects (reference ``classification/auroc.py:
121-134`` + ``toolkit.py:247-255``).  The histogram metrics in
:mod:`torcheval_tpu.parallel.sync` trade that exactness for O(bins) wire.
This module closes the gap with two TPU-native exact families:

**gather-exact** (``sharded_binary_auroc_exact`` /
``sharded_multitask_auroc_exact`` / ``sharded_multiclass_auroc_exact`` /
``sharded_binary_auprc_exact``):
``lax.all_gather(..., tiled=True)`` reassembles the shard-order
concatenation of the mesh-sharded samples *device-side* (the collective
rides ICI/DCN; no host, no pickle) and every device runs the SAME exact
jitted kernel the single-device functional uses.  Because the gathered
array is bit-identical to the concatenated input and the downstream program
is the identical deterministic XLA computation (``lax.sort`` is stable),
the result is **bit-for-bit equal** to ``binary_auroc(concat(shards))`` —
not merely close.  Wire cost: O(N), like the reference, but collective
bandwidth instead of host pickle bandwidth.

**ustat-exact** (``sharded_binary_auroc_ustat`` /
``sharded_multiclass_auroc_ustat``): never ships the majority class.
Exact AUROC equals the normalized Mann-Whitney U statistic

    U = Σ_{neg j} [ #pos > s_j  +  ½ · #pos == s_j ],
    AUROC = U / (#pos · #neg)

(the same identity the fused Pallas kernel computes,
``ops/pallas_auc.py:16-27``).  Each device packs and sorts its LOCAL
minority-class scores, ONE all-gather ships just those runs — with the
per-shard capacity cap set, O(P · cap) ≈ O(minority) wire, the pod-scale
win when positives are rare — every device re-sorts the runs and resolves
its local majority shard's pair counts with two vectorized binary searches
(exact integer counts), and ONE ``psum`` merges the partial U.  Pair
counts are exact integers; scores are compared in their own float dtype
(float32 minimum) and the U accumulation is float32 (float64 under
``jax_enable_x64``) — machine-precision like every other float
implementation, with no quantization term.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec

import numpy as np

from torcheval_tpu.metrics.functional._host_checks import (
    all_concrete,
    value_checks_enabled,
)
from torcheval_tpu.parallel._compat import shard_map
from torcheval_tpu.parallel._compile_cache import compiled_spmd
from torcheval_tpu.parallel.mesh import AxisSpec, _axis_size


def _accum_dtype() -> jnp.dtype:
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


# Memoized jit(shard_map(...)) programs (see _compile_cache docstring).
_compiled = compiled_spmd


def _resolve_multi_axis_comm(comm: str, axis: AxisSpec) -> str:
    """THE tuple-axis schedule policy, shared by every ustat wrapper,
    :func:`eager_ustat_pin`, and ``routing.explain_route``: multi-axis
    sample sharding keeps every collective (they take the axis tuple
    directly) but has no single-axis ``lax.ppermute`` ring.  Returns the
    resolved ``comm``; raises for an explicit ring request."""
    if isinstance(axis, str):
        return comm
    if comm == "ring":
        raise ValueError(
            "comm='ring' needs a single mesh axis (lax.ppermute has no "
            "multi-axis ring); use comm='gather' or a 1-D mesh axis for "
            "the sample dimension."
        )
    return "gather"


def _check_even_1d(scores, targets, mesh: Mesh, axis: str) -> None:
    if scores.ndim != 1 or targets.ndim != 1 or scores.shape != targets.shape:
        raise ValueError(
            "scores and targets should be 1-D of equal length, got "
            f"{scores.shape} / {targets.shape}."
        )
    size = _axis_size(mesh, axis)
    if scores.shape[0] % size != 0:
        raise ValueError(
            f"sample count {scores.shape[0]} must divide evenly over mesh "
            f"axis {axis!r} of size {size} (pad the batch or use a "
            "divisible shard size)."
        )


def _check_even_tasks(scores, targets, mesh: Mesh, axis: str) -> None:
    if scores.ndim != 2 or scores.shape != targets.shape:
        raise ValueError(
            "scores and targets should be (num_tasks, N) of equal shape, "
            f"got {scores.shape} / {targets.shape}."
        )
    size = _axis_size(mesh, axis)
    if scores.shape[1] % size != 0:
        raise ValueError(
            f"sample count {scores.shape[1]} must divide evenly over mesh "
            f"axis {axis!r} of size {size}."
        )


def sharded_multitask_auroc_exact(
    scores: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    axis: AxisSpec = "dp",
) -> jax.Array:
    """Bit-exact pod AUROC for multi-task ``(num_tasks, N)`` inputs
    sharded over the sample axis — the mesh analog of
    ``binary_auroc(..., num_tasks=T)`` (same gather-exact scheme as
    :func:`sharded_binary_auroc_exact`)."""
    from torcheval_tpu.ops.pallas_ustat import binary_ustat_route

    _check_even_tasks(scores, targets, mesh, axis)
    # Route decided eagerly on the same data the replicated kernel sees
    # (bitwise-consistency with the eager oracle, as in the multiclass
    # wrapper).
    route = binary_ustat_route(scores, targets)
    return _gather_exact(_k_binary_auroc, route, mesh, axis, 1, scores, targets)


def _k_binary_auroc(route, s_all, t_all):
    from torcheval_tpu.metrics.functional.classification.auroc import (
        _binary_auroc_compute,
    )

    return _binary_auroc_compute(s_all, t_all, ustat_route=route)


def _k_binary_auprc(route, s_all, t_all):
    from torcheval_tpu.metrics.functional.classification.auprc import (
        _binary_auprc_compute,
    )

    return _binary_auprc_compute(s_all, t_all, ustat_route=route)


def _k_multiclass_auroc(statics, s_all, t_all):
    from torcheval_tpu.metrics.functional.classification.auroc import (
        _multiclass_auroc_compute,
    )

    num_classes, average, cap = statics
    return _multiclass_auroc_compute(
        s_all, t_all, num_classes, average, ustat_cap=cap
    )


def _gather_exact(
    kernel_fn, statics, mesh: Mesh, axis: str, sample_axis: int, scores, targets
):
    """Shared gather-exact scaffold: device-side tiled all-gather along the
    sample axis reassembles the shard-order concatenation, then ``kernel_fn``
    (a module-level function wrapping the identical single-device jitted
    compute; hashable ``statics`` carry the route decision) runs replicated
    — the bit-for-bit contract of the whole family."""
    fn = _compiled(_build_gather_exact, (kernel_fn, statics, sample_axis), mesh, axis)
    return fn(scores, targets)


def _build_gather_exact(statics, mesh: Mesh, axis: str):
    kernel_fn, kernel_statics, sample_axis = statics

    def local(s, t):
        s_all = lax.all_gather(s, axis, axis=sample_axis, tiled=True)
        t_all = lax.all_gather(t, axis, axis=sample_axis, tiled=True)
        return kernel_fn(kernel_statics, s_all, t_all)

    spec = (
        PartitionSpec(axis) if sample_axis == 0 else PartitionSpec(None, axis)
    )
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=spec,
            out_specs=PartitionSpec(),
            check_vma=False,  # gathered result is replicated by construction
        )
    )


def sharded_binary_auroc_exact(
    scores: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    axis: AxisSpec = "dp",
) -> jax.Array:
    """Bit-exact pod AUROC from mesh-sharded samples.

    Device-side all-gather in shard order + the single-device exact kernel:
    the result equals ``binary_auroc(scores, targets)`` on the unsharded
    arrays bit-for-bit (same values through the same deterministic XLA
    program).  This is the distributed-exactness contract the reference
    meets by pickling raw buffers to one rank (reference
    ``functional/classification/auroc.py:111-142``, ``toolkit.py:247-255``)
    — minus the host round trip.
    """
    from torcheval_tpu.ops.pallas_ustat import binary_ustat_route

    _check_even_1d(scores, targets, mesh, axis)
    route = binary_ustat_route(scores[None], targets[None])
    return _gather_exact(_k_binary_auroc, route, mesh, axis, 0, scores, targets)


def sharded_binary_auprc_exact(
    scores: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    axis: AxisSpec = "dp",
) -> jax.Array:
    """Bit-exact pod average precision (same scheme as
    :func:`sharded_binary_auroc_exact`; kernel =
    ``functional.binary_auprc``'s tie-group step sum)."""
    from torcheval_tpu.ops.pallas_ustat import binary_ustat_route

    _check_even_1d(scores, targets, mesh, axis)
    route = binary_ustat_route(scores[None], targets[None], need_pos=True)
    return _gather_exact(_k_binary_auprc, route, mesh, axis, 0, scores, targets)


def sharded_multitask_auprc_exact(
    scores: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    axis: AxisSpec = "dp",
) -> jax.Array:
    """Bit-exact pod average precision for multi-task ``(num_tasks, N)``
    inputs sharded over the sample axis (same gather-exact scheme as
    :func:`sharded_multitask_auroc_exact`; the rare-positive rank-sum
    route is decided eagerly for bitwise consistency, as everywhere)."""
    from torcheval_tpu.ops.pallas_ustat import binary_ustat_route

    _check_even_tasks(scores, targets, mesh, axis)
    route = binary_ustat_route(scores, targets, need_pos=True)
    return _gather_exact(_k_binary_auprc, route, mesh, axis, 1, scores, targets)


def sharded_multiclass_auroc_exact(
    scores: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    axis: AxisSpec = "dp",
    *,
    num_classes: int,
    average: Optional[str] = "macro",
) -> jax.Array:
    """Bit-exact pod one-vs-rest multiclass AUROC (gather-exact scheme).

    O(N·C) wire — the exactness ceiling; prefer
    :func:`sharded_multiclass_auroc_ustat` (O(N) wire) or the histogram
    variant (O(C·bins) wire) when the pod is bandwidth-bound.
    """
    from torcheval_tpu.metrics.functional.classification.auroc import (
        _multiclass_auroc_param_check,
    )
    from torcheval_tpu.ops.pallas_ustat import ustat_route_cap

    _multiclass_auroc_param_check(num_classes, average)
    if scores.ndim != 2 or targets.ndim != 1:
        raise ValueError(
            "scores should be (N, C) and targets (N,), got "
            f"{scores.shape} / {targets.shape}."
        )
    size = _axis_size(mesh, axis)
    if scores.shape[0] % size != 0:
        raise ValueError(
            f"sample count {scores.shape[0]} must divide evenly over mesh "
            f"axis {axis!r} of size {size}."
        )
    # The gathered arrays equal the unsharded inputs bit-for-bit, so making
    # the rank-sum fast-path decision HERE (eagerly, on the same data the
    # replicated kernel will see) keeps the family's contract: the result
    # stays bitwise-equal to eager `multiclass_auroc(scores, targets)`,
    # whichever formulation the route picks.
    cap = ustat_route_cap(scores, targets, num_classes)
    return _gather_exact(
        _k_multiclass_auroc, (num_classes, average, cap), mesh, axis, 0,
        scores, targets,
    )


def _work_dtype(dtype) -> jnp.dtype:
    """Scores are compared in their own float dtype (float32 minimum), so
    x64 inputs keep full ordering resolution."""
    return dtype if dtype in (jnp.float32, jnp.float64) else jnp.float32


def _resolve_ustat_cap(
    requested: Optional[int],
    n_local: int,
    scores,
    targets,
    count_fn,
    param: str,
    noun: str,
) -> int:
    """Shared cap policy for the ustat family: ``None`` packs the full
    shard; an explicit cap below the shard length is validated against the
    measured per-shard maximum (``count_fn``, one fused round trip) unless
    value checks are skipped — then overflow silently drops the largest
    scores, as documented on each variant."""
    cap = min(requested, n_local) if requested is not None else n_local
    if (
        requested is not None
        and cap < n_local
        and value_checks_enabled()
        and all_concrete(scores, targets)
    ):
        overflow = int(count_fn())
        if overflow > cap:
            raise ValueError(
                f"{param}={requested} but a shard holds {overflow} {noun};"
                " raise the cap (or pass None to disable packing)."
            )
    return cap


def _check_finite_scores(
    scores, fn_name: str
) -> Optional[Tuple[float, float, float]]:
    """The ustat families pack minority runs with ±inf sentinels, so a
    legitimately infinite score would be indistinguishable from padding
    (tie counts absorb pads; the binary ``n_chosen - hi`` base can go
    negative).  Raise eagerly instead of returning a wrong AUROC.
    Skippable via ``skip_value_checks`` like every other host check; the
    gather-exact variants handle non-finite scores consistently.

    Returns the fetched ``(min, max, min nonzero |score|)`` when the
    check ran (so callers can reuse the round trip for their own route
    decisions), else ``None``."""
    if value_checks_enabled() and all_concrete(scores) and scores.size:
        # One fused round trip (the _host_checks bounds pattern): min/max
        # propagate NaN and surface +/-inf, so two scalars decide it.
        lo, hi, min_nz = (float(x) for x in np.asarray(_finite_gate_stats(scores)))
        _raise_if_not_finite(lo, hi, fn_name)
        return lo, hi, min_nz
    return None


def _raise_if_not_finite(lo: float, hi: float, fn_name: str) -> None:
    if not (np.isfinite(lo) and np.isfinite(hi)):
        raise ValueError(
            f"{fn_name} requires finite scores (its packed-run padding "
            "uses +/-inf sentinels); use the gather-exact variant for "
            "inputs that may contain inf/nan."
        )


def _finite_gate_stats_body(scores):
    """min, max, and smallest nonzero |score| — the finite check plus the
    Pallas-kernel gate's stats (bf16-split exactness needs magnitudes
    ≥ 2^-100; see ``pallas_ustat._MIN_SPLIT``).  Shared by the standalone
    and fused-wrapper fetch kernels."""
    from torcheval_tpu.ops.pallas_ustat import _min_nonzero_abs

    return [
        jnp.min(scores).astype(jnp.float32),
        jnp.max(scores).astype(jnp.float32),
        _min_nonzero_abs(scores),
    ]


@jax.jit
def _finite_gate_stats(scores) -> jax.Array:
    """One fused round trip of :func:`_finite_gate_stats_body`."""
    return jnp.stack(_finite_gate_stats_body(scores))


def sharded_binary_auroc_ustat(
    scores: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    axis: AxisSpec = "dp",
    *,
    max_minority_count_per_shard: Optional[int] = None,
    comm: str = "auto",
) -> jax.Array:
    """Exact pod AUROC gathering ONLY the minority class.

    Scheme (Mann-Whitney form, see module docstring): every device packs
    its local samples of the globally-rarer class into a sorted run
    (``+inf`` pads keep the shape static), one tiled all-gather ships the
    runs, each device re-sorts them and counts, for each of its local
    *other*-class samples, the exact number of gathered scores above /
    equal via two binary searches; one ``psum`` merges the partial U.

    Static shapes make the wire saving opt-in:
    ``max_minority_count_per_shard`` caps the per-shard run length, giving
    O(P · cap) ≈ O(min(#pos, #neg)) wire in the rare-class regime; left as
    ``None`` the run is the full shard length and the gather costs O(N)
    like the gather-exact path (still host-free).  A host-side check
    raises if any shard holds more minority samples than the cap
    (skippable via ``skip_value_checks``, in which case overflow silently
    drops that shard's largest minority scores).

    The minority side is chosen inside the program (``jnp.where`` masks, no
    host sync).  Exact pair counts; see module docstring for the
    accumulation-precision note.

    Scores must be finite: the packed runs pad with ``+inf`` sentinels, so
    infinite scores are rejected eagerly (skippable via
    ``skip_value_checks``; use the gather-exact variant for such inputs).

    ``comm="ring"`` replaces the all-gather with a ``ppermute`` ring of
    the packed runs — the multiclass variant's schedule (additive counts
    over disjoint chunks → BITWISE-identical result) at O(cap) peak
    memory instead of O(P·cap), with counting overlapped per step.
    """
    _check_even_1d(scores, targets, mesh, axis)
    if comm not in ("auto", "gather", "ring"):
        raise ValueError(
            f"comm should be 'auto', 'gather' or 'ring', got {comm!r}."
        )
    comm = _resolve_multi_axis_comm(comm, axis)
    _check_finite_scores(scores, "sharded_binary_auroc_ustat")
    size = _axis_size(mesh, axis)
    n_local = scores.shape[0] // size
    cap = _resolve_ustat_cap(
        max_minority_count_per_shard,
        n_local,
        scores,
        targets,
        lambda: _max_shard_minority_count(targets, world=size),
        "max_minority_count_per_shard",
        "minority-class samples",
    )
    if comm == "auto":
        # No kernel route in the binary family: ring only pays for
        # itself when the gathered pack is prohibitively large.
        comm = _choose_ustat_comm(1, cap, size)
    fn = _compiled(
        _build_binary_auroc_ustat,
        (cap, comm, bool(jax.config.jax_enable_x64)),
        mesh,
        axis,
    )
    return fn(scores, targets)


def _build_binary_auroc_ustat(statics, mesh: Mesh, axis: str):
    cap, comm, _x64 = statics
    acc = _accum_dtype()
    size = _axis_size(mesh, axis)

    def local(s, t):
        s = s.astype(_work_dtype(s.dtype))
        pos_mask = t != 0
        n_pos = lax.psum(jnp.sum(pos_mask, dtype=jnp.int32), axis)
        n_total = s.shape[0] * size
        n_neg = n_total - n_pos
        # Minority = positives iff they are no more than half the samples.
        pick_pos = n_pos * 2 <= n_total
        chosen_mask = jnp.where(pick_pos, pos_mask, ~pos_mask)
        n_chosen = jnp.where(pick_pos, n_pos, n_neg).astype(acc)

        # Ascending sort floats real scores above the +inf pads' tail, so
        # the cap slice keeps every minority score unless the shard
        # overflows (checked above).
        run = jnp.sort(jnp.where(chosen_mask, s, jnp.inf))[:cap]

        # Queries: this device's samples of the other class.  +inf pads sit
        # past every finite query, so `lo`/`hi` count only real scores.
        # method="sort": one variadic sort instead of a gather-based binary
        # search (TPU gathers serialize; see the multiclass variant).
        if comm == "ring":
            # Rotate the sorted runs; lo/hi are additive over disjoint
            # chunks.  int32 accumulation keeps every partial sum exact
            # (counts ≤ N), so the accumulated integers — and everything
            # derived from them — are BITWISE the gathered result's
            # after the single .astype(acc), the same one rounding the
            # gather path applies.  size-1 rotations: the last chunk is
            # counted in place, not shipped home.
            perm = [(j, (j + 1) % size) for j in range(size)]
            zeros = jnp.zeros(s.shape, jnp.int32)

            def count(chunk, lo_a, hi_a):
                lo_a = lo_a + jnp.searchsorted(
                    chunk, s, side="left", method="sort"
                )
                hi_a = hi_a + jnp.searchsorted(
                    chunk, s, side="right", method="sort"
                )
                return lo_a, hi_a

            def body(_, carry):
                chunk, lo_a, hi_a = carry
                lo_a, hi_a = count(chunk, lo_a, hi_a)
                return lax.ppermute(chunk, axis, perm=perm), lo_a, hi_a

            chunk, lo_i, hi_i = lax.fori_loop(
                0, size - 1, body, (run, zeros, zeros)
            )
            lo_i, hi_i = count(chunk, lo_i, hi_i)
            lo, hi = lo_i.astype(acc), hi_i.astype(acc)
        else:
            gathered = jnp.sort(lax.all_gather(run, axis, axis=0, tiled=True))
            lo = jnp.searchsorted(
                gathered, s, side="left", method="sort"
            ).astype(acc)
            hi = jnp.searchsorted(
                gathered, s, side="right", method="sort"
            ).astype(acc)
        ties = hi - lo
        # chosen=pos: U = Σ_neg #pos>q = n_chosen - hi;  chosen=neg:
        # U = Σ_pos #neg<q = lo.  Either way + ½·ties.
        base = jnp.where(pick_pos, n_chosen - hi, lo)
        contrib = jnp.where(chosen_mask, 0.0, base + 0.5 * ties)
        u = lax.psum(jnp.sum(contrib, dtype=acc), axis)

        factor = n_pos.astype(acc) * n_neg.astype(acc)
        return jnp.where(
            factor == 0, jnp.asarray(0.5, acc), u / factor
        ).astype(jnp.float32)

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(PartitionSpec(axis), PartitionSpec(axis)),
            out_specs=PartitionSpec(),
            check_vma=False,
        )
    )


def sharded_binary_auprc_ustat(
    scores: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    axis: AxisSpec = "dp",
    *,
    max_positive_count_per_shard: Optional[int] = None,
    comm: str = "auto",
) -> jax.Array:
    """Exact pod average precision shipping ONLY the positive class.

    Step-sum AP (the single-device ``binary_auprc`` semantics,
    ``auprc.py:_auprc_rows``) is a sum over positive entries ``a`` of the
    tie-group-end precision ``TP(≥v_a) / (TP(≥v_a) + FP(≥v_a))``, divided
    by ``n_pos``.  Both factors are computable from (1) the full multiset
    of positive scores and (2) per-threshold global negative counts — so
    the scheme is:

    1. Each shard packs its local positive scores into a sorted run
       (``+inf`` pads); ONE tiled all-gather ships the ``(P · cap)``
       runs — O(#pos) wire with the cap set, never O(N).
    2. Every device re-sorts the gathered positives; per entry,
       ``TP(≥v) = n_pos − #{P < v}`` by one binary search of the multiset
       against itself (exact, tie groups share the count).
    3. Each shard counts its local negatives ``≥ v`` for every gathered
       ``v`` (binary search over its sorted local negatives) and ONE
       ``psum`` merges the exact global ``FP`` vector — O(P · cap) wire.
    4. The masked precision sum over real entries, divided by ``n_pos``,
       is replicated-identical on every device.

    The exactness contract the reference meets by raw gather
    (reference ``toolkit.py:247-255``), at O(#pos) wire; matches the
    single-device kernel to float32 (both sum the same per-group terms
    through XLA tree reductions).  ``max_positive_count_per_shard``: like
    the binary ustat cap — a host check raises on overflow (skippable via
    ``skip_value_checks``, then overflow silently drops the largest
    positive scores).  Scores must be finite (``+inf`` pads), like the
    other ustat variants.

    ``comm="ring"``: here the gathered positives are the QUERY set, so
    the ring rotates each chunk of positive entries together with its
    partial ``(#positives < v, FP(≥v))`` counts; every device adds its
    local contributions to the visiting entries, and after P steps each
    chunk arrives home complete — O(cap) peak memory instead of
    O(P·cap).  The per-entry counts are identical integers; only the
    final precision SUM order differs (per-chunk instead of globally
    sorted), so ring-vs-gather parity is f32 summation order (~1e-7),
    not bitwise.
    """
    _check_even_1d(scores, targets, mesh, axis)
    if comm not in ("auto", "gather", "ring"):
        raise ValueError(
            f"comm should be 'auto', 'gather' or 'ring', got {comm!r}."
        )
    comm = _resolve_multi_axis_comm(comm, axis)
    _check_finite_scores(scores, "sharded_binary_auprc_ustat")
    size = _axis_size(mesh, axis)
    n_local = scores.shape[0] // size
    cap = _resolve_ustat_cap(
        max_positive_count_per_shard,
        n_local,
        scores,
        targets,
        lambda: _max_shard_positive_count(targets, world=size),
        "max_positive_count_per_shard",
        "positive samples",
    )
    if comm == "auto":
        comm = _choose_ustat_comm(1, cap, size)
    fn = _compiled(
        _build_binary_auprc_ustat,
        (cap, comm, bool(jax.config.jax_enable_x64)),
        mesh,
        axis,
    )
    return fn(scores, targets)


def _build_binary_auprc_ustat(statics, mesh: Mesh, axis: str):
    cap, comm, _x64 = statics
    acc = _accum_dtype()
    size = _axis_size(mesh, axis)

    def local(s, t):
        s = s.astype(_work_dtype(s.dtype))
        pos_mask = t == 1  # the single-device kernel's hit definition
        n_pos_local = jnp.sum(pos_mask, dtype=jnp.int32)
        n_pos = lax.psum(n_pos_local, axis)

        run = jnp.sort(jnp.where(pos_mask, s, jnp.inf))[:cap]
        neg_sorted = jnp.sort(jnp.where(pos_mask, jnp.inf, s))
        n_neg_local = jnp.int32(s.shape[0]) - n_pos_local

        # Per entry: TP(≥v) = n_pos − #{P < v}; dupes share the count, so
        # each contributes its group's precision once — exactly m_g · P_g.
        if comm == "ring":
            # The entries themselves are the query set, so each chunk
            # travels WITH its partial counts: every device adds
            # #{own positives < v} and its share of FP(≥v) to the
            # visiting entries; after P steps the chunk is home with
            # complete integers.
            perm = [(j, (j + 1) % size) for j in range(size)]
            zeros = jnp.zeros(run.shape, jnp.int32)

            def count(chunk, lo_a, fp_a):
                lo_a = lo_a + jnp.searchsorted(
                    run, chunk, side="left", method="sort"
                )
                fp_a = fp_a + (
                    n_neg_local
                    - jnp.searchsorted(
                        neg_sorted, chunk, side="left", method="sort"
                    )
                )
                return lo_a, fp_a

            def body(_, carry):
                chunk, lo_a, fp_a = carry
                lo_a, fp_a = count(chunk, lo_a, fp_a)
                return (
                    lax.ppermute(chunk, axis, perm=perm),
                    lax.ppermute(lo_a, axis, perm=perm),
                    lax.ppermute(fp_a, axis, perm=perm),
                )

            # size-1 rotations, final count in place: the psum below is
            # placement-agnostic, so shipping every chunk "home" on a
            # last rotation would be pure wasted wire.
            entries, lo_self, fp_i = lax.fori_loop(
                0, size - 1, body, (run, zeros, zeros)
            )
            lo_self, fp_i = count(entries, lo_self, fp_i)
            tp = (n_pos - lo_self).astype(acc)
            fp = fp_i.astype(acc)
            real = jnp.isfinite(entries)
            # Each device sums ITS chunk's precisions; one psum merges.
            precision = jnp.where(real, tp / jnp.maximum(tp + fp, 1.0), 0.0)
            prec_sum = lax.psum(jnp.sum(precision, dtype=acc), axis)
        else:
            gathered = jnp.sort(
                lax.all_gather(run, axis, axis=0, tiled=True)
            )
            real = jnp.isfinite(gathered)
            lo_self = jnp.searchsorted(
                gathered, gathered, side="left", method="sort"
            )
            tp = (n_pos - lo_self).astype(acc)
            lo_neg = jnp.searchsorted(
                neg_sorted, gathered, side="left", method="sort"
            )
            fp = lax.psum(n_neg_local - lo_neg, axis).astype(acc)
            precision = jnp.where(real, tp / jnp.maximum(tp + fp, 1.0), 0.0)
            prec_sum = jnp.sum(precision, dtype=acc)

        ap = prec_sum / jnp.maximum(n_pos.astype(acc), 1.0)
        return jnp.where(n_pos == 0, 0.0, ap).astype(jnp.float32)

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(PartitionSpec(axis), PartitionSpec(axis)),
            out_specs=PartitionSpec(),
            check_vma=False,
        )
    )


def sharded_multiclass_auroc_ustat(
    scores: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    axis: AxisSpec = "dp",
    *,
    num_classes: int,
    average: Optional[str] = "macro",
    max_class_count_per_shard: Optional[int] = None,
    comm: str = "auto",
    _kernel: str = "auto",
    _interpret: bool = False,
) -> jax.Array:
    """Exact pod one-vs-rest multiclass AUROC with O(C ·
    max_class_count_per_shard · P) wire — ~O(N) for balanced classes,
    vs O(N·C) for the gather-exact path (1000× less at C=1000, the
    BASELINE north-star shape).

    Per class ``c`` the positives are the samples labelled ``c`` — across
    all classes that is exactly N samples, so shipping "each class's
    positive scores" costs O(N) total.  Static shapes force a per-shard
    per-class capacity: each device packs its class-``c`` positive scores
    into row ``c`` of a ``(C, cap)`` matrix (``-inf`` pads), one all-gather
    ships the ``(P, C, cap)`` pack, every device re-sorts each class row
    and resolves its local negatives' exact pair counts by binary search,
    and one ``psum`` merges the per-class U.

    ``max_class_count_per_shard=None`` (the default) AUTOTUNES: one fused
    device round trip measures the largest per-shard single-class count
    and the cap becomes that value rounded up to a multiple of 64 (a few
    stable compile shapes, zero overflow risk by construction) — the
    ~O(N)-wire behavior with no hand-picked cap.  Under tracing the
    autotune cannot peek at values and falls back to the local shard
    length (exact but O(N·C) wire).  An explicit cap skips the autotune
    round trip; a host-side check then raises if any shard holds more
    samples of one class than the cap (skippable via
    ``skip_value_checks``, in which case overflow silently drops the
    largest scores of the overflowing class).

    Scores must be finite: the packed rows pad with ``-inf``/``inf``
    sentinels, so infinite scores are rejected eagerly (skippable via
    ``skip_value_checks``; use the gather-exact variant for such inputs).

    Local counting has two exact formulations, chosen per call by
    :func:`_mc_ustat_kernel_ok`: the Pallas rank-sum kernel on TPU
    (sort-free; the default whenever its int32/magnitude bounds hold) and
    the vmapped variadic-searchsorted pair otherwise.  ``_kernel``
    (``"auto"``/``"pallas"``/``"searchsorted"``) and ``_interpret`` pin a
    formulation — test hooks, not public API.

    ``comm`` selects the communication schedule (round-4 VERDICT item 3):

    * ``"auto"`` (default) — resolves from statics only
      (:func:`_choose_ustat_comm`, identical under jit): the ring when
      it keeps the sort-free kernel route open or when the gathered
      pack would exceed ~1 GB, the gather otherwise.
    * ``"gather"`` — ONE tiled all-gather materializes the full
      ``(C, P·cap)`` pack on every device, then one counting pass.
      Simplest program; peak memory and the counting table grow with P.
    * ``"ring"`` — each device sorts only its OWN ``(C, cap)`` chunk and
      the chunks rotate around the mesh axis via ``lax.ppermute``; every
      step counts local queries against the resident chunk while the
      next chunk is in flight.  Pair counts are additive over disjoint
      table chunks, so the result is the same exact integer counts —
      with O(C·cap) peak memory instead of O(C·P·cap) (constant in P:
      at C=1000, cap=256, P=256 the gathered pack is ~262 MB, a ring
      chunk ~1 MB), compute overlapping communication, and the Pallas
      kernel's Mosaic width envelope applying to the CHUNK width, so the
      kernel route stays open at P× larger caps.
    """
    from torcheval_tpu.metrics.functional.classification.auroc import (
        _multiclass_auroc_param_check,
    )

    _multiclass_auroc_param_check(num_classes, average)
    if comm not in ("auto", "gather", "ring"):
        raise ValueError(
            f"comm should be 'auto', 'gather' or 'ring', got {comm!r}."
        )
    comm = _resolve_multi_axis_comm(comm, axis)
    if scores.ndim != 2 or targets.ndim != 1:
        raise ValueError(
            "scores should be (N, C) and targets (N,), got "
            f"{scores.shape} / {targets.shape}."
        )
    if scores.shape[1] != num_classes:
        raise ValueError(
            f"scores should have {num_classes} columns, got {scores.shape}."
        )
    size = _axis_size(mesh, axis)
    if scores.shape[0] % size != 0:
        raise ValueError(
            f"sample count {scores.shape[0]} must divide evenly over mesh "
            f"axis {axis!r} of size {size}."
        )
    n_local = scores.shape[0] // size
    if (
        max_class_count_per_shard is None
        and all_concrete(scores, targets)
        and value_checks_enabled()
        and scores.size
    ):
        # The common default path: finite check + kernel-gate stats + cap
        # autotune (round-2 VERDICT item 6) in ONE fused round trip.
        cap, known_stats = _eager_ustat_decision(
            scores, targets, num_classes, size
        )
        _raise_if_not_finite(
            known_stats[0], known_stats[1], "sharded_multiclass_auroc_ustat"
        )
    elif max_class_count_per_shard is None and all_concrete(scores, targets):
        # skip_value_checks (or empty input): autotune alone.
        known_stats = None
        most = int(
            _max_shard_class_count(targets, num_classes=num_classes, world=size)
        )
        cap = min(n_local, -(-max(most, 1) // 64) * 64)
    else:
        if max_class_count_per_shard is None and not all_concrete(
            scores, targets
        ):
            # ONLY the multiclass wrapper autotunes; under tracing the
            # autotune cannot peek at values and the pack silently widens
            # to the full shard — O(N·C) wire instead of ~O(#positives).
            # Loud, once per callsite (round-3 VERDICT weak item 5).
            from torcheval_tpu.routing import warn_route_downgrade

            warn_route_downgrade(
                "ustat-cap-autotune",
                "sharded_multiclass_auroc_ustat's cap autotune cannot "
                "run under jit (inputs are tracers); packing the full "
                f"shard ({n_local} rows) — O(N·C) wire instead of "
                "~O(#positives).  Measure the cap eagerly once (e.g. "
                "parallel.exact.eager_ustat_pin) and pass "
                "max_class_count_per_shard= explicitly.",
            )
        known_stats = _check_finite_scores(
            scores, "sharded_multiclass_auroc_ustat"
        )
        cap = _resolve_ustat_cap(
            max_class_count_per_shard,
            n_local,
            scores,
            targets,
            lambda: _max_shard_class_count(
                targets, num_classes=num_classes, world=size
            ),
            "max_class_count_per_shard",
            "samples of one class",
        )
    if _kernel == "auto":
        if comm == "auto":
            comm = _choose_ustat_comm(
                num_classes, cap, size,
                ring_buys_kernel=_ring_buys_envelope(cap, size, n_local * size),
            )
        use_kernel = _mc_kernel_ok_for_schedule(
            scores, n_local * size, cap, size, known_stats, comm
        )
    else:
        use_kernel = _kernel == "pallas"
        if comm == "auto":
            # SAME static resolution as the auto-kernel branch — a
            # pinned-kernel caller following the eager_ustat_pin recipe
            # must land on the schedule the pin assumed.
            comm = _choose_ustat_comm(
                num_classes, cap, size,
                ring_buys_kernel=_ring_buys_envelope(cap, size, n_local * size),
            )
    fn = _compiled(
        _build_mc_ustat,
        (
            num_classes,
            average,
            cap,
            use_kernel,
            comm,
            _interpret,
            bool(jax.config.jax_enable_x64),
        ),
        mesh,
        axis,
    )
    return fn(scores, targets)


# Above this gathered-pack size the auto schedule prefers the ring: the
# materialized (C, P·cap) f32 pack would claim a serious slice of a v5e's
# 16 GB HBM (and at pod scale simply not fit), while a ring chunk stays
# O(C·cap).  1 GB leaves the compute arrays room; callers with tighter
# budgets pass comm="ring" explicitly.
_RING_PACK_BYTES = 1 << 30


def _mc_kernel_ok_for_schedule(
    scores, n_total: int, cap: int, size: int, known_stats, schedule: str
) -> bool:
    """:func:`_mc_ustat_kernel_ok` evaluated for one schedule — THE
    single definition of how the ring changes the gate (padded-chunk
    int32 total; per-chunk Mosaic envelope).  Shared by the wrapper,
    :func:`eager_ustat_pin`, and ``routing.explain_route`` so the three
    surfaces cannot drift apart again."""
    from torcheval_tpu.ops.pallas_ustat import _pad_to

    ring = schedule == "ring"
    return _mc_ustat_kernel_ok(
        scores,
        n_total,
        (_pad_to(cap, 16) if ring else cap) * size,
        known_stats,
        env_cap=_pad_to(cap, 16) if ring else None,
    )


def _ring_buys_envelope(cap: int, size: int, n_total: int) -> bool:
    """True when the Pallas rank-sum table ENVELOPE admits a ring chunk
    but not the gathered table — a pure function of statics (backend,
    kill-switch flags, cap, P, N), deliberately EXCLUDING the
    value-dependent score-domain gate: every surface that resolves
    ``comm="auto"`` (the wrapper's auto and pinned-kernel branches,
    ``eager_ustat_pin``, ``explain_route``) must reach the same schedule,
    including under a caller's jit where values are unreadable.  The
    score-domain gate then only decides kernel-vs-searchsorted GIVEN the
    schedule — identically for both."""
    from torcheval_tpu.ops._flags import pallas_disabled, ustat_disabled
    from torcheval_tpu.ops.pallas_ustat import _MAX_CAP, _pad_to

    if pallas_disabled() or ustat_disabled() or jax.default_backend() != "tpu":
        return False
    ring_cap = _pad_to(cap, 16)
    if ring_cap * size * n_total >= 2**29:  # int32 bound fails either way
        return False
    return ring_cap <= _MAX_CAP < _pad_to(cap * size, 16)


def _choose_ustat_comm(
    num_rows: int, cap: int, size: int, ring_buys_kernel: bool = False
) -> str:
    """Resolve ``comm="auto"`` from STATICS only (shape-derived, so the
    decision is identical under a caller's jit): ring when it keeps the
    sort-free kernel route open (``ring_buys_kernel`` — pass
    :func:`_ring_buys_envelope`) or when the gathered pack would be
    prohibitively large; gather otherwise (its single collective is the
    simpler program, and the ring's searchsorted fallback re-sorts the
    query side P times)."""
    from torcheval_tpu.ops.pallas_ustat import _pad_to

    if ring_buys_kernel:
        return "ring"
    pack_bytes = 4 * num_rows * _pad_to(cap, 16) * size
    return "ring" if pack_bytes > _RING_PACK_BYTES else "gather"


def _mc_ustat_kernel_ok(
    scores,
    n_total: int,
    cap_tot: int,
    known_stats: Optional[Tuple[float, float, float]],
    env_cap: Optional[int] = None,
) -> bool:
    """Call-time gate for the Pallas rank-sum local-count formulation of
    the sharded multiclass ustat (vs the vmapped variadic-searchsorted
    pair, which sorts (C, P·cap + n_local) twice — the very sort this
    family exists to avoid).  Mirrors the single-device route guards:
    TPU backend, kill-switches honored per call, concrete values, scores
    strictly inside the ±3e38 pad sentinels and outside the bf16-split
    subnormal region (|score| ≥ 2^-100 or zero), and the int32 exactness
    bound — the psum'd global rank sums are ≤ N·cap_tot, so
    ``cap_tot · N < 2^29`` keeps every term of the U identity exact.
    ``known_stats`` reuses the finite-check's fetched (min, max, min
    nonzero |score|) so the common path costs no extra device round
    trip."""
    from torcheval_tpu.ops._flags import pallas_disabled, ustat_disabled
    from torcheval_tpu.ops.pallas_ustat import (
        _BIG,
        _MAX_CAP,
        _MIN_SPLIT,
        _pad_to,
    )

    if pallas_disabled() or ustat_disabled() or jax.default_backend() != "tpu":
        return False
    if not all_concrete(scores) or scores.size == 0:
        # The stats fetch requires non-empty (jnp.min of empty raises);
        # the searchsorted path handles the degenerate 0-sample case.
        return False
    # The kernel pads the table width to a multiple of 16; the padded
    # width each kernel call SEES (the full gathered table, or one ring
    # chunk — ``env_cap``) must stay inside the hardware-verified Mosaic
    # envelope (pallas_ustat._mosaic_tile) or the compiled kernel ICEs.
    # The int32-exactness bound is on the GLOBAL accumulated rank sums
    # either way.
    env = env_cap if env_cap is not None else _pad_to(cap_tot, 16)
    if env > _MAX_CAP or cap_tot * n_total >= 2**29:
        return False
    if known_stats is None:
        if not value_checks_enabled():
            # skip_value_checks keeps this path fully async (no host
            # sync) — but the kernel's score-domain preconditions
            # (|s| < 3e38, no nonzero magnitudes under 2^-100) can then
            # not be verified, so the SAFE searchsorted formulation runs
            # (exact for all finite scores).  Callers who assert the
            # domain themselves can force the kernel with
            # ``_kernel="pallas"``.
            return False
        known_stats = tuple(
            float(x) for x in np.asarray(_finite_gate_stats(scores))
        )
    lo, hi, min_nz = known_stats
    return -_BIG < lo and hi < _BIG and min_nz >= _MIN_SPLIT


def _build_mc_ustat(statics, mesh: Mesh, axis: str):
    num_classes, average, cap, use_kernel, comm, interpret, _x64 = statics
    acc = _accum_dtype()
    size = _axis_size(mesh, axis)

    def local(s, t):
        s = s.astype(_work_dtype(s.dtype))
        classes = jnp.arange(num_classes, dtype=t.dtype)
        is_class = t[None, :] == classes[:, None]  # (C, n_local)
        # Pack each class's positive scores, largest first, -inf pads; the
        # slice keeps the cap largest (only lossy on overflow, see above).
        packed = -jnp.sort(
            jnp.where(is_class, -s.T, jnp.inf), axis=-1
        )[:, :cap]
        n_pos = lax.psum(jnp.sum(is_class, axis=1, dtype=jnp.int32), axis)
        n_total = s.shape[0] * size
        if comm == "ring":
            if use_kernel:
                aurocs = _mc_ustat_kernel_counts_ring(
                    s, packed, n_pos, n_total, axis, interpret, size
                )
            else:
                aurocs = _mc_ustat_searchsorted_counts_ring(
                    s, packed, is_class, n_pos, n_total, axis, acc, size
                )
        else:
            gathered = lax.all_gather(packed, axis, axis=1, tiled=True)
            if use_kernel:
                aurocs = _mc_ustat_kernel_counts(
                    s, gathered, n_pos, n_total, axis, interpret
                )
            else:
                aurocs = _mc_ustat_searchsorted_counts(
                    s, gathered, is_class, n_pos, n_total, axis, acc
                )
        return aurocs.mean() if average == "macro" else aurocs

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(PartitionSpec(axis), PartitionSpec(axis)),
            out_specs=PartitionSpec(),
            check_vma=False,
        )
    )


def _searchsorted_above_ties(rows, queries):
    """Per-(class, query) exact int32 ``(#entries > q, #entries == q)``
    against ascending rows with ``-inf`` pads (pads cancel: never
    ``> q``, and they land in both sides of the tie difference).
    method="sort" turns the 65M-query binary search into one variadic
    sort per class — measured ~35x the gather-based 'scan' lowering on
    v5e at the (2^16, 1000) north-star shape.  Integer returns so ring
    accumulation stays exact past f32's 2^24 integer ceiling."""
    lo = jax.vmap(
        lambda r, q: jnp.searchsorted(r, q, side="left", method="sort")
    )(rows, queries)
    hi = jax.vmap(
        lambda r, q: jnp.searchsorted(r, q, side="right", method="sort")
    )(rows, queries)
    return rows.shape[-1] - hi, hi - lo


def _auroc_from_u(is_class, above, ties, n_pos, n_total: int, axis: str, acc):
    """Shared searchsorted epilogue (gather and ring schedules): mask
    same-class queries, psum the U contributions, divide by the pair
    count; degenerate classes → 0.5.  ``above``/``ties`` arrive as exact
    integers and take their ONE rounding to ``acc`` here — the same
    single cast on both schedules."""
    contrib = jnp.where(
        is_class, 0.0, above.astype(acc) + 0.5 * ties.astype(acc)
    )
    u = lax.psum(jnp.sum(contrib, axis=1, dtype=acc), axis)
    n_posf = n_pos.astype(acc)
    factor = n_posf * (n_total - n_posf)
    return jnp.where(
        factor == 0, jnp.asarray(0.5, acc), u / factor
    ).astype(jnp.float32)


def _mc_ustat_searchsorted_counts(
    s, gathered, is_class, n_pos, n_total: int, axis: str, acc
):
    """Local pair counts via the vmapped variadic-searchsorted pair — the
    portable formulation (any backend, any score magnitude, no int32
    bound; float ``acc`` accumulation)."""
    rows = jnp.sort(gathered, axis=-1)  # (C, P·cap) asc, -inf pads first
    above, ties = _searchsorted_above_ties(rows, s.T)
    return _auroc_from_u(is_class, above, ties, n_pos, n_total, axis, acc)


def _mc_ustat_kernel_counts(
    s, gathered, n_pos, n_total: int, axis: str, interpret: bool
):
    """Local pair counts via the Pallas rank-sum kernel
    (``ops/pallas_ustat.rank_sum_counts``) — the sort-free TPU
    formulation.  The single-device U identity lifts to the pod level
    because the psum makes the query multiset global: with K_A/K_B the
    psum-merged strict/non-strict rank sums of ALL samples against the
    global per-class table (width ``cap_tot`` incl. pads),

        2·U_c = 2·n_c·N − K_A − N·cap_tot + K_B − n_c²

    — the same algebra as ``ops/pallas_ustat._auroc_from_rank_sums``,
    exact in int32 under the route's ``cap_tot · N < 2^29`` bound.
    Unlike the searchsorted path there is no same-class mask: summing
    over ordered same-class pairs is the closed form n_c²/2 (globally),
    which the identity subtracts."""
    from torcheval_tpu.ops.pallas_ustat import rank_sum_counts

    rows = _ustat_kernel_table(gathered)
    cap_tot = rows.shape[-1]
    # ONE stacked kernel call + ONE psum for both passes (the
    # _auroc_from_rank_sums pattern: rows [0, C) non-strict, [C, 2C)
    # negated strict).
    c = rows.shape[0]
    k = lax.psum(
        rank_sum_counts(
            jnp.concatenate([s.T, -s.T], axis=0),
            jnp.concatenate([rows, -rows[:, ::-1]], axis=0),
            interpret=interpret,
        ),
        axis,
    )
    return _auroc_from_pod_rank_sums(k, c, n_pos, n_total, cap_tot)


def _ustat_kernel_table(packed):
    """Ascending rows with +BIG pads (the rank-sum kernel's table
    contract), width padded to a multiple of 16 — extra pad columns are
    inert, the identity's ``cap_tot`` term accounts for all pads
    uniformly.  Shared by the gathered table and each ring chunk."""
    from torcheval_tpu.ops.pallas_ustat import _BIG

    rows = jnp.sort(
        jnp.where(jnp.isinf(packed), jnp.float32(_BIG), packed), axis=-1
    )
    pad = (-rows.shape[-1]) % 16
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad)), constant_values=_BIG)
    return rows


def _auroc_from_pod_rank_sums(k, c: int, n_pos, n_total: int, cap_tot: int):
    """Shared rank-sum epilogue (gather and ring schedules): the pod U
    identity 2·U = 2·n_c·N − K_A − N·cap_tot + K_B − n_c² (see
    :func:`_mc_ustat_kernel_counts`); degenerate classes → 0.5.
    ``cap_tot`` is the total table width the accumulated ``k`` counted
    against, INCLUDING every pad column."""
    k_a, k_b = k[:c], k[c:]
    two_u = 2 * n_pos * n_total - k_a - n_total * cap_tot + k_b - n_pos * n_pos
    n_posf = n_pos.astype(jnp.float32)
    factor = n_posf * (jnp.float32(n_total) - n_posf)
    return jnp.where(
        factor == 0,
        jnp.float32(0.5),
        two_u.astype(jnp.float32) / (2.0 * factor),
    )


def _mc_ustat_kernel_counts_ring(
    s, packed, n_pos, n_total: int, axis: str, interpret: bool, size: int
):
    """Ring-overlap variant of :func:`_mc_ustat_kernel_counts`: each
    device sorts only its OWN ``(C, cap)`` chunk, the chunks rotate via
    ``lax.ppermute``, and each ring step counts the local queries against
    the resident chunk while the next is in flight.  Exactness: the
    strict/non-strict rank counts are ADDITIVE over disjoint table
    chunks, every per-chunk count is exact int32 (the kernel contract),
    and the identity's ``cap_tot`` is the sum of padded chunk widths —
    so the accumulated sums are bit-identical to the gathered table's
    (both count the same global multiset; int32 addition is exact under
    the route's ``cap_tot·N < 2^29`` bound)."""
    from torcheval_tpu.ops.pallas_ustat import rank_sum_counts

    rows = _ustat_kernel_table(packed)  # sorted ONCE; sortedness is
    cap_tot = rows.shape[-1] * size  # invariant under the rotation
    c = rows.shape[0]
    queries = jnp.concatenate([s.T, -s.T], axis=0)
    perm = [(j, (j + 1) % size) for j in range(size)]

    def count(chunk, k_acc):
        table = jnp.concatenate([chunk, -chunk[:, ::-1]], axis=0)
        return k_acc + rank_sum_counts(queries, table, interpret=interpret)

    def body(_, carry):
        chunk, k_acc = carry
        k_acc = count(chunk, k_acc)
        return lax.ppermute(chunk, axis, perm=perm), k_acc

    # size-1 rotations; the last chunk is counted in place (a final
    # rotation home would be wasted wire — the psum is placement-
    # agnostic).
    chunk, k_local = lax.fori_loop(
        0, size - 1, body, (rows, jnp.zeros((2 * c,), jnp.int32))
    )
    k_local = count(chunk, k_local)
    return _auroc_from_pod_rank_sums(
        lax.psum(k_local, axis), c, n_pos, n_total, cap_tot
    )


def _mc_ustat_searchsorted_counts_ring(
    s, packed, is_class, n_pos, n_total: int, axis: str, acc, size: int
):
    """Ring-overlap variant of :func:`_mc_ustat_searchsorted_counts`
    (portable formulation).  Per-chunk ``above``/``ties`` are additive
    over disjoint chunks; the cost is one variadic sort of
    ``(cap + n_local)`` per class per ring step — P× the query-side sort
    work of the gathered formulation, the price of O(C·cap) memory
    (document: prefer ``comm="ring"`` with the kernel route, where
    compute is flat in P)."""
    queries = s.T  # (C, n_local)
    perm = [(j, (j + 1) % size) for j in range(size)]
    zeros = jnp.zeros(queries.shape, jnp.int32)
    # Sort the chunk ONCE before the loop — sortedness is invariant under
    # the rotation, so every received chunk arrives pre-sorted.
    rows0 = jnp.sort(packed, axis=-1)  # asc, -inf pads first

    def count(chunk, above, ties):
        d_above, d_ties = _searchsorted_above_ties(chunk, queries)
        return above + d_above, ties + d_ties

    def body(_, carry):
        chunk, above, ties = carry
        above, ties = count(chunk, above, ties)
        return lax.ppermute(chunk, axis, perm=perm), above, ties

    # size-1 rotations; final chunk counted in place (see the kernel
    # ring variant).
    chunk, above, ties = lax.fori_loop(
        0, size - 1, body, (rows0, zeros, zeros)
    )
    above, ties = count(chunk, above, ties)
    return _auroc_from_u(is_class, above, ties, n_pos, n_total, axis, acc)


def _eager_ustat_decision(scores, targets, num_classes: int, world: int):
    """The multiclass pod-ustat wrapper's eager default decision — cap
    autotune + kernel-gate stats in ONE fused device round trip.  Returns
    ``(cap, (lo, hi, min_nz))``.  Rounding the cap to a multiple of 64
    keeps the compile-shape set small; it never overflows — the cap
    upper-bounds the true per-shard maximum by construction.  ONE
    definition serves the wrapper, :func:`eager_ustat_pin`, and the
    benchmark clock, so retunes cannot desynchronize them."""
    n_local = scores.shape[0] // world
    out = _mc_ustat_wrapper_stats(
        scores, targets, num_classes=num_classes, world=world
    )
    if isinstance(out, jax.core.Tracer):
        # Inside someone else's trace even ops on concrete arrays stage
        # to tracers (the _host_checks.bounds fallback pattern): compute
        # the same stats in pure numpy on the host values.
        host_s = np.asarray(scores)
        host_t = np.asarray(targets).reshape(world, -1)
        lo, hi = float(host_s.min()), float(host_s.max())
        mag = np.abs(host_s)
        nz = mag[mag > 0]
        min_nz = float(nz.min()) if nz.size else float("inf")
        most = max(
            int(np.bincount(row, minlength=num_classes).max())
            for row in host_t
        )
    else:
        lo, hi, min_nz, most_hi, most_lo = (
            float(x) for x in np.asarray(out)
        )
        most = int(most_hi) * 65536 + int(most_lo)
    cap = min(n_local, -(-max(most, 1) // 64) * 64)
    return cap, (lo, hi, min_nz)


def eager_ustat_pin(
    scores,
    targets,
    num_classes: int,
    world: int,
    comm: str = "auto",
    axis: AxisSpec = "dp",
):
    """Decide the pod ustat's ``(cap, kernel)`` pin EAGERLY on concrete
    data — the same decision :func:`sharded_multiclass_auroc_ustat` makes
    for its concrete defaults, exposed so jitted callers (whose traced
    autotune would silently pack the full shard) and the benchmark clock
    can pin it.  Returns ``(cap, kernel)`` with ``kernel`` one of
    ``"pallas"`` / ``"searchsorted"`` — pass them as
    ``max_class_count_per_shard=`` and ``_kernel=``.  ``comm`` must match
    the schedule of the pinned call; ``"auto"``, the shared default,
    resolves identically here and in the wrapper — in BOTH of the
    wrapper's kernel branches, and under a caller's jit — because the
    policy is a pure function of statics
    (:func:`_ring_buys_envelope` + pack size; no value-dependent gate).
    Under ``"ring"`` the Mosaic width envelope applies per chunk, so
    caps whose GATHERED table is too wide for the kernel can still pin
    ``"pallas"``.  Pass the pinned call's ``axis`` too when it is a
    TUPLE of mesh axes — multi-axis sharding has no ring, so the pin
    must gate under the gather envelope the wrapper will actually
    use."""
    cap, known_stats = _eager_ustat_decision(
        scores, targets, num_classes, world
    )
    comm = _resolve_multi_axis_comm(comm, axis)
    if comm == "auto":
        comm = _choose_ustat_comm(
            num_classes, cap, world,
            ring_buys_kernel=_ring_buys_envelope(
                cap, world, scores.shape[0]
            ),
        )
    ok = _mc_kernel_ok_for_schedule(
        scores, scores.shape[0], cap, world, known_stats, comm
    )
    return cap, ("pallas" if ok else "searchsorted")


@partial(jax.jit, static_argnames=("num_classes", "world"))
def _mc_ustat_wrapper_stats(scores, targets, num_classes: int, world: int):
    """The multiclass ustat wrapper's ENTIRE host-fetch budget in one
    fused kernel (composing :func:`_finite_gate_stats_body` and
    :func:`_max_shard_class_count_body`): score min / max / smallest
    nonzero magnitude (finite check + Pallas-kernel gate) and the
    per-shard class-count maximum (cap autotune).  Separate fetches cost
    one tunnel round trip each (~70 ms) — fusing them cut the
    (2^16, 1000) lifecycle measurably.  The count rides TWO f32 lanes
    (high/low 16 bits) so it stays exact past f32's 2^24 integer ceiling
    — it feeds the never-overflows cap bound."""
    most = _max_shard_class_count_body(targets, num_classes, world)
    return jnp.stack(
        _finite_gate_stats_body(scores)
        + [
            (most // 65536).astype(jnp.float32),
            (most % 65536).astype(jnp.float32),
        ]
    )


def _max_shard_class_count_body(targets, num_classes: int, world: int):
    """Largest per-shard single-class sample count (exact int32), shared
    by the standalone and fused-wrapper fetch kernels."""
    shards = jnp.reshape(targets, (world, -1))
    classes = jnp.arange(num_classes)
    counts = jnp.sum(
        shards[:, :, None] == classes[None, None, :],
        axis=1,
        dtype=jnp.int32,
    )
    return counts.max()


@partial(jax.jit, static_argnames=("num_classes", "world"))
def _max_shard_class_count(targets, num_classes: int, world: int):
    """One fused round trip of :func:`_max_shard_class_count_body`."""
    return _max_shard_class_count_body(targets, num_classes, world)


@partial(jax.jit, static_argnames=("world",))
def _max_shard_positive_count(targets, world: int):
    """Largest per-shard positive-sample count (one fused round trip)."""
    shards = jnp.reshape(targets == 1, (world, -1))
    return jnp.sum(shards, axis=1, dtype=jnp.int32).max()


@partial(jax.jit, static_argnames=("world",))
def _max_shard_minority_count(targets, world: int):
    """Largest per-shard count of the *globally* rarer binary class (one
    fused round trip)."""
    shards = jnp.reshape(targets != 0, (world, -1))
    pos = jnp.sum(shards, axis=1, dtype=jnp.int32)
    neg = shards.shape[1] - pos
    pick_pos = pos.sum() * 2 <= shards.size
    return jnp.where(pick_pos, pos.max(), neg.max())
