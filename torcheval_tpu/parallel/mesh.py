"""Mesh construction and sharding helpers.

The mesh is the TPU-native replacement for the reference's process group
(reference ``PGWrapper``, ``toolkit.py:16``): a named axis over the devices
that collectives reduce along.  A 1-D ``("dp",)`` mesh is the data-parallel
analog of the reference's world; a 2-D ``("dp", "sp")`` mesh additionally
shards the *sample* dimension of buffer-state metrics (AUROC / PR-curve
score buffers) — the scaling axis this library actually has (SURVEY §5).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


# A sharded entry point's sample axis: one mesh-axis name, or a tuple of
# names when samples are sharded jointly over several (e.g. dp×sp).
AxisSpec = Union[str, Tuple[str, ...]]


def _axis_size(mesh: Mesh, axis: AxisSpec) -> int:
    """Total device count along ``axis`` — a single mesh-axis name or a
    tuple of names (samples sharded jointly over e.g. ``("dp", "sp")``;
    every collective in the sharded families accepts the tuple form
    directly)."""
    if isinstance(axis, str):
        return mesh.shape[axis]
    size = 1
    for a in axis:
        size *= mesh.shape[a]
    return size


def device_count() -> int:
    """Global device count (addressable by this controller's program — the
    pod size under multi-host SPMD, which is what mesh shapes are sized by).
    Use ``jax.local_device_count()`` for the per-host count."""
    return len(jax.devices())


def make_mesh(
    shape: Union[int, Sequence[int], None] = None,
    axis_names: Tuple[str, ...] = ("dp",),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named device mesh.

    ``shape`` may be an int (1-D mesh over the first N devices), a tuple
    (multi-D mesh), or ``None`` (all devices on a 1-D mesh).  Device order
    follows ``jax.devices()`` so a 1-D axis rides the ICI ring on real
    hardware.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    if shape is None:
        shape = (len(devs),) if len(axis_names) == 1 else None
    if shape is None:
        raise ValueError("shape is required for a multi-axis mesh")
    if isinstance(shape, int):
        shape = (shape,)
    shape = tuple(shape)
    if len(shape) != len(axis_names):
        raise ValueError(f"shape {shape} does not match axis_names {axis_names}")
    n = int(np.prod(shape))
    if n > len(devs):
        raise ValueError(f"mesh of {n} devices requested, {len(devs)} available")
    grid = np.asarray(devs[:n], dtype=object).reshape(shape)
    return Mesh(grid, axis_names)


def shard_batch(
    mesh: Mesh,
    *arrays: jax.Array,
    axis: AxisSpec = "dp",
    dim: int = 0,
) -> Union[jax.Array, Tuple[jax.Array, ...]]:
    """Place arrays with dimension ``dim`` sharded over mesh axis ``axis``
    (a name, or a tuple of names to shard one dimension jointly over
    several mesh axes — e.g. ``axis=("dp", "sp")`` on a 2-D mesh).

    The sharded batch is the SPMD analog of the reference's per-rank data
    shard (reference ``metric_class_tester.py:301-326`` deals update batches
    to ranks); here a single logical array spans the mesh.
    """
    out = []
    for a in arrays:
        d = dim if dim >= 0 else dim + a.ndim
        if not 0 <= d < a.ndim:
            raise ValueError(f"dim {dim} out of range for array of rank {a.ndim}")
        spec = [None] * (d + 1)
        spec[d] = axis
        out.append(jax.device_put(a, NamedSharding(mesh, PartitionSpec(*spec))))
    return out[0] if len(out) == 1 else tuple(out)


def replicate(mesh: Mesh, tree):
    """Replicate every array leaf of ``tree`` across the whole mesh."""
    sharding = NamedSharding(mesh, PartitionSpec())
    return jax.tree.map(lambda a: jax.device_put(a, sharding), tree)


def bucket_shard_batch(
    mesh: Mesh,
    *arrays: jax.Array,
    axis: AxisSpec = "dp",
    min_bucket: Optional[int] = None,
    mask=None,
):
    """:func:`shard_batch` for ragged streams: pad the shared leading dim
    up to a power-of-two bucket that is also a multiple of the axis'
    device count (so the shard divides evenly), shard the padded arrays,
    and return them with the replicated validity mask to pass to
    mask-aware sharded entry points or a bucketed ``MetricCollection``.

    Returns ``(sharded_arrays_tuple, mask)`` — ``mask`` sharded like the
    batch, 1 for real rows, 0 for padding.  With M distinct batch sizes
    in the stream, the downstream sharded programs compile
    O(log max_batch) times instead of M (see ``metrics/_bucket.py``).
    """
    from torcheval_tpu.metrics._bucket import DEFAULT_MIN_BUCKET, pad_to_bucket

    padded, out_mask = pad_to_bucket(
        *arrays,
        mask=mask,
        min_bucket=DEFAULT_MIN_BUCKET if min_bucket is None else min_bucket,
        multiple_of=_axis_size(mesh, axis),
    )
    sharded = shard_batch(mesh, *padded, axis=axis)
    if len(padded) == 1:
        sharded = (sharded,)
    return sharded, shard_batch(mesh, out_mask, axis=axis)
