"""Elastic hierarchical fleet merge: tree/ring reduction with per-level
retry, live membership, and sketch-compressed payloads.

The flat sync path (``toolkit._sync_metric_object``) is one
world-sized gather into the destination rank: every host's state lands
in one inbox in one step, and one unresponsive host stalls (or kills)
the whole collective.  This module reduces the same state
**hierarchically** over the point-to-point API any
:class:`~torcheval_tpu.distributed.CollectiveGroup` with
``supports_p2p`` offers, with three properties the flat path lacks:

* **Bounded fan-in** — ``topology="tree"`` reduces over a fixed binary
  heap tree rooted at ``dst`` (position ``(rank - dst) % world``,
  parent ``(pos - 1) // 2``): the root's inbox is 2 envelopes per
  round instead of ``world - 1``, and each of the O(log world) levels
  ships already-merged state.  ``topology="ring"`` is the 1-fanout
  chain variant (O(world) levels, minimal per-hop payload).
* **Per-level resilience** — every hop runs under its own
  :class:`~torcheval_tpu.resilience.retry.ResilientGroup` with a
  deadline scaled to the subtree depth beneath it.  A hop that
  exhausts its budget *excises* the peer in this rank's
  :class:`~torcheval_tpu.resilience.membership.MembershipView` (one
  ``degraded`` telemetry event carrying the surviving-rank set) and the
  protocol routes around it: an orphaned child re-sends its envelope to
  its grandparent (climbing further dead ancestors), and a parent that
  excised a child polls re-parent tags for that child's whole subtree
  — and the excised child's own late envelope — during a bounded grace
  window, so a mid-tree death loses at most the dead host's own
  contribution and a merely-slow host loses nothing.  A rank mid
  failure-recovery keepalives its ancestor chain (relayed level by
  level), extending the linear recv deadlines above it so the
  exponential recovery window beneath a live node never cascades into
  false excisions of live subtrees.  The final result is labelled **partial**
  (``world_effective = len(contributors) < world_size``) instead of the
  run dying — no failure propagates past the root as an exception.
* **O(bins) payloads** — ``sketch="reservoir" | "histogram" | "count"
  | "rank"`` ships :mod:`torcheval_tpu.metrics._sketch` summaries
  instead of raw sample buffers (``"rank"`` wraps a sketch-mode curve
  metric's device-resident compactor counts directly — integer-add
  merges, bit-identical at every world size and topology); their
  merges are commutative/associative so tree order cannot change the
  result, and their error bounds are documented per kind.  ``sketch=None`` ships whole per-rank prepared states keyed
  by rank, reassembled in rank order at the root — bit-identical to the
  flat gather-and-merge on a clean run.

Heartbeats ride the merge itself: every envelope and ack refreshes the
sender in the receiver's membership view and carries the sender's
dead-rank gossip, so discoveries propagate without extra traffic.

Chaos hooks: the ``merge.level`` fault site fires at every
participation step with ``rank``/``level``/``round``/``topology``/
``role`` context; ``action="drop_rank"`` makes the matched rank vanish
mid-merge, ``action="slow_rank"`` makes it a straggler.  Telemetry:
each hop emits a ``sync`` event with ``level``/``fanout``/
``payload_bytes`` (the ``merge_level_seconds`` Prometheus family and
the fleet report's merge-depth table are views over these).

Front door: ``toolkit.sync_and_compute(metric, group,
topology="tree", sketch=...)``; the engine overlap hook is
``Evaluator.start_fleet_merge``.  See ``docs/source/fleet.rst`` for
topology selection and the host-loss runbook.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, List, Optional, Set, Tuple

from torcheval_tpu.distributed import CollectiveGroup, PeerTimeoutError
from torcheval_tpu.resilience import faults as _faults
from torcheval_tpu.resilience.faults import DroppedRank
from torcheval_tpu.resilience.membership import (
    MembershipView,
    resolve_membership,
)
from torcheval_tpu.resilience.retry import (
    CollectiveTimeoutError,
    ResilientGroup,
    RetryPolicy,
)
from torcheval_tpu.telemetry import events as _telemetry
from torcheval_tpu.telemetry import trace as _trace

TOPOLOGIES = ("flat", "tree", "ring")
_FAULT_SITE = "merge.level"


@dataclass(frozen=True)
class MergePolicy:
    """Budgets for one hierarchical merge round.

    ``level_deadline`` is the per-level unit budget: a hop expecting a
    subtree of height ``h`` beneath the sender waits up to
    ``h * level_deadline``.  ``attempts`` retries within each hop's
    budget (the per-level ResilientGroup's ``max_attempts``).
    ``ack_deadline`` bounds the wait for a receipt acknowledgement
    before the sender declares its parent dead and re-parents;
    ``reparent_grace`` bounds how long an ancestor polls for orphans of
    an excised child; ``result_deadline`` bounds a non-root rank's wait
    for the root's result under ``recipient="all"`` (defaults scale
    from ``level_deadline``).  ``poll_slice`` is the orphan-poll /
    ring-scan granularity.

    A rank mid failure-recovery (orphan-polling for an excised child's
    subtree) sends **keepalives** up its live ancestor chain every
    :meth:`keepalive_interval`, and each ancestor extends its recv
    deadline on one — so a live node slowed by recovery beneath it is
    never excised by a parent whose own (linear) recv deadline is
    shorter than the (exponential) recovery window.  ``poll_window_max``
    is the absolute cap on any single orphan-poll window: the computed
    :meth:`poll_window` is exponential in the dead subtree's height, so
    without a cap a tall dead subtree whose survivors already delivered
    through the dead node (and so never re-parent) would be waited on
    for minutes; the poll also exits early once every pending orphan is
    accounted for or nothing has arrived for the no-progress bound (see
    :func:`_poll_orphans`).  ``None`` disables the cap."""

    level_deadline: float = 2.0
    attempts: int = 2
    ack_deadline: Optional[float] = None
    reparent_grace: Optional[float] = None
    result_deadline: Optional[float] = None
    poll_slice: float = 0.02
    poll_window_max: Optional[float] = 60.0

    def __post_init__(self) -> None:
        if self.level_deadline <= 0:
            raise ValueError(
                f"level_deadline must be positive, got {self.level_deadline}"
            )
        if self.attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {self.attempts}")

    def ack(self) -> float:
        return (
            self.ack_deadline
            if self.ack_deadline is not None
            else self.level_deadline
        )

    def grace(self) -> float:
        return (
            self.reparent_grace
            if self.reparent_grace is not None
            else self.level_deadline
        )

    def result(self, levels: int) -> float:
        return (
            self.result_deadline
            if self.result_deadline is not None
            else self.level_deadline * (2 * levels + 2)
        )

    def ack_wait(self, target_height: int) -> float:
        """How long a sender waits for its (grand)parent's receipt.

        Two constraints pull in opposite directions: a *busy* target may
        legitimately spend its dead sibling subtree's full recv deadline
        plus the orphan-poll grace before acking (so the wait must grow
        with the target's subtree height), while a *dead* target must be
        detected before the next live ancestor's orphan-poll window
        closes.  Exponential scaling in the target height satisfies
        both: the sum of detection times over any chain of dead
        ancestors below height ``h`` stays under :meth:`poll_window`
        of ``h`` (geometric series)."""
        unit = self.ack() + self.grace()
        return 1.5 * unit * (2 ** max(0, target_height - 1))

    def poll_window(self, dead_child_height: int) -> float:
        """How long an ancestor polls re-parent tags after excising a
        child of the given subtree height: covers every descendant's
        worst-case chain of dead-ancestor detections
        (``sum ack_wait(i) for i <= h`` is under ``2 * unit * 2**h``).
        Call sites apply :meth:`capped_poll_window`."""
        unit = self.ack() + self.grace()
        return 2.0 * unit * (2 ** dead_child_height)

    def capped_poll_window(self, dead_child_height: int) -> float:
        window = self.poll_window(dead_child_height)
        if self.poll_window_max is not None:
            window = min(window, self.poll_window_max)
        return window

    def keepalive_interval(self) -> float:
        """Cadence of the mid-recovery progress signal; well under
        ``level_deadline`` so a parent's extended recv deadline never
        lapses between two keepalives from a live child."""
        return self.level_deadline / 4.0

    def recv_window(self, child_height: int) -> float:
        """Hard cap on a keepalive-extended child-envelope wait.  The
        base recv deadline stays ``level_deadline * child_height``
        (fast detection of a silent child); keepalives extend it while
        the child is visibly mid-recovery, up to this bound — the
        child's own recovery work is at most two excise-and-poll
        passes, so anything beyond is a wedged peer, excised as dead."""
        return (
            self.level_deadline * child_height
            + 4.0 * self.capped_poll_window(child_height)
            + 2.0 * (self.ack() + self.grace())
        )


@dataclass
class MergeOutcome:
    """What a fleet merge returns on every rank — never an exception.

    ``value`` is the computed metric value (on the recipient rank(s));
    ``metric`` is the reassembled merged metric (root, exact mode
    only).  ``partial`` is True when any initial rank's contribution is
    missing: ``world_effective = world_size - len(lost_ranks)``.
    ``delivered`` is False on a rank whose envelope never reached the
    root (partition) or that was fault-dropped (``dropped=True``)."""

    value: Any
    metric: Any
    world_size: int
    world_effective: int
    lost_ranks: Tuple[int, ...]
    partial: bool
    topology: str
    levels: int
    rank: int
    delivered: bool
    dropped: bool = False
    sketch: Optional[str] = None
    payload_bytes_at_root: int = 0
    overlap_skips: int = 0


@dataclass
class Envelope:
    """One hop's payload: merged state plus the membership piggyback.

    ``trace_id``/``span_id`` are the sender's causal-trace identity
    (empty when tracing is off — defaults keep the wire format
    compatible with untraced peers), riding the same piggyback channel
    as the dead-rank gossip: no extra round trips for cross-host trace
    assembly."""

    sender: int
    level: int
    contributors: FrozenSet[int]
    dead: FrozenSet[int]
    mode: str                                   # "exact" | "sketch"
    parts: Dict[int, Any] = field(default_factory=dict)
    part_bytes: Dict[int, int] = field(default_factory=dict)
    sketch: Optional[Any] = None
    trace_id: str = ""
    span_id: str = ""

    def payload_nbytes(self) -> int:
        if self.mode == "exact":
            return sum(self.part_bytes.values())
        return int(self.sketch.nbytes()) if self.sketch is not None else 0


class _Acc:
    """This rank's running reduction: per-rank parts (exact mode, keyed
    by rank so duplicate delivery dedups for free) or one commutative
    sketch (overlapping sketch envelopes are skipped and counted)."""

    def __init__(self, mode: str) -> None:
        self.mode = mode
        self.parts: Dict[int, Any] = {}
        self.part_bytes: Dict[int, int] = {}
        self.sketch: Optional[Any] = None
        self.contributors: Set[int] = set()
        self.overlap_skips = 0

    def add_local(
        self, rank: int, part: Any = None, nbytes: int = 0, sketch: Any = None
    ) -> None:
        if self.mode == "exact":
            self.parts[rank] = part
            self.part_bytes[rank] = nbytes
        else:
            self.sketch = sketch
        self.contributors.add(rank)

    def absorb(self, env: Envelope, view: MembershipView) -> bool:
        view.merge_gossip(env.dead, reason="gossip")
        view.observe(env.sender, level=env.level)
        if self.mode == "exact":
            for r, part in env.parts.items():
                if r not in self.parts:
                    self.parts[r] = part
                    self.part_bytes[r] = env.part_bytes.get(r, 0)
                    self.contributors.add(r)
            return True
        incoming = set(env.contributors)
        if incoming & self.contributors:
            # A duplicate or partially-overlapping sketch cannot be
            # subtracted; skip the whole envelope (its non-overlapping
            # contributors show up as lost, which partial accounting
            # surfaces honestly).
            if not incoming <= self.contributors:
                self.overlap_skips += 1
            return False
        if self.sketch is None:
            self.sketch = env.sketch
        else:
            self.sketch.merge(env.sketch)
        self.contributors |= incoming
        return True

    def to_envelope(
        self, sender: int, level: int, view: MembershipView
    ) -> Envelope:
        env = Envelope(
            sender=sender,
            level=level,
            contributors=frozenset(self.contributors),
            dead=frozenset(view.dead),
            mode=self.mode,
            parts=dict(self.parts),
            part_bytes=dict(self.part_bytes),
            sketch=self.sketch,
        )
        if _trace.ENABLED:
            ctx = _trace.current()
            if ctx is not None:
                env.trace_id = ctx.trace_id
                env.span_id = ctx.span_id
        return env


# ------------------------------------------------------------ tree shape
def _heights(world: int) -> List[int]:
    """Height of the heap subtree rooted at each position (leaf = 1);
    a node *sends up* at level == its height, so the root's height is
    the level count of the whole merge."""
    h = [1] * world
    for pos in range(world - 1, -1, -1):
        left, right = 2 * pos + 1, 2 * pos + 2
        if left < world:
            h[pos] = 1 + max(
                h[left], h[right] if right < world else 0
            )
    return h


def _subtree(pos: int, world: int) -> List[int]:
    out, frontier = [], [pos]
    while frontier:
        p = frontier.pop()
        out.append(p)
        for c in (2 * p + 1, 2 * p + 2):
            if c < world:
                frontier.append(c)
    return sorted(out)


def _next_round(group: CollectiveGroup) -> int:
    """Per-group monotonically increasing round id, attached to the
    innermost transport so repeated merges over re-wrapped groups keep
    distinct wire tags.  All ranks must call merges in the same order
    (the standing collective-ordering requirement)."""
    inner = group
    while hasattr(inner, "inner"):
        inner = inner.inner
    n = int(getattr(inner, "_fleet_merge_round", 0))
    try:
        inner._fleet_merge_round = n + 1
    except (AttributeError, TypeError):  # pragma: no cover - frozen group
        pass
    return n


def _fire(
    role: str, rank: int, level: int, round_id: int, topology: str
) -> None:
    if _faults.ENABLED:
        _faults.fire(
            _FAULT_SITE,
            rank=rank,
            level=level,
            round=round_id,
            topology=topology,
            role=role,
        )


def _level_group(
    group: CollectiveGroup,
    view: MembershipView,
    deadline: float,
    attempts: int,
) -> ResilientGroup:
    policy = RetryPolicy(
        max_attempts=attempts,
        base_delay=0.005,
        max_delay=0.05,
        jitter=0.0,
        deadline=deadline,
    )
    return ResilientGroup(group, policy, membership=view)


def _recv_hop(
    group: CollectiveGroup,
    view: MembershipView,
    src: int,
    tag: str,
    deadline: float,
    attempts: int,
) -> Envelope:
    rg = _level_group(group, view, deadline, attempts)
    per_attempt = max(0.001, deadline / attempts)
    return rg.recv_object(src, tag, timeout=per_attempt)


def _send_hop(
    group: CollectiveGroup,
    view: MembershipView,
    obj: Any,
    dst: int,
    tag: str,
    deadline: float,
    attempts: int,
) -> None:
    rg = _level_group(group, view, deadline, attempts)
    rg.send_object(obj, dst, tag)


def _record_level(
    seconds: float, payload_bytes: int, level: int, fanout: int
) -> None:
    if _telemetry.ENABLED:
        _telemetry.record_sync(
            "fleet_merge", seconds, payload_bytes, level=level, fanout=fanout
        )


def _ack_payload(me: int, view: MembershipView) -> tuple:
    """The wire ack: ``("ack", rank, dead-gossip[, span_id])`` — the
    4th element is this rank's merge span id, the downlink that lets the
    acked child reparent its merge span under the parent's before it
    emits its own level record (the one field cross-host trace assembly
    needs).  Omitted when tracing is off: 3-tuples stay on the wire, so
    traced and untraced builds interoperate."""
    base = ("ack", me, tuple(view.dead))
    if _trace.ENABLED:
        ctx = _trace.current()
        if ctx is not None:
            return base + (ctx.span_id,)
    return base


def _adopt_ack_parent(ack: Any) -> None:
    """Fold the parent span id an ack carried into this rank's active
    merge span (same span id, newly-learned parent).  Call before the
    level record is emitted so the event carries the link."""
    if not _trace.ENABLED:
        return
    if isinstance(ack, tuple) and len(ack) >= 4 and ack[3]:
        ctx = _trace.current()
        if ctx is not None:
            _trace.adopt(_trace.reparent(ctx, ack[3]))


# --------------------------------------------------------- tree protocol
def _tree_round(
    group: CollectiveGroup,
    view: MembershipView,
    acc: _Acc,
    dst: int,
    policy: MergePolicy,
    rid: str,
    round_id: int,
) -> bool:
    """Run this rank's part of one tree reduction.  Returns ``delivered``
    (True on the root, or once an ancestor acked this rank's envelope)."""
    me, world = group.rank, group.world_size
    my_pos = (me - dst) % world
    heights = _heights(world)
    rank_of = lambda pos: (dst + pos) % world  # noqa: E731

    parent_pos = (my_pos - 1) // 2
    ka_last = [float("-inf")]

    def keepalive() -> None:
        """Mid-recovery progress signal: tell the (static) parent this
        rank is alive so its recv deadline extends instead of falsely
        excising a whole live subtree; each ancestor relays it upward,
        so nested recovery anywhere beneath keeps the chain open."""
        if my_pos == 0:
            return
        now = time.monotonic()
        if now - ka_last[0] < policy.keepalive_interval():
            return
        ka_last[0] = now
        try:
            group.send_object(
                ("ka", me), rank_of(parent_pos), f"{rid}/ka/{my_pos}"
            )
        except Exception:  # noqa: BLE001 - keepalive is best-effort
            pass

    # 1. Receive (and ack) each child subtree's merged envelope.  The
    # wait is a raw-transport poll (like _poll_orphans) rather than one
    # ResilientGroup recv: the deadline must be extendable mid-wait by
    # the child's keepalives, which a fixed-budget recv cannot do.
    for child_pos in (2 * my_pos + 1, 2 * my_pos + 2):
        if child_pos >= world:
            continue
        child_rank = rank_of(child_pos)
        level = heights[child_pos]
        _fire("recv", me, level, round_id, "tree")
        hop_deadline = policy.level_deadline * level
        started = time.monotonic()
        hard_cap = started + policy.recv_window(level)
        deadline = started + hop_deadline
        env: Optional[Envelope] = None
        while True:
            try:
                env = group.recv_object(
                    child_rank,
                    f"{rid}/up/{child_pos}",
                    timeout=policy.poll_slice,
                )
                break
            except (PeerTimeoutError, CollectiveTimeoutError):
                pass
            try:
                group.recv_object(
                    child_rank, f"{rid}/ka/{child_pos}", timeout=0.0
                )
            except (PeerTimeoutError, CollectiveTimeoutError):
                pass
            else:
                deadline = time.monotonic() + hop_deadline
                keepalive()  # relay the liveness up the chain
            if time.monotonic() >= min(deadline, hard_cap):
                break
        if env is not None:
            try:
                acc.absorb(env, view)
                _send_hop(
                    group,
                    view,
                    _ack_payload(me, view),
                    child_rank,
                    f"{rid}/ack/{child_pos}",
                    policy.ack(),
                    policy.attempts,
                )
                _record_level(
                    time.monotonic() - started,
                    env.payload_nbytes(),
                    level,
                    2,
                )
                continue
            except (CollectiveTimeoutError, PeerTimeoutError) as exc:
                reason = f"no ack delivery at level {level}: {exc}"
        else:
            reason = f"no envelope at level {level} within deadline"
        view.excise(child_rank, reason=reason)
        _record_level(time.monotonic() - started, 0, level, 2)
        _poll_orphans(
            group, view, acc, child_pos, dst, policy, rid, heights,
            keepalive=keepalive,
        )

    if my_pos == 0:
        return True

    # 2. Send the merged envelope up, climbing past dead ancestors.
    level = heights[my_pos]
    _fire("send", me, level, round_id, "tree")
    env = acc.to_envelope(me, level, view)
    target_pos = (my_pos - 1) // 2
    tag_kind = "up"
    while True:
        target_rank = rank_of(target_pos)
        if view.is_alive(target_rank):
            started = time.monotonic()
            try:
                _send_hop(
                    group,
                    view,
                    env,
                    target_rank,
                    f"{rid}/{tag_kind}/{my_pos}",
                    policy.ack(),
                    policy.attempts,
                )
                ack = _recv_hop(
                    group,
                    view,
                    target_rank,
                    f"{rid}/ack/{my_pos}",
                    policy.ack_wait(heights[target_pos]),
                    policy.attempts,
                )
                view.observe(target_rank, level=level)
                if isinstance(ack, tuple) and len(ack) >= 3:
                    view.merge_gossip(ack[2], reason="ack gossip")
                _adopt_ack_parent(ack)
                _record_level(
                    time.monotonic() - started, env.payload_nbytes(), level, 2
                )
                return True
            except (CollectiveTimeoutError, PeerTimeoutError) as exc:
                view.excise(
                    target_rank,
                    reason=f"no ack at level {level}: {exc}",
                )
                _record_level(time.monotonic() - started, 0, level, 2)
        if target_pos == 0:
            return False  # every ancestor incl. the root is dead
        target_pos = (target_pos - 1) // 2
        tag_kind = "rp"


def _poll_orphans(
    group: CollectiveGroup,
    view: MembershipView,
    acc: _Acc,
    dead_child_pos: int,
    dst: int,
    policy: MergePolicy,
    rid: str,
    heights: List[int],
    keepalive: Optional[Any] = None,
) -> None:
    """After excising a child, poll for its subtree during the grace
    window, acking and absorbing whatever climbs up.

    The excised position itself stays in the poll (on its original
    ``up`` tag, plus ``rp``): a slow-but-alive child whose envelope
    missed the recv deadline is absorbed late instead of its whole
    subtree being lost — it re-sends only toward its *grandparent*,
    which never polls ``rp`` tags for positions it did not excise.

    The window is bounded three ways.  Hard cap:
    ``capped_poll_window`` (the exponential bound, clamped at
    ``poll_window_max``).  No-progress bound: a surviving orphan's
    worst-case chain of dead-ancestor detections sums geometrically
    below ``2 * ack_wait(tallest pending)``, so silence that long
    (plus grace) means nothing can still arrive — and the bound shrinks
    as orphans resolve.  Corroboration: once the dead child's own
    children re-parented around it, only its own late envelope could
    still arrive, and its children's matching excision says it will
    not."""
    world = group.world_size
    me = group.rank
    rank_of = lambda pos: (dst + pos) % world  # noqa: E731
    pending = set(_subtree(dead_child_pos, world))
    started = time.monotonic()
    hard_deadline = started + policy.capped_poll_window(
        heights[dead_child_pos]
    )

    def quiet_budget() -> float:
        tallest = max(heights[p] for p in pending)
        return 2.0 * policy.ack_wait(tallest) + policy.grace()

    quiet_deadline = started + quiet_budget()
    reparented = False
    while pending:
        now = time.monotonic()
        if now >= hard_deadline or now >= quiet_deadline:
            break
        if reparented and pending == {dead_child_pos}:
            break
        if keepalive is not None:
            keepalive()
        progressed = False
        for pos in sorted(pending):
            orphan_rank = rank_of(pos)
            dead_by_gossip = (
                pos != dead_child_pos and not view.is_alive(orphan_rank)
            )
            if dead_by_gossip or orphan_rank in acc.contributors:
                pending.discard(pos)
                progressed = True
                continue
            tags = [f"{rid}/rp/{pos}"]
            if pos == dead_child_pos:
                tags.insert(0, f"{rid}/up/{pos}")
            env: Optional[Envelope] = None
            for tag in tags:
                try:
                    env = group.recv_object(
                        orphan_rank, tag, timeout=policy.poll_slice
                    )
                    break
                except (PeerTimeoutError, CollectiveTimeoutError):
                    continue
            if env is None:
                if pos == dead_child_pos:
                    # A keepalive from the excised child: still alive,
                    # mid-recovery — keep its window open.
                    try:
                        group.recv_object(
                            orphan_rank, f"{rid}/ka/{pos}", timeout=0.0
                        )
                    except (PeerTimeoutError, CollectiveTimeoutError):
                        pass
                    else:
                        progressed = True
                continue
            acc.absorb(env, view)
            try:
                group.send_object(
                    _ack_payload(me, view),
                    orphan_rank,
                    f"{rid}/ack/{pos}",
                )
            except Exception:  # noqa: BLE001 - ack is best-effort
                pass
            if pos != dead_child_pos:
                reparented = True
            # The orphan's envelope covers its whole live subtree.
            for covered in _subtree(pos, world):
                pending.discard(covered)
            progressed = True
        if progressed and pending:
            quiet_deadline = time.monotonic() + quiet_budget()


# --------------------------------------------------------- ring protocol
def _ring_round(
    group: CollectiveGroup,
    view: MembershipView,
    acc: _Acc,
    dst: int,
    policy: MergePolicy,
    rid: str,
    round_id: int,
) -> bool:
    """Chain reduction from position ``world-1`` down to the head at
    ``dst``.  A sender that gets no ack skips to the next live
    downstream position; a receiver polls every upstream candidate
    (the envelope may arrive from any of them after skips)."""
    me, world = group.rank, group.world_size
    my_pos = (me - dst) % world
    rank_of = lambda pos: (dst + pos) % world  # noqa: E731

    if my_pos != world - 1:
        level = world - 1 - my_pos
        _fire("recv", me, level, round_id, "ring")
        budget = policy.level_deadline * level
        started = time.monotonic()
        deadline = started + budget
        candidates = list(range(my_pos + 1, world))
        env: Optional[Envelope] = None
        while env is None and time.monotonic() < deadline:
            for src_pos in candidates:
                src_rank = rank_of(src_pos)
                if not view.is_alive(src_rank):
                    continue
                try:
                    env = group.recv_object(
                        src_rank,
                        f"{rid}/ring/{my_pos}",
                        timeout=policy.poll_slice,
                    )
                except (PeerTimeoutError, CollectiveTimeoutError):
                    continue
                acc.absorb(env, view)
                try:
                    group.send_object(
                        _ack_payload(me, view),
                        src_rank,
                        f"{rid}/ring-ack/{src_pos}",
                    )
                except Exception:  # noqa: BLE001 - ack is best-effort
                    pass
                break
        _record_level(
            time.monotonic() - started,
            env.payload_nbytes() if env is not None else 0,
            level,
            1,
        )
        # No envelope inside the budget: the upstream chain is gone (or
        # partitioned); this rank restarts the chain from its own
        # contribution and the head's contributor set tells the truth.

    if my_pos == 0:
        return True

    level = world - my_pos
    _fire("send", me, level, round_id, "ring")
    env_out = acc.to_envelope(me, level, view)
    target_pos = my_pos - 1
    while target_pos >= 0:
        target_rank = rank_of(target_pos)
        if view.is_alive(target_rank):
            started = time.monotonic()
            try:
                _send_hop(
                    group,
                    view,
                    env_out,
                    target_rank,
                    f"{rid}/ring/{target_pos}",
                    policy.ack(),
                    policy.attempts,
                )
                ack = _recv_hop(
                    group,
                    view,
                    target_rank,
                    f"{rid}/ring-ack/{my_pos}",
                    # The downstream receiver is a round-robin poller;
                    # its ack lands within one sweep of its candidates.
                    policy.ack() + policy.poll_slice * world,
                    policy.attempts,
                )
                view.observe(target_rank, level=level)
                _adopt_ack_parent(ack)
                _record_level(
                    time.monotonic() - started,
                    env_out.payload_nbytes(),
                    level,
                    1,
                )
                return True
            except (CollectiveTimeoutError, PeerTimeoutError) as exc:
                view.excise(
                    target_rank,
                    reason=f"no ring ack at level {level}: {exc}",
                )
                _record_level(time.monotonic() - started, 0, level, 1)
        target_pos -= 1
    return False


# ------------------------------------------------------------ entry point
def fleet_merge(
    metric: Any,
    group: CollectiveGroup,
    *,
    topology: str = "tree",
    sketch: Optional[str] = None,
    sketch_options: Optional[Dict[str, Any]] = None,
    dst: int = 0,
    recipient: Any = None,
    policy: Optional[MergePolicy] = None,
    membership: Optional[MembershipView] = None,
    round_id: Optional[int] = None,
    compute: bool = True,
) -> MergeOutcome:
    """Hierarchically merge ``metric``'s state across ``group``.

    Returns a :class:`MergeOutcome` on **every** rank and never raises
    past the root: peer failures become excisions and a partial result.
    ``recipient`` defaults to ``dst`` (only the root computes the
    value); ``recipient="all"`` has the root distribute the computed
    value point-to-point to every live rank (a rank that misses the
    result inside its deadline degrades to a local-only partial outcome
    with a ``degraded`` telemetry event, because a barrier broadcast
    would hang on the very host losses this merge survives).

    ``sketch=None`` ships whole prepared per-rank states (lossless,
    rank-order reassembly at the root → bit-identical to the flat
    path); a sketch kind ships O(bins) summaries — see
    :meth:`BinaryAUROC.sketch_state` for kinds, options, and bounds.
    """
    if topology not in ("tree", "ring"):
        raise ValueError(
            f"topology must be 'tree' or 'ring', got {topology!r}"
        )
    if sketch == "exact":
        sketch = None  # exact rides the rank-keyed parts map
    policy = policy if policy is not None else MergePolicy()
    me, world = group.rank, group.world_size
    recipient = dst if recipient is None else recipient
    levels = (
        _heights(world)[0] if topology == "tree" else max(1, world - 1)
    ) if world >= 1 else 0

    if world <= 1:
        value = metric.compute() if compute else None
        return MergeOutcome(
            value=value,
            metric=metric,
            world_size=max(world, 1),
            world_effective=max(world, 1),
            lost_ranks=(),
            partial=False,
            topology=topology,
            levels=0,
            rank=max(me, 0),
            delivered=True,
            sketch=sketch,
        )
    if not group.supports_p2p:
        raise ValueError(
            f"{type(group).__name__} has no point-to-point transport; "
            "use topology='flat' (toolkit.sync_and_compute) instead"
        )

    view = resolve_membership(membership, world, me)
    rnd = _next_round(group) if round_id is None else int(round_id)
    rid = f"fm{rnd}"

    acc = _Acc("exact" if sketch is None else "sketch")
    if sketch is None:
        metric._prepare_for_merge_state()
        from torcheval_tpu.metrics._sketch import state_nbytes

        acc.add_local(me, part=metric, nbytes=state_nbytes(metric))
    else:
        opts = dict(sketch_options or {})
        if sketch == "reservoir":
            opts.setdefault("salt", me)
        acc.add_local(me, sketch=metric.sketch_state(sketch, **opts))

    delivered = True
    round_fn = _tree_round if topology == "tree" else _ring_round
    merge_ctx = None
    if _trace.ENABLED:
        # Every rank of one round derives the SAME trace id from the
        # shared round id — cross-host trace identity with zero extra
        # round trips.  The initial parent link points at whatever
        # scheduled this rank's merge (the engine block span, via
        # PendingMerge's handoff); acks later reparent non-root merge
        # spans under their tree parent's span, and the root keeps the
        # local link — bridging the whole cross-host tree into the
        # root's engine trace.
        local = _trace.current()
        merge_ctx = _trace.derive(
            f"merge-{rid}",
            parent_span_id=local.span_id if local is not None else "",
        )
    try:
        _fire("start", me, 0, rnd, topology)
        if _trace.ENABLED and merge_ctx is not None:
            with _trace.activate(merge_ctx):
                delivered = round_fn(group, view, acc, dst, policy, rid, rnd)
        else:
            delivered = round_fn(group, view, acc, dst, policy, rid, rnd)
    except DroppedRank:
        # This rank "vanished": no sends, no acks, no result — its
        # peers excise it and carry on.  Locally we still return a
        # well-formed (undelivered) outcome so a caller thread joins.
        return MergeOutcome(
            value=None,
            metric=None,
            world_size=world,
            world_effective=view.world_effective,
            lost_ranks=tuple(sorted(view.dead)),
            partial=True,
            topology=topology,
            levels=levels,
            rank=me,
            delivered=False,
            dropped=True,
            sketch=sketch,
        )

    my_pos = (me - dst) % world
    if my_pos == 0:
        outcome = _root_outcome(
            acc, view, world, me, topology, levels, sketch, compute
        )
        if recipient == "all":
            import numpy as np

            value = outcome.value
            if hasattr(value, "shape"):  # device array -> host bytes
                value = np.asarray(value)
            wire = (
                value,
                outcome.lost_ranks,
                outcome.payload_bytes_at_root,
                outcome.overlap_skips,
            )
            # Send to every initial rank, not just the ones this view
            # thinks are alive: a live rank the root wrongly excised
            # (its envelope arrived late or via an orphan poll) still
            # deserves the result, sends are non-blocking, and an
            # unclaimed message to a truly dead rank is tolerated.
            for peer in range(world):
                if peer == me:
                    continue
                try:
                    group.send_object(wire, peer, f"{rid}/res/{peer}")
                except Exception:  # noqa: BLE001 - peer may have died
                    pass
        return outcome

    if recipient == "all":
        try:
            value, lost, root_bytes, skips = group.recv_object(
                (dst) % world, f"{rid}/res/{me}", timeout=policy.result(levels)
            )
            lost = tuple(lost)
            return MergeOutcome(
                value=value,
                metric=None,
                world_size=world,
                world_effective=world - len(lost),
                lost_ranks=lost,
                partial=bool(lost),
                topology=topology,
                levels=levels,
                rank=me,
                delivered=delivered,
                sketch=sketch,
                payload_bytes_at_root=root_bytes,
                overlap_skips=skips,
            )
        except (PeerTimeoutError, CollectiveTimeoutError) as exc:
            if _telemetry.ENABLED:
                _telemetry.record_degraded(
                    "fleet_merge",
                    f"no result from root: {exc}",
                    "local",
                    survivors=view.survivors_label(),
                )
            # All this rank knows is that the root's result did not
            # arrive: report the root (plus already-known deaths) as
            # lost, not every peer — the rest of the fleet may be fine.
            local_value = metric.compute() if compute else None
            lost = tuple(sorted(view.dead | {dst % world}))
            return MergeOutcome(
                value=local_value,
                metric=None,
                world_size=world,
                world_effective=world - len(lost),
                lost_ranks=lost,
                partial=True,
                topology=topology,
                levels=levels,
                rank=me,
                delivered=delivered,
                sketch=sketch,
            )

    lost = tuple(sorted(view.dead))
    return MergeOutcome(
        value=None,
        metric=None,
        world_size=world,
        world_effective=view.world_effective,
        lost_ranks=lost,
        partial=bool(lost) or not delivered,
        topology=topology,
        levels=levels,
        rank=me,
        delivered=delivered,
        sketch=sketch,
    )


def _root_outcome(
    acc: _Acc,
    view: MembershipView,
    world: int,
    rank: int,
    topology: str,
    levels: int,
    sketch: Optional[str],
    compute: bool,
) -> MergeOutcome:
    contributors = sorted(acc.contributors)
    lost = tuple(sorted(set(range(world)) - acc.contributors))
    metric = None
    value = None
    if acc.mode == "exact":
        metric = _assemble_exact(acc.parts)
        if compute and metric is not None:
            value = metric.compute()
        root_bytes = sum(acc.part_bytes.values())
    else:
        if compute and acc.sketch is not None:
            value = acc.sketch.compute()
        root_bytes = int(acc.sketch.nbytes()) if acc.sketch else 0
    return MergeOutcome(
        value=value,
        metric=metric,
        world_size=world,
        world_effective=len(contributors),
        lost_ranks=lost,
        partial=len(contributors) < world,
        topology=topology,
        levels=levels,
        rank=rank,
        delivered=True,
        sketch=sketch,
        payload_bytes_at_root=root_bytes,
        overlap_skips=acc.overlap_skips,
    )


def _assemble_exact(parts: Dict[int, Any]) -> Any:
    """Reassemble per-rank prepared states in rank order — the exact
    sequence the flat path's ``clone(g[0]).merge_state(g[1:])`` uses,
    so a clean tree/ring merge is bit-identical to the flat gather."""
    import copy

    if not parts:
        return None
    ranks = sorted(parts)
    base = copy.deepcopy(parts[ranks[0]])
    rest = [parts[r] for r in ranks[1:]]
    if rest:
        base.merge_state(rest)
    return base


class PendingMerge:
    """Handle for a fleet merge overlapped with further eval work
    (``Evaluator.start_fleet_merge``): the merge runs on a daemon
    thread over a state snapshot; :meth:`result` joins and returns the
    :class:`MergeOutcome` (or re-raises the thread's error — which the
    merge itself never produces for *peer* failures, only for
    programming errors)."""

    def __init__(self, target: Any, args: tuple, kwargs: dict) -> None:
        self._outcome: Optional[MergeOutcome] = None
        self._error: Optional[BaseException] = None
        # Explicit thread handoff of the caller's trace context
        # (start_fleet_merge activates the scheduling engine-block span
        # around this constructor) so the merge's spans parent on the
        # block that scheduled them.
        self._trace_ctx = _trace.capture() if _trace.ENABLED else None

        def run() -> None:
            if _trace.ENABLED:
                _trace.adopt(self._trace_ctx)
            try:
                self._outcome = target(*args, **kwargs)
            except BaseException as exc:  # noqa: BLE001 - relayed in result()
                self._error = exc

        self._thread = threading.Thread(
            target=run, name="fleet-merge", daemon=True
        )
        self._thread.start()

    def done(self) -> bool:
        return not self._thread.is_alive()

    def result(self, timeout: Optional[float] = None) -> MergeOutcome:
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("fleet merge still running")
        if self._error is not None:
            raise self._error
        assert self._outcome is not None
        return self._outcome
