"""JAX version compatibility for the parallel layer.

The sharded builders are written against the stable ``jax.shard_map``
API (jax >= 0.6).  Older installs only ship
``jax.experimental.shard_map.shard_map``, whose replication checker is
spelled ``check_rep`` instead of ``check_vma``; this adapter presents the
stable keyword signature over whichever one exists.
"""

import jax

__all__ = ["shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_vma,
    )
