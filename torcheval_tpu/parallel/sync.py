"""Explicit in-jit metric-state sync over a device mesh.

This is the TPU-native replacement for the reference's gather→merge→compute
protocol (reference ``toolkit.py:24-78,235-257``): instead of pickling Metric
objects across processes, each device reduces its local batch shard to
sufficient statistics and ONE fused XLA collective merges them across the
mesh axis.  The collective is chosen per state to mirror the metric's
``merge_state`` semantics (reference merge archetypes, SURVEY §1-L3):

* counter states (add-merge)      → ``lax.psum``
* ``Min`` / ``Max`` states         → ``lax.pmin`` / ``lax.pmax``
* ``Throughput.elapsed_time_sec`` → ``lax.pmax`` (slowest-rank gating,
  reference ``aggregation/throughput.py:99-107``)
* buffer states (concat-merge)    → ``lax.all_gather(..., tiled=True)``

Everything here is ordinary ``shard_map`` code — collectives ride ICI on a
pod mesh and DCN across slices, exactly where XLA places them.
"""

from __future__ import annotations

import time
from functools import lru_cache
from typing import Any, Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec

from torcheval_tpu.parallel._compat import shard_map
from torcheval_tpu.parallel._compile_cache import compiled_spmd
from torcheval_tpu.parallel.mesh import AxisSpec, _axis_size
from torcheval_tpu.resilience import faults as _faults
from torcheval_tpu.telemetry import events as _telemetry
from torcheval_tpu.telemetry import perfscope as _perfscope

Reduction = Union[str, Any]  # 'sum' | 'max' | 'min' | 'mean' | 'concat' | pytree

_REDUCERS = {
    "sum": lambda x, axis: lax.psum(x, axis),
    "max": lambda x, axis: lax.pmax(x, axis),
    "min": lambda x, axis: lax.pmin(x, axis),
    "mean": lambda x, axis: lax.pmean(x, axis),
    "concat": lambda x, axis: lax.all_gather(x, axis, axis=0, tiled=True),
}


def _reduce_leaf(value: jax.Array, how: str, axis: str) -> jax.Array:
    try:
        return _REDUCERS[how](value, axis)
    except KeyError:
        raise ValueError(
            f"Unknown reduction {how!r}; expected one of {sorted(_REDUCERS)}"
        ) from None


def _timed_dispatch(fn, op: str, payload_bytes: int, *args):
    """Instrumented dispatch wrapper for the sharded histogram programs.
    With the telemetry bus on: wall time (blocked to completion — the
    collective rides inside the program, so this bounds it from above)
    plus the merge's wire payload estimate, emitted as ONE ``sync``
    event.  With perfscope on: the program is priced once per argument
    signature (``spmd:<op>``).  Callers branch on ``_telemetry.ENABLED
    or _perfscope.ENABLED`` so the fully-disabled path stays a bare
    call."""
    if _perfscope.ENABLED:
        _perfscope.profile_program(
            f"spmd:{op}",
            fn,
            args,
            batch_args=args,
            signature=tuple(
                (tuple(leaf.shape), str(leaf.dtype))
                for leaf in jax.tree.leaves(args)
            ),
        )
    if not _telemetry.ENABLED:
        return fn(*args)
    t0 = time.monotonic()
    out = fn(*args)
    jax.block_until_ready(out)
    _telemetry.record_sync(op, time.monotonic() - t0, payload_bytes)
    return out


def mesh_merge_states(states, axis: str, reductions: Reduction = "sum"):
    """Merge per-device partial states across mesh axis ``axis``.

    For use INSIDE ``shard_map``/``pjit`` code.  ``states`` is any pytree of
    arrays; ``reductions`` is a single reduction name applied to every leaf,
    or a pytree (prefix) of names matching ``states``.

    This is the in-jit analog of ``Metric.merge_state`` (reference
    ``metric.py:91-110``): addition for counters, max/min for extrema,
    concatenation for sample buffers.
    """
    if isinstance(reductions, str):
        return jax.tree.map(lambda v: _reduce_leaf(v, reductions, axis), states)
    return jax.tree.map(
        lambda how, v: _reduce_leaf(v, how, axis), reductions, states
    )


def make_synced_update(
    kernel: Callable[..., Any],
    mesh: Mesh,
    axis: AxisSpec = "dp",
    reductions: Reduction = "sum",
    in_specs: Optional[Sequence[PartitionSpec]] = None,
    retry: Optional[Any] = None,
) -> Callable[..., Any]:
    """Wrap a functional sufficient-statistic kernel into a jitted SPMD
    update with one fused cross-device merge.

    ``kernel(*batch) -> state_pytree`` is any of the library's functional
    ``_*_update`` kernels (they are pure and shape-polymorphic over the batch
    dim).  Each device runs it on its local shard of the batch (inputs are
    sharded over ``axis`` on their leading dimension by default) and the
    partial states are merged with the per-leaf collectives in
    ``reductions`` — the whole thing is one XLA program: local reduction +
    one fused collective, replicated result.

    This replaces the reference's per-rank ``metric.update`` +
    ``sync_and_compute`` round (reference ``toolkit.py:24-78``) with a path
    that never leaves the device.

    ``retry`` (a :class:`torcheval_tpu.resilience.RetryPolicy`) re-issues
    the dispatch on transient failure with backoff, raising
    :class:`~torcheval_tpu.resilience.CollectiveTimeoutError` on
    exhaustion — the retry is symmetric across hosts because every host
    runs the same policy over the same SPMD program.  Each failed
    attempt emits a ``retry`` telemetry event when the bus is on.
    """
    if in_specs is None:
        specs: Any = PartitionSpec(axis)
    else:
        specs = tuple(in_specs)

    def local(*batch):
        return mesh_merge_states(kernel(*batch), axis, reductions)

    # After any of the merges — psum/pmax/pmin/pmean, or a tiled all_gather
    # for 'concat' — every device holds the identical full value.  The
    # varying-axes checker can't statically prove that for all_gather, so
    # disable it when a concat leaf is present.
    leaves = (
        [reductions] if isinstance(reductions, str) else jax.tree.leaves(reductions)
    )
    jitted = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=specs,
            out_specs=PartitionSpec(),
            check_vma="concat" not in leaves,
        )
    )
    op = f"synced_update:{getattr(kernel, '__name__', str(kernel))}"

    def attempt_call(*batch):
        # Chaos site "sync.dispatch" fires per attempt (inside the retry
        # loop) so injected transient failures are retried like real ones.
        if _faults.ENABLED:
            _faults.fire("sync.dispatch", op=op)
        return jitted(*batch)

    if retry is not None:
        import random as _random

        from torcheval_tpu.resilience.retry import retry_call as _retry_call

        _rng = _random.Random(retry.seed)

        def dispatch(*batch):
            return _retry_call(
                op, lambda: attempt_call(*batch), retry, rng=_rng
            )

    else:
        dispatch = attempt_call

    def synced(*batch):
        if not _telemetry.ENABLED:
            return dispatch(*batch)
        t0 = time.monotonic()
        out = dispatch(*batch)
        jax.block_until_ready(out)
        # The merged state pytree IS the collective's payload (every
        # device ends up holding the full value).
        payload = sum(
            getattr(leaf, "nbytes", 0) for leaf in jax.tree.leaves(out)
        )
        _telemetry.record_sync(op, time.monotonic() - t0, payload)
        return out

    return synced


def sharded_auroc_histogram(
    scores: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    axis: AxisSpec = "dp",
    num_bins: int = 8192,
    weights: Optional[jax.Array] = None,
    assume_01_targets: Optional[bool] = None,
    assume_split_safe_weights: Optional[bool] = None,
) -> jax.Array:
    """Pod-scale binary AUROC with O(num_bins) communication.

    The reference's only distributed AUROC story is gathering every raw
    sample to one rank (reference ``classification/auroc.py:121-134`` +
    ``toolkit.py:247-255``) — O(total samples) over the wire.  Here each
    device histograms its local scores (validated in [0, 1]; see `_check_scores_in_unit_interval`) into
    ``num_bins`` threshold bins for positives/negatives, ONE ``psum`` merges
    the ``2 × num_bins`` histogram across the mesh, and the ROC integral is
    computed from the binned cumulative TP/FP curves on every device.

    Like the reference's opt-in fbgemm CUDA kernel (reference
    ``functional/classification/auroc.py:42-46,150-162``) this trades
    exactness for speed: scores are quantized to ``num_bins`` levels
    (exact for already-quantized scores; error ``O(1/num_bins)`` otherwise).
    Use the exact ``binary_auroc`` on gathered buffers when bit-exactness
    matters more than wire cost.

    ``assume_01_targets``: ``None`` (default) checks eagerly that targets
    are exactly 0/1 and routes accordingly — under a caller's jit the
    check sees only tracers, so the scatter path runs.  Pass ``True``
    (asserting 0/1 targets) to keep the faster binned-counts dispatch
    reachable under jit (the ``ustat_cap`` recipe); ``False`` forces the
    scatter path (required semantics for soft targets, whose fractional
    positives only the scatter carries).

    Weighted calls with 0/1 targets route through the weighted Pallas
    payload kernel when the work is large enough and the weights admit
    the exact bf16 split (see ``pallas_binned.split_safe_weights``);
    ``assume_split_safe_weights`` pins that gate under jit the same way
    ``assume_01_targets`` pins the target gate.  Weighted results follow
    the kernel's f32 summation-order contract (~1e-6 vs the scatter;
    weighted(ones) stays BITWISE equal to unweighted).
    """
    return _run_sharded_binary(
        _build_auroc_hist_local,
        _build_auroc_hist_counts_local,
        _build_auroc_hist_wcounts_local,
        num_bins,
        mesh,
        axis,
        scores,
        targets,
        weights,
        assume_01_targets,
        assume_split_safe_weights,
    )


def _binned_roc_area(cum_tp, cum_fp):
    """Trapezoid ROC area from descending-threshold cumulative counts
    (with the (0, 0) origin prepended); degenerate rows → 0.5.  ONE
    definition serves the weighted (scatter) and unweighted (counts)
    formulations so the bitwise weighted(ones) ≡ unweighted contract
    cannot drift."""
    factor = cum_tp[..., -1] * cum_fp[..., -1]
    area = jnp.trapezoid(cum_tp, cum_fp, axis=-1)
    return jnp.where(factor == 0, 0.5, area / factor)


def _binned_step_ap(delta_tp, cum_tp, cum_all):
    """Step-rule AP from descending-threshold per-bin TP increments and
    cumulative TP / predicted-positive counts; no positives → 0.  ONE
    definition serves both formulations (see :func:`_binned_roc_area`).
    The 0/0 guards must not clamp small weighted counts — AP is invariant
    to weight scale."""
    precision = jnp.where(
        cum_all > 0, cum_tp / jnp.where(cum_all > 0, cum_all, 1.0), 1.0
    )
    total_pos = cum_tp[-1]
    ap = (delta_tp * precision).sum() / jnp.where(
        total_pos > 0, total_pos, 1.0
    )
    return jnp.where(total_pos == 0, 0.0, ap)


def _build_auroc_hist_local(num_bins: int, axis: str):
    def local(s, t, w):
        pos, tot = _local_binned_counts(s, t, w, num_bins, axis)
        neg = tot - pos
        # Descending-threshold cumulative curves, from the (0, 0) origin.
        cum_tp = jnp.concatenate([jnp.zeros(1), jnp.cumsum(pos[::-1])])
        cum_fp = jnp.concatenate([jnp.zeros(1), jnp.cumsum(neg[::-1])])
        return _binned_roc_area(cum_tp, cum_fp)

    return local


@lru_cache(maxsize=64)
def _grid_np(num_bins: int) -> "np.ndarray":
    """The threshold grid that reproduces the scatter formulation's bins
    BITWISE: ``t_j`` is the smallest f32 ``x ≥ 0`` with
    ``f32(x · num_bins) ≥ j``, so ``#(s ≥ t_j)`` equals the
    reversed-cumulative per-bin counts of
    ``clip(int(s · num_bins), 0, num_bins − 1)`` for every f32 score —
    not just bin-aligned ones.  A naive ``j / num_bins`` grid diverges by
    1–2 samples per bin at representable bin edges for
    non-power-of-two ``num_bins`` (f32 rounding of ``s · num_bins`` vs
    ``j / num_bins``), which would make the weighted (scatter) and
    unweighted (counts) paths disagree on identical data.  Found by
    32-step bisection on the f32 bit pattern (f32 multiply is monotone),
    host-side, cached per ``num_bins``."""
    j = np.arange(num_bins, dtype=np.float32)
    nb = np.float32(num_bins)
    lo = np.zeros(num_bins, np.uint32)  # 0.0: satisfies only j = 0
    hi = np.full(num_bins, np.float32(1.0).view(np.uint32), np.uint32)
    for _ in range(32):
        mid = (lo + hi) // 2
        ok = mid.view(np.float32) * nb >= j
        hi = np.where(ok, mid, hi)
        lo = np.where(ok, lo, mid + 1)
    t = hi.view(np.float32)
    assert (t * nb >= j).all()
    t.setflags(write=False)
    return t


def _grid(num_bins: int):
    return jnp.asarray(_grid_np(num_bins))


def _build_auroc_hist_wcounts_local(num_bins: int, split3: bool, axis: str):
    """Weighted binary local stage through the weighted Pallas binned
    kernel (``pallas_binned._binned_wcount_kernel`` — MXU payload matmuls
    instead of the serializing per-bin scatter; round-4 VERDICT item 4).
    Preconditions (0/1 targets, split-safe weights) are gated by
    ``_weighted_kernel_route`` before this builder is selected."""
    from torcheval_tpu.ops.pallas_binned import (
        _pallas_binned_weighted_counts_jit,
        has_pallas,
    )

    def local(s, t, w):
        w_tp, w_fp, _, _ = _pallas_binned_weighted_counts_jit(
            s.astype(jnp.float32)[None],
            (t != 0)[None],
            w.astype(jnp.float32),
            _grid(num_bins),
            interpret=not has_pallas(),
            split3=split3,
        )
        num_tp = lax.psum(w_tp[0], axis)
        num_fp = lax.psum(w_fp[0], axis)
        zero = jnp.zeros(1, jnp.float32)
        cum_tp = jnp.concatenate([zero, num_tp[::-1]])
        cum_fp = jnp.concatenate([zero, num_fp[::-1]])
        return _binned_roc_area(cum_tp, cum_fp)

    return local


def _build_auprc_hist_wcounts_local(num_bins: int, split3: bool, axis: str):
    """Weighted AP local stage through the weighted Pallas binned kernel
    (see :func:`_build_auroc_hist_wcounts_local`)."""
    from torcheval_tpu.ops.pallas_binned import (
        _pallas_binned_weighted_counts_jit,
        has_pallas,
    )

    def local(s, t, w):
        w_tp, w_fp, _, _ = _pallas_binned_weighted_counts_jit(
            s.astype(jnp.float32)[None],
            (t != 0)[None],
            w.astype(jnp.float32),
            _grid(num_bins),
            interpret=not has_pallas(),
            split3=split3,
        )
        cum_tp = lax.psum(w_tp[0], axis)[::-1]
        cum_all = lax.psum(w_tp[0] + w_fp[0], axis)[::-1]
        delta_tp = jnp.diff(cum_tp, prepend=0.0)
        return _binned_step_ap(delta_tp, cum_tp, cum_all)

    return local


def _weighted_kernel_route(
    weights, num_rows: int, n_local: int, num_bins: int,
    assume_split_safe: Optional[bool],
):
    """Decide the weighted histogram formulation: ``(use_kernel,
    split3_table)``.  The kernel needs (a) the binned-counts dispatch to
    pick Pallas for this work shape and (b) weights whose exact bf16
    split holds (every nonzero |w| ≥ 2^-100, finite —
    ``pallas_binned.split_safe_weights``).  ``assume_split_safe`` pins
    (b) under jit, where the gate sees tracers (the ``assume_01_targets``
    recipe); tracer weights without the pin warn once per callsite and
    keep the always-correct scatter."""
    if _hist_route(num_rows, n_local, num_bins) != "pallas":
        return False, False
    safe = assume_split_safe
    if safe is None:
        from torcheval_tpu.metrics.functional._host_checks import all_concrete
        from torcheval_tpu.ops.pallas_binned import split_safe_weights

        if not all_concrete(weights) and weights.size:
            from torcheval_tpu.routing import warn_route_downgrade

            warn_route_downgrade(
                "weighted-hist-gate",
                "the weighted histogram's weights-domain gate cannot "
                "read values under jit (weights are tracers); running "
                "the scatter formulation.  Pass "
                "assume_split_safe_weights=True (asserting every "
                "nonzero |weight| ≥ 2^-100 and finite) to keep the "
                "Pallas payload kernel reachable under jit.",
            )
            return False, False
        safe = split_safe_weights(weights)
    if not safe:
        return False, False
    from torcheval_tpu.ops.pallas_binned import _split_safe_thresholds

    return True, _split_safe_thresholds(_grid(num_bins))


def _build_auroc_hist_counts_local(num_bins: int, route: str, axis: str):
    """Unweighted binary local stage through the 3-way binned-counts
    dispatch (``binned_auc._binned_counts_rows``: broadcast / Pallas MXU /
    sort by measured regime) instead of the scatter histogram — TPU
    scatters serialize (the 16384-bin scatter measured 55.9 ms at 4M
    samples on v5e; the dispatch's formulations are 4-50x faster)."""
    from torcheval_tpu.metrics.functional.classification.binned_auc import (
        _binned_counts_rows,
    )

    def local(s, t):
        # f32 cast first: the bisected grid reproduces the scatter path
        # bitwise for f32 scores ONLY (f64 / low-precision scores can
        # disagree near bin edges between `s >= t_j` and trunc binning).
        num_tp, num_fp, _, _ = _binned_counts_rows(
            s.astype(jnp.float32)[None],
            (t != 0)[None],
            _grid(num_bins),
            route=route,
        )
        num_tp = lax.psum(num_tp[0], axis).astype(jnp.float32)
        num_fp = lax.psum(num_fp[0], axis).astype(jnp.float32)
        zero = jnp.zeros(1, jnp.float32)
        cum_tp = jnp.concatenate([zero, num_tp[::-1]])
        cum_fp = jnp.concatenate([zero, num_fp[::-1]])
        return _binned_roc_area(cum_tp, cum_fp)

    return local


def _check_scores_in_unit_interval(scores) -> None:
    """Raise when histogram-binned scores fall outside [0, 1] — silent
    clipping would distort the curve if logits are passed by mistake (the
    reference's binned family validates its grid the same way, reference
    ``binned_precision_recall_curve.py:235-242``).  Host check: one fused
    round trip, skipped under tracing or ``skip_value_checks``."""
    from torcheval_tpu.metrics.functional._host_checks import (
        all_concrete,
        bounds,
        value_checks_enabled,
    )

    if not value_checks_enabled() or not all_concrete(scores):
        return
    if scores.size == 0:
        return
    lo, hi = bounds(scores)
    _raise_if_scores_out_of_unit(float(lo), float(hi))


def _raise_if_scores_out_of_unit(lo: float, hi: float) -> None:
    if lo < 0 or hi > 1:
        raise ValueError(
            "The values in `scores` should be in the range of [0, 1] for "
            f"histogram-binned curve metrics, got min {lo} max {hi} "
            "(apply a sigmoid/softmax first, or use the exact sharded "
            "variants in torcheval_tpu.parallel.exact)."
        )


@jax.jit
def _binary_hist_stats_kernel(scores, targets):
    return jnp.stack(
        [
            jnp.min(scores).astype(jnp.float32),
            jnp.max(scores).astype(jnp.float32),
            jnp.sum(
                (targets != 0) & (targets != 1), dtype=jnp.int32
            ).astype(jnp.float32),
        ]
    )


def _binary_hist_gate(scores, targets) -> bool:
    """Fused score-range validation + exact-0/1-target stat in ONE device
    round trip (the `_host_checks` one-fetch pattern), deciding the
    unweighted formulation: True → binned-counts dispatch, False → the
    scatter path (soft targets; or tracing / ``skip_value_checks`` /
    empty input, where the stats cannot be read).  Tracer-safe like
    ``_host_checks.bounds``: inside someone else's trace even concrete
    inputs stage to tracers, so the stats fall back to pure numpy on the
    host values."""
    from torcheval_tpu.metrics.functional._host_checks import (
        all_concrete,
        value_checks_enabled,
    )

    if not value_checks_enabled() or scores.size == 0:
        return False
    if not all_concrete(scores):
        return False
    if not all_concrete(targets):
        # Scores are still checkable (the replaced code always validated
        # them); only the target stat is out of reach — scatter path.
        _check_scores_in_unit_interval(scores)
        return False
    out = _binary_hist_stats_kernel(scores, targets)
    if isinstance(out, jax.core.Tracer):
        host_s = np.asarray(scores)
        host_t = np.asarray(targets)
        lo, hi = float(host_s.min()), float(host_s.max())
        non01 = int(((host_t != 0) & (host_t != 1)).sum())
    else:
        lo, hi, non01f = (float(x) for x in np.asarray(out))
        non01 = int(non01f)
    _raise_if_scores_out_of_unit(lo, hi)
    return non01 == 0


def _local_binned_counts(s, t, w, num_bins: int, axis: str):
    """Per-device positive/total weighted histograms over the [0, 1] score
    grid, psum-merged across the mesh axis — the shared first stage of
    every O(num_bins)-communication curve metric here."""
    # f32 cast keeps the weighted(ones) ≡ unweighted contract across
    # score dtypes: the counts path's bisected grid is f32-exact only.
    idx = jnp.clip(
        (s.astype(jnp.float32) * num_bins).astype(jnp.int32),
        0,
        num_bins - 1,
    )
    wt = w.astype(jnp.float32)
    pos = jnp.zeros(num_bins, jnp.float32).at[idx].add(
        wt * t.astype(jnp.float32)
    )
    tot = jnp.zeros(num_bins, jnp.float32).at[idx].add(wt)
    return lax.psum(pos, axis), lax.psum(tot, axis)


def _run_sharded_binary(
    weighted_builder,
    counts_builder,
    wcounts_builder,
    num_bins: int,
    mesh: Mesh,
    axis: str,
    scores,
    targets,
    weights,
    assume_01_targets: Optional[bool] = None,
    assume_split_safe_weights: Optional[bool] = None,
):
    """Shared shape check + shard_map wrapper for the 1-D histogram metrics.

    The builders are module-level factories for the per-device function;
    routing through the shared ``compiled_spmd`` memoizer keeps the jitted
    program cached across calls (a per-call closure would re-trace and
    re-compile every invocation).  Unweighted calls with verifiably 0/1
    targets run ``counts_builder`` (the binned-counts dispatch, with the
    formulation chosen at call time outside jit); weighted calls — and
    soft/non-binary targets, whose fractional-positive semantics
    (``pos += w·t``) only the scatter carries — keep the scatter
    histogram."""
    if scores.ndim != 1 or targets.ndim != 1:
        raise ValueError(
            f"scores and targets should be 1-D, got {scores.shape} / {targets.shape}."
        )
    if assume_01_targets is None:
        # ONE fused fetch validates the score range AND decides the
        # formulation; an explicit assume_01_targets skips the target
        # stat but keeps the score validation.
        from torcheval_tpu.metrics.functional._host_checks import all_concrete

        if not all_concrete(scores, targets) and scores.size:
            # Tracer inputs silently force the scatter formulation even
            # for 0/1 targets — the pod analog of the ustat tracer
            # downgrade.  Loud, once per callsite.
            from torcheval_tpu.routing import warn_route_downgrade

            warn_route_downgrade(
                "hist-01-gate",
                "the sharded histogram's 0/1-target gate cannot read "
                "values under jit (inputs are tracers); running the "
                "scatter formulation.  Pass assume_01_targets=True to "
                "keep the binned-counts dispatch reachable under jit "
                "(or False to silence this for soft targets).",
            )
        assume_01_targets = _binary_hist_gate(scores, targets)
    else:
        _check_scores_in_unit_interval(scores)
    n_local = scores.shape[0] // _axis_size(mesh, axis)
    if weights is None and assume_01_targets:
        route = _hist_route(1, n_local, num_bins)
        fn = compiled_spmd(
            _build_hist_spmd, (counts_builder, (num_bins, route)), mesh, axis
        )
        if _telemetry.ENABLED or _perfscope.ENABLED:
            # Wire payload of the psum merge: 2 × num_bins f32 counters.
            return _timed_dispatch(
                fn, "binary_hist_counts", 2 * num_bins * 4, scores, targets
            )
        return fn(scores, targets)
    if weights is not None and assume_01_targets:
        # Weighted with verifiably-0/1 targets: the Pallas payload kernel
        # when the dispatch and the weights-domain gate allow it
        # (fractional/soft targets never reach here — their semantics
        # need the scatter's ``pos += w·t``).
        use_kernel, split3 = _weighted_kernel_route(
            weights, 1, n_local, num_bins, assume_split_safe_weights
        )
        if use_kernel:
            fn = compiled_spmd(
                _build_hist_spmd,
                (wcounts_builder, (num_bins, split3)),
                mesh,
                axis,
            )
            if _telemetry.ENABLED or _perfscope.ENABLED:
                return _timed_dispatch(
                    fn,
                    "binary_hist_wcounts",
                    2 * num_bins * 4,
                    scores,
                    targets,
                    weights,
                )
            return fn(scores, targets, weights)
    if weights is None:
        weights = jnp.ones_like(scores, dtype=jnp.float32)
    fn = compiled_spmd(
        _build_hist_spmd, (weighted_builder, (num_bins,)), mesh, axis
    )
    if _telemetry.ENABLED or _perfscope.ENABLED:
        return _timed_dispatch(
            fn, "binary_hist_scatter", 2 * num_bins * 4, scores, targets, weights
        )
    return fn(scores, targets, weights)


def _hist_route(num_rows: int, n_local: int, num_bins: int) -> str:
    """Call-time binned-counts formulation choice for the histogram
    family's per-device stage (see ``binned_auc._select_binned_route`` —
    evaluated OUTSIDE jit so kill-switches are honored per call)."""
    from torcheval_tpu.metrics.functional.classification.binned_auc import (
        _select_binned_route,
    )

    return _select_binned_route(num_rows, n_local, _grid_np(num_bins))


def _build_hist_spmd(statics, mesh: Mesh, axis: str):
    """shard_map builder for the histogram family (shared-memoizer
    convention, see ``parallel._compile_cache``): ``statics`` carries the
    module-level local-builder plus its own statics tuple."""
    local_builder, local_statics = statics
    local = local_builder(*local_statics, axis)
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=PartitionSpec(axis),
            out_specs=PartitionSpec(),
            # The psum-merged outputs are replicated by construction; the
            # varying-axes checker also cannot see through pallas_call
            # (the binned-counts Pallas route runs inside this map).
            check_vma=False,
        )
    )


def sharded_auprc_histogram(
    scores: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    axis: AxisSpec = "dp",
    num_bins: int = 8192,
    weights: Optional[jax.Array] = None,
    assume_01_targets: Optional[bool] = None,
    assume_split_safe_weights: Optional[bool] = None,
) -> jax.Array:
    """Pod-scale binary average precision with O(num_bins) communication.

    Same histogram scheme as :func:`sharded_auroc_histogram` — each device
    bins its local scores (validated in [0, 1]; see `_check_scores_in_unit_interval`), ONE ``psum`` merges
    the ``2 × num_bins`` histogram, and the step-rule AP

        AP = Σ_bins ΔR_bin · P_bin

    is evaluated over descending-threshold bins on every device.  Exact
    for scores already quantized to the bin grid; error ``O(1/num_bins)``
    otherwise.  No positives → 0 (matching ``binary_auprc``).  Invariant
    to the scale of ``weights`` (like sklearn's ``sample_weight``).
    ``assume_01_targets`` as in :func:`sharded_auroc_histogram`."""

    return _run_sharded_binary(
        _build_auprc_hist_local,
        _build_auprc_hist_counts_local,
        _build_auprc_hist_wcounts_local,
        num_bins,
        mesh,
        axis,
        scores,
        targets,
        weights,
        assume_01_targets,
        assume_split_safe_weights,
    )


def _build_auprc_hist_counts_local(num_bins: int, route: str, axis: str):
    """Unweighted AP local stage through the binned-counts dispatch (see
    :func:`_build_auroc_hist_counts_local`); the cumulative counts are the
    dispatch's outputs directly, per-bin increments by differencing."""
    from torcheval_tpu.metrics.functional.classification.binned_auc import (
        _binned_counts_rows,
    )

    def local(s, t):
        # f32 cast: see _build_auroc_hist_counts_local.
        num_tp, num_fp, _, _ = _binned_counts_rows(
            s.astype(jnp.float32)[None],
            (t != 0)[None],
            _grid(num_bins),
            route=route,
        )
        cum_tp = lax.psum(num_tp[0], axis).astype(jnp.float32)[::-1]
        cum_all = (
            lax.psum(num_tp[0] + num_fp[0], axis).astype(jnp.float32)[::-1]
        )
        delta_tp = jnp.diff(cum_tp, prepend=0.0)
        return _binned_step_ap(delta_tp, cum_tp, cum_all)

    return local


def _build_auprc_hist_local(num_bins: int, axis: str):
    def local(s, t, w):
        pos, tot = _local_binned_counts(s, t, w, num_bins, axis)
        # Descending-threshold bins: cumulative TP / predicted-positive
        # counts at each bin end, precision there, weighted by the bin's
        # recall increment.
        delta_tp = pos[::-1]
        cum_tp = jnp.cumsum(delta_tp)
        cum_all = jnp.cumsum(tot[::-1])
        return _binned_step_ap(delta_tp, cum_tp, cum_all)

    return local


def sharded_multiclass_auroc_histogram(
    scores: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    axis: AxisSpec = "dp",
    num_bins: int = 2048,
    average: Optional[str] = "macro",
    weights: Optional[jax.Array] = None,
    assume_split_safe_weights: Optional[bool] = None,
) -> jax.Array:
    """Pod-scale one-vs-rest multiclass AUROC — the BASELINE north-star
    workload shape (1000-class, samples sharded over the pod) with
    O(C × num_bins) communication instead of gathering every raw sample.

    Each device bins its local ``(n_local, C)`` scores (validated in
    [0, 1]; see `_check_scores_in_unit_interval`) into per-class
    cumulative threshold counts through the 3-way binned-counts dispatch
    (``binned_auc._binned_counts_rows`` — the (C, n_local) rows are the
    same shape family its Pallas MXU kernel was measured on; the old
    per-class scatter histogram serialized on TPU at 1.76 s/step for the
    (2^17, 1000)×2048 workload), ONE ``psum`` merges the
    ``(C, 2 × num_bins)`` statistics across the mesh, and every device
    integrates the binned ROC curves — all classes vectorized.
    Quantization caveat as :func:`sharded_auroc_histogram`.

    ``weights`` (per-sample, ``(N,)``) weight every class's TP/FP mass
    like sklearn's ``sample_weight``.  The weighted local stage runs the
    Pallas payload kernel (``pallas_binned._binned_wcount_kernel``) when
    the dispatch and the weights-domain gate allow it —
    ``assume_split_safe_weights`` pins the gate under jit — and a
    vmapped per-class scatter otherwise (always correct; serializing on
    TPU, so large weighted pods want the kernel route).  Weighted
    results follow the kernel's f32 summation-order contract (~1e-6 vs
    the scatter; weighted(ones) is BITWISE equal to unweighted on the
    kernel route).
    """
    if scores.ndim != 2 or targets.ndim != 1:
        raise ValueError(
            "scores should be (N, C) and targets (N,), got "
            f"{scores.shape} / {targets.shape}."
        )
    _check_scores_in_unit_interval(scores)
    num_classes = scores.shape[1]
    n_local = scores.shape[0] // _axis_size(mesh, axis)
    if weights is not None:
        use_kernel, split3 = _weighted_kernel_route(
            weights, num_classes, n_local, num_bins, assume_split_safe_weights
        )
        builder, statics = (
            (_build_mc_hist_wcounts_local,
             (num_bins, num_classes, average, split3))
            if use_kernel
            else (_build_mc_hist_wscatter_local,
                  (num_bins, num_classes, average))
        )
        fn = compiled_spmd(
            _build_hist_spmd, (builder, statics), mesh, axis
        )
        if _telemetry.ENABLED or _perfscope.ENABLED:
            # psum payload: (C, 2 × num_bins) f32 per-class counters.
            return _timed_dispatch(
                fn,
                "multiclass_hist_weighted",
                num_classes * 2 * num_bins * 4,
                scores,
                targets,
                weights,
            )
        return fn(scores, targets, weights)
    route = _hist_route(num_classes, n_local, num_bins)
    fn = compiled_spmd(
        _build_hist_spmd,
        (_build_mc_hist_local, (num_bins, num_classes, average, route)),
        mesh,
        axis,
    )
    if _telemetry.ENABLED or _perfscope.ENABLED:
        return _timed_dispatch(
            fn,
            "multiclass_hist_counts",
            num_classes * 2 * num_bins * 4,
            scores,
            targets,
        )
    return fn(scores, targets)


def _build_mc_hist_local(
    num_bins: int, num_classes: int, average, route: str, axis: str
):
    from torcheval_tpu.metrics.functional.classification._sort_scan import (
        class_hits,
    )
    from torcheval_tpu.metrics.functional.classification.binned_auc import (
        _binned_counts_rows,
    )

    def local(s, t):
        # f32 cast: see _build_auroc_hist_counts_local.
        num_tp, num_fp, _, _ = _binned_counts_rows(
            s.T.astype(jnp.float32),
            class_hits(t, num_classes),
            _grid(num_bins),
            route=route,
        )
        return _mc_roc_from_counts(
            lax.psum(num_tp, axis).astype(jnp.float32),
            lax.psum(num_fp, axis).astype(jnp.float32),
            num_classes,
            average,
        )

    return local


def _mc_roc_from_counts(num_tp, num_fp, num_classes: int, average):
    """Shared weighted/unweighted epilogue: descending-threshold
    cumulative curves from psum-merged per-threshold counts → per-class
    binned ROC areas → optional macro mean."""
    zero = jnp.zeros((num_classes, 1), jnp.float32)
    cum_tp = jnp.concatenate([zero, num_tp[:, ::-1]], axis=-1)
    cum_fp = jnp.concatenate([zero, num_fp[:, ::-1]], axis=-1)
    aurocs = _binned_roc_area(cum_tp, cum_fp)
    return aurocs.mean() if average == "macro" else aurocs


def _build_mc_hist_wcounts_local(
    num_bins: int, num_classes: int, average, split3: bool, axis: str
):
    """Weighted multiclass local stage through the weighted Pallas
    binned kernel — ONE kernel pass over the (C, n_local) class rows
    with the per-sample weights shipped once (shared across rows), vs
    C per-class scatter histograms."""
    from torcheval_tpu.metrics.functional.classification._sort_scan import (
        class_hits,
    )
    from torcheval_tpu.ops.pallas_binned import (
        _pallas_binned_weighted_counts_jit,
        has_pallas,
    )

    def local(s, t, w):
        w_tp, w_fp, _, _ = _pallas_binned_weighted_counts_jit(
            s.T.astype(jnp.float32),
            class_hits(t, num_classes),
            w.astype(jnp.float32),
            _grid(num_bins),
            interpret=not has_pallas(),
            split3=split3,
        )
        return _mc_roc_from_counts(
            lax.psum(w_tp, axis), lax.psum(w_fp, axis), num_classes, average
        )

    return local


def _build_mc_hist_wscatter_local(
    num_bins: int, num_classes: int, average, axis: str
):
    """Weighted multiclass fallback: a vmapped per-class scatter
    histogram (always correct — tracer weights, subnormal weights, or
    work too small for the kernel route).  Bins by the same
    ``clip(floor(s·num_bins))`` rule as the binary scatter path, which
    the bisected ``_grid_np`` grid makes set-identical to the kernel's
    ``s ≥ t_j`` counting."""
    from torcheval_tpu.metrics.functional.classification._sort_scan import (
        class_hits,
    )

    def local(s, t, w):
        wt = w.astype(jnp.float32)
        hits = class_hits(t, num_classes).astype(jnp.float32)  # (C, n)
        idx = jnp.clip(
            (s.astype(jnp.float32) * num_bins).astype(jnp.int32),
            0,
            num_bins - 1,
        ).T  # (C, n)

        def one_class(idx_c, hit_c):
            pos = jnp.zeros(num_bins, jnp.float32).at[idx_c].add(wt * hit_c)
            tot = jnp.zeros(num_bins, jnp.float32).at[idx_c].add(wt)
            return pos, tot

        pos, tot = jax.vmap(one_class)(idx, hits)  # (C, num_bins) each
        per_bin_tp = lax.psum(pos, axis)
        per_bin_fp = lax.psum(tot - pos, axis)
        # Per-threshold counts are the reversed-cumulative per-bin mass
        # (the `_grid_np` contract), matching the kernel epilogue.
        num_tp = jnp.cumsum(per_bin_tp[:, ::-1], axis=-1)[:, ::-1]
        num_fp = jnp.cumsum(per_bin_fp[:, ::-1], axis=-1)[:, ::-1]
        return _mc_roc_from_counts(num_tp, num_fp, num_classes, average)

    return local
