"""Shared memoizer for the parallel layer's ``jit(shard_map(...))`` programs.

``jax.jit`` caches by function identity, so building a shard_map closure
inside a public wrapper would miss that cache and re-trace — and, through
a remote compiler, re-compile — on EVERY call (measured ~15 s/call vs
~1.8 s of device work for the 1000-class ustat at (2^16, 1000) on v5e).
Keying on the module-level builder + hashable statics + mesh returns the
already-compiled program instead.

One builder convention for every call site: ``builder(statics, mesh,
axis) -> jitted fn``, with ``statics`` a hashable tuple.

The memoizer is a :class:`LruCache` — a bounded, eviction-counting LRU
shared with the engine's per-signature scan cache and the serve layer's
program cache.  A resident server cannot tolerate unbounded compile
caches: capacity comes from ``TORCHEVAL_TPU_COMPILE_CACHE_CAP`` (read
when the cache is constructed; default 256) and the oldest entry is
dropped past it, counted in ``telemetry.report()``'s ``spmd_cache``
section and on the bus as ``spmd_cache_evict`` events.

Each lookup is also a telemetry hook (``spmd_cache_hit`` /
``spmd_cache_miss`` events): with the bus enabled, the hit/miss outcome
is recorded around the memoized call; disabled, the lookup is a bare
dict probe behind a single branch.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Hashable, NamedTuple, Optional

from jax.sharding import Mesh

from torcheval_tpu import _flags
from torcheval_tpu.telemetry import events as _telemetry


class SpmdCacheInfo(NamedTuple):
    """``functools.CacheInfo`` plus the memory footprint of the cached
    programs and the eviction count: ``peak_bytes`` is the largest
    ``memory_analysis()`` peak perfscope priced across the ``spmd:*``
    programs (0 until perfscope has profiled one — enable with
    ``TORCHEVAL_TPU_PERFSCOPE=1``); ``evictions`` counts entries dropped
    past the LRU capacity."""

    hits: int
    misses: int
    maxsize: int
    currsize: int
    peak_bytes: int = 0
    evictions: int = 0


def _capacity_from_flag() -> int:
    value = _flags.get("COMPILE_CACHE_CAP")
    return value if isinstance(value, int) and value > 0 else 256


class LruCache:
    """Bounded LRU memoizer with hit/miss/eviction counters.

    The shared shape for every compile-adjacent cache in the library:
    the SPMD program memoizer below, ``engine.scan.ScanRunner``'s
    per-signature set, and the serve layer's cross-tenant program cache.
    ``capacity=None`` reads ``TORCHEVAL_TPU_COMPILE_CACHE_CAP`` at
    construction.  ``telemetry_events=True`` records each lookup (and
    each eviction) on the bus behind the usual one-branch guard.

    Thread-safe: the serve layer probes its program cache from a worker
    thread while tests drive ``compiled_spmd`` from the main thread.
    """

    def __init__(
        self,
        capacity: Optional[int] = None,
        *,
        name: str = "cache",
        telemetry_events: bool = False,
    ) -> None:
        self.name = name
        self.capacity = (
            capacity if capacity and capacity > 0 else _capacity_from_flag()
        )
        self._events = telemetry_events
        self._data: "OrderedDict[Hashable, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def __contains__(self, key: Hashable) -> bool:
        with self._lock:
            return key in self._data

    def get(self, key: Hashable, default: Any = None) -> Any:
        """Probe without counting a miss-for-insert: refreshes recency
        and counts a hit when present, counts a miss when absent."""
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                self.hits += 1
                hit = True
                value = self._data[key]
            else:
                self.misses += 1
                hit = False
                value = default
        if self._events and _telemetry.ENABLED:
            _telemetry.record_cache(hit=hit)
        return value

    def put(self, key: Hashable, value: Any) -> None:
        evicted = False
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            if len(self._data) > self.capacity:
                self._data.popitem(last=False)
                self.evictions += 1
                evicted = True
        if evicted and self._events and _telemetry.ENABLED:
            _telemetry.record_cache(hit=False, evicted=True)

    def get_or_create(self, key: Hashable, factory: Callable[[], Any]) -> Any:
        """The memoizer: counts a hit or runs ``factory`` and inserts
        (one miss, possibly one eviction).  ``factory`` runs outside the
        lock — compiles are slow and must not serialize unrelated
        lookups; a concurrent duplicate insert is harmless (last write
        wins, both values are equivalent programs)."""
        sentinel = _MISSING
        value = self.get(key, sentinel)
        if value is not sentinel:
            return value
        value = factory()
        self.put(key, value)
        return value

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self.hits = 0
            self.misses = 0
            self.evictions = 0

    def cache_info(self) -> SpmdCacheInfo:
        with self._lock:
            return SpmdCacheInfo(
                self.hits,
                self.misses,
                self.capacity,
                len(self._data),
                0,
                self.evictions,
            )


_MISSING = object()

_SPMD_CACHE = LruCache(name="spmd", telemetry_events=False)


def compiled_spmd(builder, statics, mesh: Mesh, axis: str):
    key = (builder, statics, mesh, axis)
    fn = _SPMD_CACHE.get(key, _MISSING)
    hit = fn is not _MISSING
    if not hit:
        fn = builder(statics, mesh, axis)
        _SPMD_CACHE.put(key, fn)
    if _telemetry.ENABLED:
        _telemetry.record_cache(hit=hit)
    return fn


# ``compiled_spmd`` was an lru_cache object itself before the telemetry
# wrapper; callers (``parallel/exact.py``, tests) introspect it like one.
compiled_spmd.cache_info = _SPMD_CACHE.cache_info
compiled_spmd.cache_clear = _SPMD_CACHE.clear


def spmd_cache_info() -> SpmdCacheInfo:
    """Hit/miss/eviction counters of the shared sharded-program memoizer
    — a :class:`SpmdCacheInfo` ``(hits, misses, maxsize, currsize,
    peak_bytes, evictions)``.  A steady-state eval loop should show hits
    climbing and misses flat; climbing misses mean program churn (e.g.
    rebuilding meshes per step, which keys a fresh entry every call);
    climbing evictions mean the working set exceeds
    ``TORCHEVAL_TPU_COMPILE_CACHE_CAP`` and programs are being recompiled
    in rotation.  ``peak_bytes`` reports the largest perfscope-priced
    memory peak among the cached programs.  Surfaced by
    :func:`torcheval_tpu.routing.hot_path_stats`."""
    info = _SPMD_CACHE.cache_info()
    peak = 0
    for program, entry in _telemetry.aggregates()["perf"].items():
        if program.startswith("spmd:"):
            peak = max(peak, entry["peak_bytes"])
    return SpmdCacheInfo(
        info.hits,
        info.misses,
        info.maxsize,
        info.currsize,
        peak,
        info.evictions,
    )


def spmd_cache_clear() -> None:
    """Drop every memoized sharded program (test isolation hook)."""
    _SPMD_CACHE.clear()
