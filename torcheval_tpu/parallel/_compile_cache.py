"""Shared memoizer for the parallel layer's ``jit(shard_map(...))`` programs.

``jax.jit`` caches by function identity, so building a shard_map closure
inside a public wrapper would miss that cache and re-trace — and, through
a remote compiler, re-compile — on EVERY call (measured ~15 s/call vs
~1.8 s of device work for the 1000-class ustat at (2^16, 1000) on v5e).
Keying on the module-level builder + hashable statics + mesh returns the
already-compiled program instead.

One builder convention for every call site: ``builder(statics, mesh,
axis) -> jitted fn``, with ``statics`` a hashable tuple.

Each lookup is also a telemetry hook (``spmd_cache_hit`` /
``spmd_cache_miss`` events): with the bus enabled, the miss counter is
diffed around the memoized call; disabled, the lookup is the bare
``lru_cache`` hit it always was behind a single branch.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

from jax.sharding import Mesh

from torcheval_tpu.telemetry import events as _telemetry


class SpmdCacheInfo(NamedTuple):
    """``functools.CacheInfo`` plus the memory footprint of the cached
    programs: ``peak_bytes`` is the largest ``memory_analysis()`` peak
    perfscope priced across the ``spmd:*`` programs (0 until perfscope
    has profiled one — enable with ``TORCHEVAL_TPU_PERFSCOPE=1``)."""

    hits: int
    misses: int
    maxsize: int
    currsize: int
    peak_bytes: int = 0


@lru_cache(maxsize=256)
def _compiled_spmd_cached(builder, statics, mesh: Mesh, axis: str):
    return builder(statics, mesh, axis)


def compiled_spmd(builder, statics, mesh: Mesh, axis: str):
    if not _telemetry.ENABLED:
        return _compiled_spmd_cached(builder, statics, mesh, axis)
    misses_before = _compiled_spmd_cached.cache_info().misses
    fn = _compiled_spmd_cached(builder, statics, mesh, axis)
    _telemetry.record_cache(
        hit=_compiled_spmd_cached.cache_info().misses == misses_before
    )
    return fn


# ``compiled_spmd`` was the lru_cache object itself before the telemetry
# wrapper; callers (``parallel/exact.py``, tests) introspect it like one.
compiled_spmd.cache_info = _compiled_spmd_cached.cache_info
compiled_spmd.cache_clear = _compiled_spmd_cached.cache_clear


def spmd_cache_info() -> SpmdCacheInfo:
    """Hit/miss counters of the shared sharded-program memoizer — a
    :class:`SpmdCacheInfo` ``(hits, misses, maxsize, currsize,
    peak_bytes)``.  A steady-state eval loop should show hits climbing
    and misses flat; climbing misses mean program churn (e.g. rebuilding
    meshes per step, which keys a fresh entry every call).
    ``peak_bytes`` reports the largest perfscope-priced memory peak
    among the cached programs.  Surfaced by
    :func:`torcheval_tpu.routing.hot_path_stats`."""
    info = _compiled_spmd_cached.cache_info()
    peak = 0
    for program, entry in _telemetry.aggregates()["perf"].items():
        if program.startswith("spmd:"):
            peak = max(peak, entry["peak_bytes"])
    return SpmdCacheInfo(
        info.hits, info.misses, info.maxsize, info.currsize, peak
    )


def spmd_cache_clear() -> None:
    """Drop every memoized sharded program (test isolation hook)."""
    _compiled_spmd_cached.cache_clear()
