"""Shared memoizer for the parallel layer's ``jit(shard_map(...))`` programs.

``jax.jit`` caches by function identity, so building a shard_map closure
inside a public wrapper would miss that cache and re-trace — and, through
a remote compiler, re-compile — on EVERY call (measured ~15 s/call vs
~1.8 s of device work for the 1000-class ustat at (2^16, 1000) on v5e).
Keying on the module-level builder + hashable statics + mesh returns the
already-compiled program instead.

One builder convention for every call site: ``builder(statics, mesh,
axis) -> jitted fn``, with ``statics`` a hashable tuple.
"""

from __future__ import annotations

from functools import lru_cache

from jax.sharding import Mesh


@lru_cache(maxsize=256)
def compiled_spmd(builder, statics, mesh: Mesh, axis: str):
    return builder(statics, mesh, axis)


def spmd_cache_info():
    """Hit/miss counters of the shared sharded-program memoizer — a
    ``functools.CacheInfo`` ``(hits, misses, maxsize, currsize)``.  A
    steady-state eval loop should show hits climbing and misses flat;
    climbing misses mean program churn (e.g. rebuilding meshes per step,
    which keys a fresh entry every call).  Surfaced by
    :func:`torcheval_tpu.routing.hot_path_stats`."""
    return compiled_spmd.cache_info()


def spmd_cache_clear() -> None:
    """Drop every memoized sharded program (test isolation hook)."""
    compiled_spmd.cache_clear()
