"""Shared memoizer for the parallel layer's ``jit(shard_map(...))`` programs.

``jax.jit`` caches by function identity, so building a shard_map closure
inside a public wrapper would miss that cache and re-trace — and, through
a remote compiler, re-compile — on EVERY call (measured ~15 s/call vs
~1.8 s of device work for the 1000-class ustat at (2^16, 1000) on v5e).
Keying on the module-level builder + hashable statics + mesh returns the
already-compiled program instead.

One builder convention for every call site: ``builder(statics, mesh,
axis) -> jitted fn``, with ``statics`` a hashable tuple.
"""

from __future__ import annotations

from functools import lru_cache

from jax.sharding import Mesh


@lru_cache(maxsize=256)
def compiled_spmd(builder, statics, mesh: Mesh, axis: str):
    return builder(statics, mesh, axis)
