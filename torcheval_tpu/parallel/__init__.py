"""Mesh / SPMD parallelism — the TPU-native distributed execution layer.

The reference's distributed story is one process per rank, each holding its
own ``Metric`` object, synchronized by pickling whole objects through
``torch.distributed`` object collectives (reference ``toolkit.py:247-255``).
A TPU pod runs the opposite model: one logical SPMD program over a
``jax.sharding.Mesh``; arrays are sharded, and XLA inserts the collectives
(``psum`` / ``all_gather``) that ride ICI/DCN.

This package provides that layer:

* :mod:`torcheval_tpu.parallel.mesh` — mesh construction and batch-sharding
  helpers (``make_mesh``, ``shard_batch``, ``replicate``, and the
  ragged-stream ``bucket_shard_batch`` that pads to a device-divisible
  power-of-two bucket before sharding).
* :mod:`torcheval_tpu.parallel.sync` — explicit in-jit state sync:
  ``make_synced_update`` wraps any functional sufficient-statistic kernel in
  ``shard_map`` so each device reduces its local batch shard and one fused
  collective merges the partials (``psum``/``pmax``/``pmin`` chosen per state,
  mirroring each metric's ``merge_state`` semantics); ``mesh_merge_states``
  is the raw per-leaf collective for use inside user ``shard_map`` code;
  plus the O(bins)-wire quantized ``sharded_*_histogram`` curve metrics.
* :mod:`torcheval_tpu.parallel.exact` — pod-scale *exact* curve metrics:
  the gather-exact family (bit-for-bit equal to the single-device
  kernels) and the Mann-Whitney ustat family (ships only the minority
  class — O(min(#pos, #neg)) wire).
* :mod:`torcheval_tpu.parallel.fleet_merge` — the elastic hierarchical
  state merge over the host wire: tree/ring reduction with per-level
  retry deadlines, live membership (unresponsive hosts are excised and
  the result labelled partial instead of the run dying), and optional
  sketch-compressed payloads; the front door is
  ``toolkit.sync_and_compute(..., topology="tree")``.

Note the *implicit* path needs no code at all: class metrics already accept
mesh-sharded inputs — their update kernels are jitted pure functions, so
XLA's partitioner auto-inserts the same collectives (verified by
``tests/metrics/parallel/test_mesh_sync.py``).  Use the explicit path when
you want guaranteed single-collective sync or per-shard control.
"""

from torcheval_tpu.parallel._compile_cache import (
    spmd_cache_clear,
    spmd_cache_info,
)
from torcheval_tpu.parallel.mesh import (
    bucket_shard_batch,
    device_count,
    make_mesh,
    replicate,
    shard_batch,
)
from torcheval_tpu.parallel.exact import (
    sharded_binary_auprc_exact,
    sharded_binary_auprc_ustat,
    sharded_binary_auroc_exact,
    sharded_binary_auroc_ustat,
    sharded_multiclass_auroc_exact,
    sharded_multiclass_auroc_ustat,
    sharded_multitask_auprc_exact,
    sharded_multitask_auroc_exact,
)
from torcheval_tpu.parallel.fleet_merge import (
    MergeOutcome,
    MergePolicy,
    PendingMerge,
    fleet_merge,
)
from torcheval_tpu.parallel.sync import (
    make_synced_update,
    mesh_merge_states,
    sharded_auprc_histogram,
    sharded_auroc_histogram,
    sharded_multiclass_auroc_histogram,
)

__all__ = [
    "MergeOutcome",
    "MergePolicy",
    "PendingMerge",
    "bucket_shard_batch",
    "device_count",
    "fleet_merge",
    "make_mesh",
    "make_synced_update",
    "mesh_merge_states",
    "replicate",
    "shard_batch",
    "sharded_auprc_histogram",
    "sharded_auroc_histogram",
    "sharded_binary_auprc_exact",
    "sharded_binary_auprc_ustat",
    "sharded_binary_auroc_exact",
    "sharded_binary_auroc_ustat",
    "sharded_multiclass_auroc_exact",
    "sharded_multiclass_auroc_histogram",
    "sharded_multiclass_auroc_ustat",
    "sharded_multitask_auprc_exact",
    "sharded_multitask_auroc_exact",
    "spmd_cache_clear",
    "spmd_cache_info",
]
