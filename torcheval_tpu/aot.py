"""Ahead-of-time warmup for the metric update hot path.

A jit-cached update program is only free after its first call; with M
distinct batch shapes in an evaluation stream, the first pass through the
data pays M traces + compiles (~15 s each through a remote TPU
compiler).  Bucketing (``metrics/_bucket.py``) shrinks M to
O(log max_batch) — :func:`warmup` then moves even those compiles off the
measured path by replaying a representative batch through every
reachable bucket size before the real stream starts.

Pairs with ``TORCHEVAL_TPU_CACHE_DIR``
(:func:`torcheval_tpu.ops._flags.configure_persistent_cache`): warmed
programs land in the persistent compile cache, so the NEXT process skips
the compiles entirely.

Trace/compile accounting lives in :mod:`torcheval_tpu._stats` —
:func:`trace_count` after a warmed stream shows zero additional update
traces.
"""

from typing import Any, Iterable, Optional, Sequence, Tuple

import numpy as np

from torcheval_tpu._stats import (  # noqa: F401  (re-exported)
    reset_trace_count,
    trace_count,
    trace_counts,
)
from torcheval_tpu.metrics._bucket import bucket_sizes

__all__ = [
    "warmup",
    "trace_count",
    "trace_counts",
    "reset_trace_count",
    "bucket_sizes",
]


def _tile_to(a: np.ndarray, n: int) -> np.ndarray:
    """A length-``n`` batch with the same trailing shape/dtype as ``a``,
    cycling ``a``'s rows (values are irrelevant for compilation; cycling
    real rows keeps class indices in their valid range for the host-side
    value checks on the update path)."""
    if a.shape[0] == n:
        return a
    reps = -(-n // max(a.shape[0], 1))
    return np.concatenate([a] * reps, axis=0)[:n]


def warmup(
    metric_or_collection: Any,
    example_batch: Sequence[Any],
    *,
    max_batch: Optional[int] = None,
    sizes: Optional[Iterable[int]] = None,
    fused: Optional[bool] = None,
    autotune: bool = False,
) -> Tuple[int, ...]:
    """Pre-compile every update program a ragged evaluation stream can
    reach, so the stream itself runs trace-free.

    ``example_batch`` is one representative update's positional args
    (e.g. ``(input, target)``); its leading dim seeds the size sweep.
    ``max_batch`` extends the sweep to the largest batch the stream will
    produce (default: the example's size); ``sizes`` overrides the sweep
    entirely with explicit batch sizes.  For a bucketed
    ``MetricCollection`` the swept sizes are the reachable bucket sizes
    — O(log max_batch) of them — and each warmed program is exactly the
    masked program later updates dispatch to.  ``fused`` picks the entry
    point for collections (default: ``fused_update`` when its members
    allow it); plain metrics always warm ``update``.

    State is snapshotted before and restored after (checkpoint
    round-trip), so warmup is invisible to the metric values.  Returns
    the tuple of batch sizes actually warmed.

    An :class:`~torcheval_tpu.engine.Evaluator` delegates to its own
    :meth:`~torcheval_tpu.engine.Evaluator.warmup` — the swept shapes
    become stacked scan-block programs instead of per-batch programs
    (``fused`` does not apply there).

    ``autotune=True`` additionally RACES the top-2 candidate routes for
    each ambiguous routing decision on the real warmed shapes —
    megakernel on/off, wavefront pallas/xla, CM row-chunk size,
    sketch-vs-sort — and records the wall-clock winners in the persisted
    route-cost store (:mod:`torcheval_tpu.routing_autotune`), so later
    ``routing`` decisions pick by measured cost instead of the static
    heuristics.  The race compiles at most ``TORCHEVAL_TPU_AUTOTUNE_
    PROBE_BUDGET`` extra candidate programs (default 8) and skips
    decisions the store already measured for this shape/flag/device
    context; an explicit ``TORCHEVAL_TPU_AUTOTUNE=0`` kill-switch
    outranks the argument and skips racing entirely.
    """
    from torcheval_tpu.engine import Evaluator
    from torcheval_tpu.metrics.collection import MetricCollection

    if isinstance(metric_or_collection, Evaluator):
        return metric_or_collection.warmup(
            example_batch, max_batch=max_batch, sizes=sizes
        )

    obj = metric_or_collection
    arrays = [np.asarray(a) for a in example_batch]
    if not arrays:
        raise ValueError("example_batch must contain at least one array.")
    n = arrays[0].shape[0]
    top = int(max_batch) if max_batch is not None else n

    is_collection = isinstance(obj, MetricCollection)
    if sizes is not None:
        sweep = tuple(int(s) for s in sizes)
    elif is_collection and obj._bucket:
        sweep = bucket_sizes(top, min_bucket=obj._min_bucket)
    else:
        sweep = (top,)

    if is_collection:
        if fused is None:
            try:
                obj._check_fusable()
                fused = True
            except ValueError:
                fused = False
        entry = obj.fused_update if fused else obj.update
    else:
        entry = obj.update

    # state_dict() hands back fresh, never-donated copies (metric.py), so
    # the snapshot survives donated warmup updates untouched.
    snapshot = obj.state_dict()
    try:
        for b in sweep:
            entry(*(_tile_to(a, b) for a in arrays))
        if autotune:
            _race_routes(obj, entry, arrays, max(sweep), is_collection)
    finally:
        obj.load_state_dict(snapshot)
    return tuple(sweep)


def _race_routes(obj, entry, arrays, top, is_collection) -> int:
    """Race the top-2 candidates of each ambiguous routing decision on
    ``obj``'s real warmed shape and persist the wall-clock outcomes as
    ``site="race"`` rows in the route-cost store.  Returns the number of
    candidate timings spent (0 when the store layer is explicitly off).

    Candidates are forced through the public flag overrides
    (``_flags.overridden``), so each one compiles and dispatches exactly
    the program a user pinning that flag would get; the decided flag is
    masked out of the stored route-token context
    (``routing_autotune._context_token``), so the forced value never
    makes the row unbindable at pick time.  State mutation from the race
    calls is erased by :func:`warmup`'s snapshot restore."""
    import time

    import jax

    from torcheval_tpu import _flags
    from torcheval_tpu import routing_autotune as _autotune
    from torcheval_tpu.ops import _flags as _oflags

    if _oflags.autotune_mode() is False:
        return 0  # the explicit kill-switch outranks the argument
    if not _autotune.ENABLED:
        _autotune.enable()

    batch = tuple(_tile_to(a, top) for a in arrays)
    signature = _autotune.batch_signature(batch)
    budget = _autotune.probe_budget()
    spent = 0

    def _timed(call, stateful) -> float:
        call()  # untimed: pays the trace + compile
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            out = call()
            jax.block_until_ready((out, stateful.state_dict()))
            best = min(best, time.perf_counter() - t0)
        return best

    def _race(decision, sig, candidates):
        """candidates: [(choice, thunk, stateful), ...]."""
        nonlocal spent
        if spent + len(candidates) > budget:
            return
        pref = _autotune.preference(decision, sig)
        if pref is not None and pref["kind"] == "measured":
            return  # already raced for this shape/flag/device context
        for choice, call, stateful in candidates:
            try:
                seconds = _timed(call, stateful)
            except Exception:  # pragma: no cover - candidate unsupported
                continue  # a route that cannot run never wins a race
            if _autotune.ENABLED:
                _autotune.record_measurement(
                    decision, choice, sig, seconds, site="race"
                )
            spent += 1

    def _under(flag, raw):
        def call():
            with _flags.overridden(flag, raw):
                entry(*batch)
            return None

        return call

    members = list(obj._metrics.values()) if is_collection else [obj]

    # Megakernel on/off — only when the forced-on plan actually covers
    # this collection (otherwise there is nothing ambiguous to race).
    if is_collection and getattr(entry, "__name__", "") == "fused_update":
        from torcheval_tpu.ops import _mega_plan

        with _flags.overridden("MEGAKERNEL", "1"):
            plan = _mega_plan.plan_for(
                obj._metrics, batch, {}, obj._slices
            )
        if plan is not None:
            _race(
                "megakernel",
                signature,
                [
                    ("mega", _under("MEGAKERNEL", "1"), obj),
                    ("fused", _under("MEGAKERNEL", "0"), obj),
                ],
            )

    # CM row-chunk size: flag default vs 2x, for the matmul slab family.
    _CM_CLASSES = {
        "MulticlassConfusionMatrix",
        "BinaryConfusionMatrix",
        "MulticlassF1Score",
        "MulticlassPrecision",
        "MulticlassRecall",
    }
    if any(type(m).__name__ in _CM_CLASSES for m in members):
        base = _oflags.cm_row_chunk()
        _race(
            "cm_row_chunk",
            "*",
            [
                (str(base), _under("CM_ROW_CHUNK", str(base)), obj),
                (str(base * 2), _under("CM_ROW_CHUNK", str(base * 2)), obj),
            ],
        )

    # Wavefront pallas vs lax.scan for the device text family.
    if any(
        type(m).__module__.startswith("torcheval_tpu.metrics.text")
        for m in members
    ):
        _race(
            "wavefront",
            "*",
            [
                ("pallas", _under("WAVEFRONT", "1"), obj),
                ("scan", _under("WAVEFRONT", "0"), obj),
            ],
        )

    # Sketch vs sort: construction-time state layout, so the race runs on
    # fresh twins (runtime picks stay advice-only — see routing_autotune).
    if not is_collection and type(obj).__name__ in (
        "BinaryAUROC",
        "BinaryAUPRC",
    ):
        try:
            twins = [
                ("sketch", type(obj)(sketch=True)),
                ("sort", type(obj)(sketch=False)),
            ]
        except Exception:  # pragma: no cover - exotic subclass ctor
            twins = []
        if twins:
            # The sort path defers its cost to compute(), so the raced
            # step is one update AND one compute — the real per-batch
            # cost of a stream that reads the metric out each step.
            def _step(t):
                t.update(*batch)
                return t.compute()

            _race(
                "rank_sketch",
                signature,
                [
                    (choice, lambda t=twin: _step(t), twin)
                    for choice, twin in twins
                ],
            )

    _autotune.flush()
    return spent
