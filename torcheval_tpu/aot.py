"""Ahead-of-time warmup for the metric update hot path.

A jit-cached update program is only free after its first call; with M
distinct batch shapes in an evaluation stream, the first pass through the
data pays M traces + compiles (~15 s each through a remote TPU
compiler).  Bucketing (``metrics/_bucket.py``) shrinks M to
O(log max_batch) — :func:`warmup` then moves even those compiles off the
measured path by replaying a representative batch through every
reachable bucket size before the real stream starts.

Pairs with ``TORCHEVAL_TPU_CACHE_DIR``
(:func:`torcheval_tpu.ops._flags.configure_persistent_cache`): warmed
programs land in the persistent compile cache, so the NEXT process skips
the compiles entirely.

Trace/compile accounting lives in :mod:`torcheval_tpu._stats` —
:func:`trace_count` after a warmed stream shows zero additional update
traces.
"""

from typing import Any, Iterable, Optional, Sequence, Tuple

import numpy as np

from torcheval_tpu._stats import (  # noqa: F401  (re-exported)
    reset_trace_count,
    trace_count,
    trace_counts,
)
from torcheval_tpu.metrics._bucket import bucket_sizes

__all__ = [
    "warmup",
    "trace_count",
    "trace_counts",
    "reset_trace_count",
    "bucket_sizes",
]


def _tile_to(a: np.ndarray, n: int) -> np.ndarray:
    """A length-``n`` batch with the same trailing shape/dtype as ``a``,
    cycling ``a``'s rows (values are irrelevant for compilation; cycling
    real rows keeps class indices in their valid range for the host-side
    value checks on the update path)."""
    if a.shape[0] == n:
        return a
    reps = -(-n // max(a.shape[0], 1))
    return np.concatenate([a] * reps, axis=0)[:n]


def warmup(
    metric_or_collection: Any,
    example_batch: Sequence[Any],
    *,
    max_batch: Optional[int] = None,
    sizes: Optional[Iterable[int]] = None,
    fused: Optional[bool] = None,
) -> Tuple[int, ...]:
    """Pre-compile every update program a ragged evaluation stream can
    reach, so the stream itself runs trace-free.

    ``example_batch`` is one representative update's positional args
    (e.g. ``(input, target)``); its leading dim seeds the size sweep.
    ``max_batch`` extends the sweep to the largest batch the stream will
    produce (default: the example's size); ``sizes`` overrides the sweep
    entirely with explicit batch sizes.  For a bucketed
    ``MetricCollection`` the swept sizes are the reachable bucket sizes
    — O(log max_batch) of them — and each warmed program is exactly the
    masked program later updates dispatch to.  ``fused`` picks the entry
    point for collections (default: ``fused_update`` when its members
    allow it); plain metrics always warm ``update``.

    State is snapshotted before and restored after (checkpoint
    round-trip), so warmup is invisible to the metric values.  Returns
    the tuple of batch sizes actually warmed.

    An :class:`~torcheval_tpu.engine.Evaluator` delegates to its own
    :meth:`~torcheval_tpu.engine.Evaluator.warmup` — the swept shapes
    become stacked scan-block programs instead of per-batch programs
    (``fused`` does not apply there).
    """
    from torcheval_tpu.engine import Evaluator
    from torcheval_tpu.metrics.collection import MetricCollection

    if isinstance(metric_or_collection, Evaluator):
        return metric_or_collection.warmup(
            example_batch, max_batch=max_batch, sizes=sizes
        )

    obj = metric_or_collection
    arrays = [np.asarray(a) for a in example_batch]
    if not arrays:
        raise ValueError("example_batch must contain at least one array.")
    n = arrays[0].shape[0]
    top = int(max_batch) if max_batch is not None else n

    is_collection = isinstance(obj, MetricCollection)
    if sizes is not None:
        sweep = tuple(int(s) for s in sizes)
    elif is_collection and obj._bucket:
        sweep = bucket_sizes(top, min_bucket=obj._min_bucket)
    else:
        sweep = (top,)

    if is_collection:
        if fused is None:
            try:
                obj._check_fusable()
                fused = True
            except ValueError:
                fused = False
        entry = obj.fused_update if fused else obj.update
    else:
        entry = obj.update

    # state_dict() hands back fresh, never-donated copies (metric.py), so
    # the snapshot survives donated warmup updates untouched.
    snapshot = obj.state_dict()
    try:
        for b in sweep:
            entry(*(_tile_to(a, b) for a in arrays))
    finally:
        obj.load_state_dict(snapshot)
    return tuple(sweep)
