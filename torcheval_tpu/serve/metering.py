"""Per-tenant metering: the serve plane's cost-attribution ledger.

The serve layer coalesces many tenants onto shared compiled programs —
which is exactly what makes per-process telemetry blind to the question
operators actually ask: *which tenant* is eating the queue, the spill
churn, and the shared program's device time?  This module keeps an
always-on (one-branch zero-cost-off) per-tenant ledger maintained by
:class:`~torcheval_tpu.serve.service.EvalService` hook sites:

* **Traffic** — submits (admitted / shed / rejected), dispatched
  batches, valid rows, payload bytes, and the per-tenant queue depth
  observed at the last admission decision.
* **Lifecycle** — quarantine, spill, and resume counts (spill + resume
  is the churn signal ROADMAP's placement tier consumes).
* **Latency** — queue-wait and end-to-end (enqueue → dispatch complete)
  quantiles.  Raw samples are appended to a bounded host-side pending
  list on the hot path and folded into
  :class:`~torcheval_tpu.monitor.StreamDigest` ladders lazily at
  snapshot time, so the mergeable digest machinery prices nothing per
  batch.
* **Device-time attribution** — every dispatch through a shared group
  program charges its tenant's valid rows against that program's row
  and seconds totals.  A program's seconds are its perfscope roofline
  price per call when :func:`record_program_price` saw a profile
  (``max(bytes/HBM-peak, flops/FLOP-peak)`` from the
  ``ProgramProfileEvent`` figures), measured dispatch wall clock
  otherwise.  Per-tenant device-seconds are the program totals split by
  row share — they conserve each program's total *by construction*, the
  invariant ``tests/serve/test_metering.py`` pins to 1e-6 relative.
  A tenant holding more than ``dominance_share`` of a shared program's
  rows is the **noisy neighbor**; the verdict names the program.

Enablement is the ``TORCHEVAL_TPU_TENANT_METERING`` tribool: truthy →
on at import, falsy → off, unset → **auto**: off until the first
:class:`EvalService` is constructed (:func:`activate_for_serve`), so
non-serve processes never pay the branch's true side.  Explicit
:func:`enable` / :func:`disable` outrank the auto resolution (the
hot-path overhead harness forces the hooks cold this way).

Surfaces: :func:`ledger_rows` feeds ``telemetry.report()["tenants"]``,
the ``torcheval_tpu_tenant_*`` Prometheus families, and the
``--tenants`` CLI table (via :mod:`torcheval_tpu.telemetry.tenants`);
:func:`publish` emits one ``TenantSampleEvent`` per tenant so dumps and
fleet snapshots carry the ledger; :func:`rebalance_hints` returns the
typed per-tenant signal set (queue depth, shed rate, spill churn,
device-seconds) the future placement tier consumes as a stable API.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from torcheval_tpu import _flags

# One tenant above this share of a shared program's rows is the
# dominant (noisy-neighbor) tenant of that program.
DEFAULT_DOMINANCE_SHARE = 0.5

# Hot-path latency samples wait here (bounded, newest kept) until a
# snapshot folds them into the StreamDigest ladders in fixed-size
# masked chunks — one compiled digest program regardless of arrival
# counts, zero device work per dispatch.
_PENDING_CAP = 4096
_FLUSH_CHUNK = 512

_QUANTILES = (0.5, 0.9, 0.99)

_LOCK = threading.RLock()

# Explicit enable()/disable() override; None defers to the flag/auto.
_forced: Optional[bool] = None


def _resolve_enabled() -> bool:
    # Import-time resolution: only an explicit truthy flag turns the
    # hooks on before any serve use; unset stays cold until
    # activate_for_serve().
    return bool(_flags.get("TENANT_METERING"))


# Module-level flag: hook sites read this as a plain attribute (the
# one-branch zero-overhead contract, see telemetry.events.ENABLED).
ENABLED: bool = _resolve_enabled()


# ------------------------------------------------------------------- control
def enable() -> None:
    """Force metering on, outranking the flag and the serve auto-on."""
    global ENABLED, _forced
    with _LOCK:
        _forced = True
        ENABLED = True


def disable() -> None:
    """Force metering off — hook sites go back to one cold branch.  The
    accumulated ledger is kept (inspect after a run; :func:`reset`
    drops it)."""
    global ENABLED, _forced
    with _LOCK:
        _forced = False
        ENABLED = False


def enabled() -> bool:
    # tpulint: disable=TPU006 -- single racy bool read, same contract as every hook site's plain attribute read
    return ENABLED


def activate_for_serve() -> None:
    """Cold resolver run at ``EvalService`` construction: the unset
    (auto) tribool turns metering on exactly when the serve plane is in
    use.  An explicit flag value or a prior :func:`enable` /
    :func:`disable` call outranks the auto-on."""
    global ENABLED
    with _LOCK:
        if _forced is not None:
            ENABLED = _forced
            return
        mode = _flags.get("TENANT_METERING")
        ENABLED = True if mode is None else bool(mode)


def reset() -> None:
    """Drop the whole ledger and the forced override (test isolation)."""
    global _forced, ENABLED
    with _LOCK:
        _tenants.clear()
        _programs.clear()
        _program_ids.clear()
        _forced = None
        ENABLED = _resolve_enabled()


# -------------------------------------------------------------------- ledger
class _TenantLedger:
    """Cumulative counters for one tenant (guarded by ``_LOCK``)."""

    __slots__ = (
        "admitted",
        "shed",
        "rejected",
        "dispatched",
        "quarantined",
        "spills",
        "resumes",
        "rows",
        "payload_bytes",
        "queue_depth",
        "pending_wait",
        "pending_e2e",
        "wait_digest",
        "e2e_digest",
    )

    def __init__(self) -> None:
        self.admitted = 0
        self.shed = 0
        self.rejected = 0
        self.dispatched = 0
        self.quarantined = 0
        self.spills = 0
        self.resumes = 0
        self.rows = 0
        self.payload_bytes = 0
        self.queue_depth = 0
        self.pending_wait: List[float] = []
        self.pending_e2e: List[float] = []
        self.wait_digest: Any = None
        self.e2e_digest: Any = None


_tenants: Dict[str, _TenantLedger] = {}

# Shared-program attribution table: interned program id ->
# {"seconds", "rows", "calls", "priced" (roofline price per call, or
# None), "by_tenant": rows per tenant}.
_programs: Dict[str, Dict[str, Any]] = {}
_program_ids: Dict[Any, str] = {}


def program_id(key: Any) -> str:
    """Intern a shared-program identity (the registry's
    ``(signature, width)``) to a short stable-in-process label."""
    with _LOCK:
        pid = _program_ids.get(key)
        if pid is None:
            pid = f"serve_group#{len(_program_ids)}"
            _program_ids[key] = pid
        return pid


def _program_entry(pid: str) -> Dict[str, Any]:
    entry = _programs.get(pid)
    if entry is None:
        entry = {
            "seconds": 0.0,
            "rows": 0,
            "calls": 0,
            "priced": None,
            "by_tenant": {},
        }
        _programs[pid] = entry
    return entry


def payload_nbytes(args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> int:
    """Total bytes of one submission's array payload — metadata only
    (``.nbytes``), no device traffic.  Only called from hook sites
    after the ``ENABLED`` branch."""
    total = 0
    for leaf in list(args) + list(kwargs.values()):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def batch_rows(args: Tuple[Any, ...]) -> int:
    """Leading-dimension row count of one submission (0 when unsized).
    Only called from hook sites after the ``ENABLED`` branch."""
    if not args:
        return 0
    shape = getattr(args[0], "shape", None)
    if shape:
        return int(shape[0])
    try:
        return len(args[0])
    except TypeError:
        return 0


# ------------------------------------------------------------------- hooks
# Only called from serve hook sites after their `if _metering.ENABLED:`
# branch (the zero-overhead contract); the helpers do not re-check.
def record_submit(
    tenant: str,
    outcome: str,
    rows: int = 0,
    nbytes: int = 0,
    queue_depth: int = 0,
) -> None:
    """One admission decision: ``outcome`` is ``admitted`` / ``shed`` /
    ``rejected``.  ``queue_depth`` is the TENANT's queued count after
    the decision (the rebalance-hints gauge)."""
    with _LOCK:
        t = _tenants.get(tenant)
        if t is None:
            t = _tenants[tenant] = _TenantLedger()
        if outcome == "admitted":
            t.admitted += 1
            t.payload_bytes += int(nbytes)
        elif outcome == "shed":
            t.shed += 1
        else:
            t.rejected += 1
        t.queue_depth = int(queue_depth)


def record_dispatch(
    tenant: str,
    program: str,
    rows: int,
    seconds: float,
    wait_s: float,
    e2e_s: float,
    queue_depth: Optional[int] = None,
) -> None:
    """One applied batch: charge ``rows`` valid rows of ``program``
    (an interned :func:`program_id`) to ``tenant`` and bank the latency
    samples.  ``seconds`` is the measured dispatch wall clock — the
    fallback price when the program has no roofline price yet.
    ``queue_depth`` (when given) refreshes the tenant's queued-count
    gauge after the pop."""
    with _LOCK:
        t = _tenants.get(tenant)
        if t is None:
            t = _tenants[tenant] = _TenantLedger()
        t.dispatched += 1
        t.rows += int(rows)
        if queue_depth is not None:
            t.queue_depth = int(queue_depth)
        if len(t.pending_wait) >= _PENDING_CAP:
            del t.pending_wait[: _FLUSH_CHUNK]
            del t.pending_e2e[: _FLUSH_CHUNK]
        t.pending_wait.append(float(wait_s))
        t.pending_e2e.append(float(e2e_s))
        entry = _program_entry(program)
        entry["calls"] += 1
        entry["rows"] += int(rows)
        priced = entry["priced"]
        entry["seconds"] += (
            priced if priced is not None else float(seconds)
        )
        by_tenant = entry["by_tenant"]
        by_tenant[tenant] = by_tenant.get(tenant, 0) + int(rows)


def record_program_price(program: str, profile: Dict[str, Any]) -> None:
    """Adopt a perfscope :func:`~torcheval_tpu.telemetry.perfscope.
    profile_program` result as ``program``'s per-call roofline price:
    the binding-roof seconds ``max(bytes/HBM-peak, flops/FLOP-peak)``.
    Later dispatches are charged the price instead of wall clock."""
    from torcheval_tpu.tools import roofline as _roofline

    peaks = _roofline.device_peaks()
    price = max(
        float(profile.get("bytes_accessed", 0)) / (peaks["hbm_gbps"] * 1e9),
        float(profile.get("flops", 0)) / max(peaks["flops"], 1.0),
    )
    with _LOCK:
        _program_entry(program)["priced"] = price


def record_quarantine(tenant: str) -> None:
    """The tenant was quarantined.  Its pre-quarantine ledger —
    including its attributed device-seconds — is kept intact."""
    with _LOCK:
        t = _tenants.get(tenant)
        if t is None:
            t = _tenants[tenant] = _TenantLedger()
        t.quarantined += 1
        t.queue_depth = 0


def record_session(action: str, tenant: str) -> None:
    """Session lifecycle tick; only ``spill`` / ``resume`` meter (their
    sum is the spill-churn rebalance signal)."""
    with _LOCK:
        t = _tenants.get(tenant)
        if t is None:
            t = _tenants[tenant] = _TenantLedger()
        if action == "spill":
            t.spills += 1
        elif action == "resume":
            t.resumes += 1


# ----------------------------------------------------------------- snapshot
def _flush_digests(t: _TenantLedger) -> None:
    """Fold the pending latency samples into the tenant's StreamDigest
    ladders (cold path; fixed-shape masked chunks → one compile)."""
    if not t.pending_wait and not t.pending_e2e:
        return
    import numpy as np

    from torcheval_tpu.monitor import StreamDigest

    for attr, pending in (
        ("wait_digest", t.pending_wait),
        ("e2e_digest", t.pending_e2e),
    ):
        if not pending:
            continue
        digest = getattr(t, attr)
        if digest is None:
            digest = StreamDigest(quantiles=_QUANTILES)
            setattr(t, attr, digest)
        for start in range(0, len(pending), _FLUSH_CHUNK):
            chunk = pending[start : start + _FLUSH_CHUNK]
            values = np.zeros(_FLUSH_CHUNK, dtype=np.float32)
            values[: len(chunk)] = chunk
            mask = np.zeros(_FLUSH_CHUNK, dtype=bool)
            mask[: len(chunk)] = True
            digest.update(values, mask=mask)
        del pending[:]


def _quantiles_of(digest: Any) -> Tuple[float, float, float]:
    if digest is None:
        return (0.0, 0.0, 0.0)
    values = digest.compute()
    if getattr(values, "size", 0) == 0:
        return (0.0, 0.0, 0.0)
    p50, p90, p99 = (float(v) for v in values)
    return (p50, p90, p99)


def _device_seconds(tenant: str) -> float:
    # Caller holds _LOCK.  Split every program's banked seconds by the
    # tenant's row share — summing over tenants returns each program's
    # total exactly (the conservation invariant).
    total = 0.0
    for entry in _programs.values():
        rows = entry["by_tenant"].get(tenant, 0)
        if rows and entry["rows"]:
            total += entry["seconds"] * rows / entry["rows"]
    return total


def _dominance(
    tenant: str, share: float
) -> Tuple[str, float]:
    # Caller holds _LOCK.  The program (if any) where this tenant's row
    # share crosses the noisy-neighbor threshold; ties go to the
    # largest share.
    worst_pid, worst_share = "", 0.0
    for pid, entry in _programs.items():
        if entry["rows"] <= 0 or len(entry["by_tenant"]) < 2:
            continue  # an unshared program has no neighbors to disturb
        frac = entry["by_tenant"].get(tenant, 0) / entry["rows"]
        if frac > share and frac > worst_share:
            worst_pid, worst_share = pid, frac
    return worst_pid, worst_share


def has_data() -> bool:
    with _LOCK:
        return bool(_tenants)


def _owner_of(tenant: str) -> str:
    """The tenant's owning host per the cluster placement tier (lazy:
    the ledger stays importable with telemetry stripped)."""
    from torcheval_tpu.telemetry import tenants as _tenants_mod

    return _tenants_mod.owner_of(tenant)


def ledger_rows(
    dominance_share: float = DEFAULT_DOMINANCE_SHARE,
) -> List[Dict[str, Any]]:
    """The cumulative ledger, one plain dict per tenant, sorted by
    attributed device-seconds (descending, then tenant id).  The row
    schema is the single contract every surface renders —
    ``report()["tenants"]``, the Prometheus families, the ``--tenants``
    CLI table, and :func:`rebalance_hints` all agree because they all
    read this."""
    with _LOCK:
        out = []
        for tenant in sorted(_tenants):
            t = _tenants[tenant]
            _flush_digests(t)
            offered = t.admitted + t.shed
            wait_q = _quantiles_of(t.wait_digest)
            e2e_q = _quantiles_of(t.e2e_digest)
            pid, frac = _dominance(tenant, dominance_share)
            out.append(
                {
                    "tenant": tenant,
                    "submits": offered + t.rejected,
                    "admitted": t.admitted,
                    "shed": t.shed,
                    "rejected": t.rejected,
                    "dispatched": t.dispatched,
                    "quarantined": t.quarantined,
                    "spills": t.spills,
                    "resumes": t.resumes,
                    "rows": t.rows,
                    "payload_bytes": t.payload_bytes,
                    "queue_depth": t.queue_depth,
                    "shed_rate": t.shed / offered if offered else 0.0,
                    "wait_p50_s": wait_q[0],
                    "wait_p99_s": wait_q[2],
                    "e2e_p50_s": e2e_q[0],
                    "e2e_p99_s": e2e_q[2],
                    "device_seconds": _device_seconds(tenant),
                    "dominant_program": pid,
                    "dominant_share": frac,
                    # Owning host per the serve cluster's placement
                    # tier; "" when no cluster is running.  Lazy import
                    # keeps the ledger importable without telemetry.
                    "owner": _owner_of(tenant),
                }
            )
    out.sort(key=lambda r: (-r["device_seconds"], r["tenant"]))
    return out


def program_rows() -> List[Dict[str, Any]]:
    """Per shared-program attribution totals (the conservation-test
    denominators): banked seconds, rows, calls, per-tenant row split,
    and whether the per-call price is roofline or wall clock."""
    with _LOCK:
        return [
            {
                "program": pid,
                "seconds": entry["seconds"],
                "rows": entry["rows"],
                "calls": entry["calls"],
                "priced": entry["priced"] is not None,
                "by_tenant": dict(entry["by_tenant"]),
            }
            for pid, entry in sorted(_programs.items())
        ]


def publish(
    dominance_share: float = DEFAULT_DOMINANCE_SHARE,
) -> int:
    """Emit one ``TenantSampleEvent`` per tenant onto the telemetry bus
    (no-op returning 0 with the bus off) so JSONL dumps, flight-recorder
    bundles, and fleet snapshots carry the ledger.  Returns the number
    of samples emitted."""
    from torcheval_tpu.telemetry import events as _events

    if not _events.ENABLED:
        return 0
    rows = ledger_rows(dominance_share)
    for row in rows:
        _events.record_tenant_sample(**row)
    return len(rows)


# ----------------------------------------------------------- rebalance hints
@dataclass(frozen=True)
class TenantSignal:
    """One tenant's rebalance inputs: live queue depth, cumulative shed
    fraction, spill churn (spills + resumes), and attributed
    device-seconds."""

    tenant: str
    queue_depth: int
    shed_rate: float
    spill_churn: int
    device_seconds: float


@dataclass(frozen=True)
class RebalanceHints:
    """The typed signal set the placement tier consumes (ROADMAP item
    3): per-tenant signals sorted hottest-first by device-seconds, plus
    the process-wide noisy-neighbor verdict."""

    tenants: Tuple[TenantSignal, ...]
    dominant_tenant: str
    dominant_program: str
    dominant_share: float
    device_seconds_total: float


def rebalance_hints(
    dominance_share: float = DEFAULT_DOMINANCE_SHARE,
) -> RebalanceHints:
    """Snapshot the ledger as :class:`RebalanceHints` — the stable API
    for hot/cold placement decisions, so consumers never scrape report
    text.  Empty (no tenants) until metering is on and serve traffic
    flowed."""
    rows = ledger_rows(dominance_share)
    signals = tuple(
        TenantSignal(
            tenant=row["tenant"],
            queue_depth=row["queue_depth"],
            shed_rate=row["shed_rate"],
            spill_churn=row["spills"] + row["resumes"],
            device_seconds=row["device_seconds"],
        )
        for row in rows
    )
    dominant = next(
        (row for row in rows if row["dominant_program"]), None
    )
    return RebalanceHints(
        tenants=signals,
        dominant_tenant=dominant["tenant"] if dominant else "",
        dominant_program=(
            dominant["dominant_program"] if dominant else ""
        ),
        dominant_share=dominant["dominant_share"] if dominant else 0.0,
        device_seconds_total=sum(r["device_seconds"] for r in rows),
    )
