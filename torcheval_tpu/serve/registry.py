"""Tenant sessions coalesced onto shared sliced collections.

The serve layer's core cost model: N tenants evaluating the same metric
suite must not cost N compiled programs and N dispatches per batch-mix.
A :class:`SessionRegistry` groups tenants by *collection signature* —
metric names, types, configuration, and state layout — and seats every
same-signature tenant on one shared :class:`~torcheval_tpu.metrics.
MetricCollection` built with ``slices=K``: tenant *t*'s batch rides the
fused sliced program with ``slice_ids`` pinned to *t*'s seat, so its
per-seat clone sees ``mask * (slice_ids == seat)`` — exactly the masked
update a solo run performs (bit-identical results, the quarantine
suite's isolation property), while the group pays ONE program launch
for however many tenants share the dispatch signature.

Programs are shared even across *overflow* groups (tenant K+1 of a
signature lands in a second group): the jitted apply for a signature is
built once over a registry-owned **template** collection and cached in
a bounded :class:`~torcheval_tpu.parallel._compile_cache.LruCache`
keyed by ``(signature, width, health)``.  The template is a structure
donor only — the program is purely functional in the state pytree, so
every group with the signature calls the same compiled program over its
own states.  (A re-trace setattrs tracers onto the template's members,
which is why groups never trace through their OWN members: their states
stay concrete under any abort.)

Seats are fungible: spilling a tenant frees its seat entirely and a
later resume may land on a different seat or group — seat state dicts
are keyed ``"{metric}/{state}"`` with no seat index for exactly this
reason.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu._stats import bump_trace
from torcheval_tpu.metrics import MetricCollection
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.ops import _mega_plan
import torcheval_tpu.serve.metering as _metering
from torcheval_tpu.parallel._compile_cache import LruCache
from torcheval_tpu.telemetry import health as _health
from torcheval_tpu.telemetry import perfscope as _perfscope

DEFAULT_GROUP_WIDTH = 8

# Config-attr values folded into signature_of by value.  Anything else
# (arrays, callables, user objects) is fingerprinted by identity, which
# over-splits — two tenants with distinct exotic config objects get
# separate groups — but can never wrongly coalesce differently
# configured metrics onto one program.
_PLAIN = (str, int, float, bool, bytes, type(None))


def _config_fingerprint(metric: Metric) -> Tuple[Tuple[str, Any], ...]:
    """Public non-state instance attributes, by value where safe.

    Metric configuration lives in public scalar attributes
    (``self.k``, ``self.threshold``, ``self.num_classes``,
    ``self.average``, ...); states and infrastructure attrs are
    excluded.  Two same-type metrics with different config therefore
    never share a signature even when their state layouts coincide.
    """
    states = set(metric._state_name_to_default)
    out = []
    for key in sorted(vars(metric)):
        if key.startswith("_") or key in states:
            continue
        value = vars(metric)[key]
        if isinstance(value, _PLAIN):
            out.append((key, value))
        elif isinstance(value, tuple) and all(
            isinstance(v, _PLAIN) for v in value
        ):
            out.append((key, ("tuple",) + value))
        else:
            out.append((key, f"<id:{type(value).__qualname__}@{id(value):#x}>"))
    return tuple(out)


def signature_of(metrics: Mapping[str, Metric]) -> Tuple[Any, ...]:
    """Hashable coalescing signature of a metric suite: sorted
    ``(name, qualified type, config fingerprint, state layout)`` per
    member.  Tenants opened with equal signatures share seats on one
    sliced collection (and one compiled program); pass an explicit
    ``signature=`` to :meth:`SessionRegistry.open` to override — e.g.
    to force-coalesce metrics whose config is held in objects the
    fingerprint can only compare by identity."""
    sig = []
    for name in sorted(metrics):
        m = metrics[name]
        cls = type(m)
        layout = tuple(
            (
                s,
                tuple(getattr(getattr(m, s), "shape", ())),
                str(getattr(getattr(m, s), "dtype", type(getattr(m, s)).__name__)),
            )
            for s in sorted(m._state_name_to_default)
        )
        sig.append(
            (name, f"{cls.__module__}.{cls.__qualname__}",
             _config_fingerprint(m), layout)
        )
    return tuple(sig)


@dataclass
class _ApplyBundle:
    """One shared compiled program for a (signature, width, health)
    key: the jitted apply, the template collection it traces through,
    and the health bounds baked into it."""

    apply: Any
    template: MetricCollection
    health: bool
    bounds: Tuple[Tuple[str, int], ...]


def _build_bundle(
    template: MetricCollection, health: bool
) -> _ApplyBundle:
    # Mirrors MetricCollection.fused_update's program, minus donation
    # (serve snapshots rely on pre-dispatch states staying alive) and
    # bound to the TEMPLATE so group members never hold tracers.
    # tpulint: disable=TPU001 -- cold compile path: `health` is _health.ENABLED captured at the bundle cache key, not a hot-path probe
    bounds = _health.label_bounds(template._metrics) if health else ()

    def apply(states, a, kw):
        bump_trace("serve_group")
        for name, m in template._all_members.items():
            for s, v in states[name].items():
                setattr(m, s, v)
        template._trace_update(a, kw)
        if health:
            return (
                template._read_states(),
                # tpulint: disable=TPU001 -- traced only when the bundle was built with health on (keyed on _health.ENABLED)
                _health.stats_for_update(a, kw, bounds),
            )
        return template._read_states()

    return _ApplyBundle(
        apply=jax.jit(apply), template=template, health=health, bounds=bounds
    )


class TenantGroup:
    """One ``slices=width`` collection plus its seat bookkeeping.

    Seat clones (``"{name}@{seat}"``) hold per-tenant state; the global
    members accumulate the union of every seated tenant's batches and
    are never read by the serve layer.
    """

    def __init__(
        self,
        signature: Tuple[Any, ...],
        template_metrics: Mapping[str, Metric],
        width: int,
        *,
        bucket: bool = True,
    ) -> None:
        self.signature = signature
        self.width = int(width)
        self.collection = MetricCollection(
            {n: copy.deepcopy(m) for n, m in template_metrics.items()},
            bucket=bucket,
            donate=False,
            slices=self.width,
        )
        # States are fixed jax arrays for the group's lifetime (resets
        # and load_state_dict both install arrays), so one fusability
        # sweep at construction covers every dispatch.
        self.collection._check_fusable()
        self.free: List[int] = list(range(self.width))
        self.occupants: Dict[int, str] = {}

    def acquire(self, tenant: str) -> int:
        seat = self.free.pop(0)
        self.occupants[seat] = tenant
        return seat

    def release(self, seat: int) -> None:
        """Free a seat for the next tenant: reset its clones so no
        state leaks across occupancies."""
        self.reset_seat(seat)
        self.occupants.pop(seat, None)
        self.free.append(seat)

    def reset_seat(self, seat: int) -> None:
        for name in self.collection._metrics:
            self.collection._slice_members[f"{name}@{seat}"].reset()

    def seat_state_dict(self, seat: int) -> Dict[str, Any]:
        """Flat ``"{metric}/{state}"`` snapshot of one seat — no seat
        index in the keys, so a resume can load it into any seat."""
        out: Dict[str, Any] = {}
        for name in self.collection._metrics:
            clone = self.collection._slice_members[f"{name}@{seat}"]
            for state, value in clone.state_dict().items():
                out[f"{name}/{state}"] = value
        return out

    def load_seat(self, seat: int, flat: Mapping[str, Any]) -> None:
        per_metric: Dict[str, Dict[str, Any]] = {
            name: {} for name in self.collection._metrics
        }
        for key, value in flat.items():
            name, _, state = key.partition("/")
            if name in per_metric:
                # Spill checkpoints hold host numpy; rehydrate to device
                # arrays (bit-exact — device_put does not touch the
                # payload).  Group states are always plain arrays
                # (_check_fusable at construction).
                per_metric[name][state] = jnp.asarray(value)
        for name, states in per_metric.items():
            if states:
                self.collection._slice_members[
                    f"{name}@{seat}"
                ].load_state_dict(states)

    def seat_compute(self, seat: int) -> Dict[str, Any]:
        return {
            name: self.collection._slice_members[f"{name}@{seat}"].compute()
            for name in self.collection._metrics
        }


# Session lifecycle states.
ACTIVE = "active"
SPILLED = "spilled"
QUARANTINED = "quarantined"
CLOSED = "closed"


@dataclass
class Session:
    """One tenant's registration: lifecycle state plus (while resident)
    the group/seat holding its metric states."""

    tenant: str
    signature: Tuple[Any, ...]
    state: str = ACTIVE
    group: Optional[TenantGroup] = None
    seat: int = -1
    batches: int = 0
    last_touch: int = 0
    quarantine_reason: str = ""
    extras: Dict[str, Any] = field(default_factory=dict)

    @property
    def resident(self) -> bool:
        return self.group is not None


class SessionRegistry:
    """Tenant → session map with signature-coalesced seating and the
    per-signature shared-program cache.

    Not thread-safe on its own; :class:`~torcheval_tpu.serve.service.
    EvalService` serializes access under its lock.
    """

    def __init__(
        self,
        *,
        group_width: int = DEFAULT_GROUP_WIDTH,
        bucket: bool = True,
        program_cache: Optional[LruCache] = None,
    ) -> None:
        if group_width < 1:
            raise ValueError(f"group_width must be >= 1, got {group_width}")
        self._group_width = int(group_width)
        self._bucket = bool(bucket)
        self._groups: Dict[Tuple[Any, ...], List[TenantGroup]] = {}
        self._templates: Dict[Tuple[Any, ...], Dict[str, Metric]] = {}
        # The generalized per-signature compile cache: bounded by
        # COMPILE_CACHE_CAP like the SPMD memoizer, evictions on the
        # telemetry bus.
        self._programs = (
            program_cache
            if program_cache is not None
            else LruCache(name="serve_programs", telemetry_events=True)
        )
        self._sessions: Dict[str, Session] = {}
        self._clock = 0

    # -- lifecycle --------------------------------------------------------
    def open(
        self,
        tenant: str,
        metrics: Mapping[str, Metric],
        *,
        signature: Optional[Tuple[Any, ...]] = None,
    ) -> Session:
        """Register ``tenant`` and seat it on a (possibly shared)
        group.  The tenant's current metric states are adopted into its
        seat, so opening with pre-accumulated metrics resumes them."""
        existing = self._sessions.get(tenant)
        if existing is not None and existing.state != CLOSED:
            raise ValueError(f"tenant {tenant!r} already has an open session")
        if not metrics:
            raise ValueError("open() requires at least one metric")
        sig = signature if signature is not None else signature_of(metrics)
        if sig not in self._templates:
            self._templates[sig] = {
                n: copy.deepcopy(m) for n, m in metrics.items()
            }
        session = Session(tenant=tenant, signature=sig)
        self._sessions[tenant] = session
        self.attach(session)
        for name, metric in metrics.items():
            session.group.collection._slice_members[
                f"{name}@{session.seat}"
            ].load_state_dict(metric.state_dict())
        return session

    def attach(self, session: Session) -> None:
        """Seat a session on a group with a free slot, creating an
        overflow group when the signature's groups are all full."""
        groups = self._groups.setdefault(session.signature, [])
        group = next((g for g in groups if g.free), None)
        if group is None:
            group = TenantGroup(
                session.signature,
                self._templates[session.signature],
                self._group_width,
                bucket=self._bucket,
            )
            groups.append(group)
        session.seat = group.acquire(session.tenant)
        session.group = group
        session.state = ACTIVE
        self.touch(session)

    def release(self, session: Session) -> None:
        """Free the session's seat (resetting its clones).  The caller
        sets the session's next lifecycle state."""
        if session.group is not None:
            session.group.release(session.seat)
        session.group = None
        session.seat = -1

    def session(self, tenant: str) -> Optional[Session]:
        return self._sessions.get(tenant)

    def sessions(self) -> Dict[str, Session]:
        return dict(self._sessions)

    def touch(self, session: Session) -> None:
        self._clock += 1
        session.last_touch = self._clock

    def forget(self, session: Session) -> None:
        """Drop a session from the registry entirely (seat freed, map
        entry removed) WITHOUT touching its spill namespace — the
        cluster's migration commit path: the tenant's durable state now
        belongs to another host, so ``close()``'s namespace deletion
        must not run here."""
        self.release(session)
        self._sessions.pop(session.tenant, None)

    def resident_lru(self) -> List[Session]:
        """Resident sessions, least-recently-touched first."""
        return sorted(
            (s for s in self._sessions.values() if s.resident),
            key=lambda s: s.last_touch,
        )

    # -- dispatch ---------------------------------------------------------
    def bundle(self, group: TenantGroup) -> _ApplyBundle:
        """The shared program for ``group``'s signature (and the
        current health flag), built on first use and LRU-bounded."""
        health = _health.ENABLED
        # The megakernel route token joins the key so a flag/backend flip
        # — or a routing_autotune epoch bump after a new measurement —
        # rebuilds the shared program instead of reusing a stale route.
        key = (group.signature, group.width, health, _mega_plan.route_token())

        def factory() -> _ApplyBundle:
            template = MetricCollection(
                {
                    n: copy.deepcopy(m)
                    for n, m in self._templates[group.signature].items()
                },
                bucket=self._bucket,
                donate=False,
                slices=group.width,
            )
            return _build_bundle(template, health)

        return self._programs.get_or_create(key, factory)

    def dispatch(
        self,
        session: Session,
        args: Tuple[Any, ...],
        kwargs: Dict[str, Any],
    ) -> None:
        """Apply one batch to ``session``'s seat through the shared
        program.  Raises whatever the update (or the data-health
        monitor) raises; the batch may already be installed when a
        health escalation fires — callers snapshot/restore around this
        (the service's quarantine path)."""
        if session.group is None:
            raise RuntimeError(
                f"tenant {session.tenant!r} is not resident (state="
                f"{session.state})"
            )
        if not args:
            raise TypeError("dispatch requires at least one batch array")
        group = session.group
        col = group.collection
        kwargs = dict(kwargs)
        rows = jnp.asarray(args[0]).shape[0]
        kwargs["slice_ids"] = jnp.full((rows,), session.seat, dtype=jnp.int32)
        args, kwargs = col._bucket_args(tuple(args), kwargs)
        bundle = self.bundle(group)
        out = bundle.apply(col._read_states(), args, kwargs)
        # An abort above leaves tracers only on the bundle's template;
        # the group's own states are untouched and stay concrete.
        if bundle.health:
            new_states, health_stats = out
        else:
            new_states, health_stats = out, None
        col._install_states(new_states)
        if health_stats is not None:
            # After install, mirroring fused_update: an escalation must
            # not leave tracer states behind.  The service undoes the
            # poisoned install from its pre-dispatch snapshot.
            # tpulint: disable=TPU001 -- health_stats is non-None only when the program was built with health=_health.ENABLED
            _health.inspect(
                health_stats,
                source="serve_group",
                bounds=bundle.bounds,
            )
        if _perfscope.ENABLED:
            # Price the shared program once per (signature, width,
            # health) — a shadow lowering over avals, no execution.  Any
            # tracers the re-trace leaves land on the bundle's template,
            # never on the group's states (same invariant as the apply
            # itself).
            profiled = _perfscope.profile_program(
                "serve_group",
                bundle.apply,
                (col._read_states(), args, kwargs),
                batch_args=(args, kwargs),
                signature=(group.signature, group.width, bundle.health),
            )
            if profiled is not None and _metering.ENABLED:
                # The roofline price becomes the metering ledger's
                # per-call device-time charge for this shared program.
                _metering.record_program_price(
                    _metering.program_id((group.signature, group.width)),
                    profiled,
                )

    # -- seat state -------------------------------------------------------
    def seat_state_dict(self, session: Session) -> Dict[str, Any]:
        self._require_resident(session)
        return session.group.seat_state_dict(session.seat)

    def load_seat(self, session: Session, flat: Mapping[str, Any]) -> None:
        self._require_resident(session)
        session.group.load_seat(session.seat, flat)

    def compute(self, session: Session) -> Dict[str, Any]:
        self._require_resident(session)
        return session.group.seat_compute(session.seat)

    def _require_resident(self, session: Session) -> None:
        if session.group is None:
            raise RuntimeError(
                f"tenant {session.tenant!r} is not resident (state="
                f"{session.state})"
            )

    # -- introspection ----------------------------------------------------
    def program_cache_info(self):
        return self._programs.cache_info()

    def group_count(self) -> int:
        return sum(len(gs) for gs in self._groups.values())
