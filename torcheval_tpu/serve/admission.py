"""Admission control: bounded queues, typed outcomes, load shedding.

Overload must degrade throughput, never kill the process.  Every
``submit()`` passes through an :class:`AdmissionController` that holds
ONE bounded global queue with per-tenant occupancy counts; when a burst
fills it, the configured policy decides who pays:

``reject-newest`` (default)
    The arriving batch is shed.  Cheapest and fairest under uniform
    load — nobody's already-queued work is discarded.
``drop-oldest``
    The oldest queued batch is shed to admit the arrival.  Prefers
    freshness: right when results are only useful within a deadline.
``fair``
    Per-tenant quota ``max(1, global_capacity // queued_tenants)`` on
    top of the global bound — a slow-consumer tenant saturates its own
    quota and sheds only its own batches while light tenants keep
    admitting.

Outcomes are typed (:class:`Admitted` / :class:`Shed` /
:class:`Rejected`) rather than exceptional: overload is an expected
operating mode and callers branch on the type.  ``Shed`` means queue
pressure (retryable later); ``Rejected`` means the tenant cannot submit
at all (unknown, quarantined, draining).

Deadlines are enforced lazily at pop time: an item whose
``enqueued_at + deadline_s`` has passed is shed with reason
``"deadline"`` instead of dispatched — work the caller has already
given up on is never executed.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

POLICIES = ("reject-newest", "drop-oldest", "fair")

DEFAULT_GLOBAL_CAPACITY = 256
DEFAULT_PER_TENANT_CAPACITY = 64


@dataclass(frozen=True)
class Admitted:
    """The batch is queued; ``ticket`` orders it globally."""

    tenant: str
    ticket: int
    queue_depth: int


@dataclass(frozen=True)
class Shed:
    """Queue pressure discarded a batch (the submitted one, or —
    under ``drop-oldest`` — someone's older one to admit this one).
    Retryable once the queue drains."""

    tenant: str
    reason: str  # "global-queue-full" | "tenant-queue-full" | "fair-quota"
    policy: str
    queue_depth: int


@dataclass(frozen=True)
class Rejected:
    """The tenant cannot submit at all right now."""

    tenant: str
    reason: str  # "unknown-tenant" | "quarantined" | "draining" | "closed"


@dataclass
class QueueItem:
    """One queued batch with its admission metadata."""

    ticket: int
    tenant: str
    args: Tuple[Any, ...]
    kwargs: Dict[str, Any]
    enqueued_at: float
    deadline_s: Optional[float]
    trace_ctx: Any = None

    def expired(self, now: float) -> bool:
        return (
            self.deadline_s is not None
            and now - self.enqueued_at > self.deadline_s
        )


@dataclass
class _State:
    queue: Deque[QueueItem] = field(default_factory=deque)
    per_tenant: Dict[str, int] = field(default_factory=dict)


class AdmissionController:
    """Bounded admission with a pluggable shed policy.  Thread-safe;
    every method takes the internal lock, and none calls out under it.
    """

    def __init__(
        self,
        *,
        global_capacity: int = DEFAULT_GLOBAL_CAPACITY,
        per_tenant_capacity: int = DEFAULT_PER_TENANT_CAPACITY,
        policy: str = "reject-newest",
        deadline_s: Optional[float] = None,
    ) -> None:
        if policy not in POLICIES:
            raise ValueError(
                f"policy must be one of {POLICIES}, got {policy!r}"
            )
        if global_capacity < 1:
            raise ValueError(
                f"global_capacity must be >= 1, got {global_capacity}"
            )
        if per_tenant_capacity < 1:
            raise ValueError(
                f"per_tenant_capacity must be >= 1, got {per_tenant_capacity}"
            )
        self.policy = policy
        self.global_capacity = int(global_capacity)
        self.per_tenant_capacity = int(per_tenant_capacity)
        self.deadline_s = deadline_s
        self._lock = threading.Lock()
        self._state = _State()
        self._ticket = 0

    # -- introspection ----------------------------------------------------
    def depth(self, tenant: Optional[str] = None) -> int:
        with self._lock:
            if tenant is None:
                return len(self._state.queue)
            return self._state.per_tenant.get(tenant, 0)

    # -- admission --------------------------------------------------------
    def offer(
        self,
        tenant: str,
        args: Tuple[Any, ...],
        kwargs: Dict[str, Any],
        *,
        now: float,
        deadline_s: Optional[float] = None,
        trace_ctx: Any = None,
    ) -> Tuple[Any, List[QueueItem]]:
        """Try to enqueue one batch.  Returns ``(outcome, dropped)``:
        ``dropped`` is the list of OTHER items the drop-oldest policy
        evicted to make room (shed on the caller's event bus)."""
        if deadline_s is None:
            deadline_s = self.deadline_s
        dropped: List[QueueItem] = []
        with self._lock:
            state = self._state
            queued = state.per_tenant.get(tenant, 0)
            if queued >= self.per_tenant_capacity:
                return (
                    Shed(
                        tenant=tenant,
                        reason="tenant-queue-full",
                        policy=self.policy,
                        queue_depth=len(state.queue),
                    ),
                    dropped,
                )
            if self.policy == "fair":
                tenants = len(state.per_tenant) + (0 if queued else 1)
                quota = max(1, self.global_capacity // max(1, tenants))
                if queued >= quota:
                    return (
                        Shed(
                            tenant=tenant,
                            reason="fair-quota",
                            policy=self.policy,
                            queue_depth=len(state.queue),
                        ),
                        dropped,
                    )
            if len(state.queue) >= self.global_capacity:
                if self.policy != "drop-oldest":
                    return (
                        Shed(
                            tenant=tenant,
                            reason="global-queue-full",
                            policy=self.policy,
                            queue_depth=len(state.queue),
                        ),
                        dropped,
                    )
                victim = state.queue.popleft()
                self._decrement(victim.tenant)
                dropped.append(victim)
            self._ticket += 1
            item = QueueItem(
                ticket=self._ticket,
                tenant=tenant,
                args=tuple(args),
                kwargs=dict(kwargs),
                enqueued_at=now,
                deadline_s=deadline_s,
                trace_ctx=trace_ctx,
            )
            state.queue.append(item)
            state.per_tenant[tenant] = queued + 1
            return (
                Admitted(
                    tenant=tenant,
                    ticket=item.ticket,
                    queue_depth=len(state.queue),
                ),
                dropped,
            )

    def pop(
        self, *, now: float
    ) -> Tuple[Optional[QueueItem], List[QueueItem]]:
        """Next dispatchable item (None when the queue is empty) plus
        the deadline-expired items skipped to reach it."""
        expired: List[QueueItem] = []
        with self._lock:
            state = self._state
            while state.queue:
                item = state.queue.popleft()
                self._decrement(item.tenant)
                if item.expired(now):
                    expired.append(item)
                    continue
                return item, expired
        return None, expired

    def purge(self, tenant: str) -> List[QueueItem]:
        """Drop every queued item of ``tenant`` (quarantine path)."""
        with self._lock:
            state = self._state
            kept, purged = deque(), []
            for item in state.queue:
                (purged if item.tenant == tenant else kept).append(item)
            state.queue = kept
            state.per_tenant.pop(tenant, None)
            return purged

    def _decrement(self, tenant: str) -> None:
        # Caller holds the lock.
        left = self._state.per_tenant.get(tenant, 0) - 1
        if left > 0:
            self._state.per_tenant[tenant] = left
        else:
            self._state.per_tenant.pop(tenant, None)
