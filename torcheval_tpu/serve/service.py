"""EvalService — the overload-safe multi-tenant front door.

One service owns a :class:`~torcheval_tpu.serve.registry.
SessionRegistry` (tenant seating + shared programs), an
:class:`~torcheval_tpu.serve.admission.AdmissionController` (bounded
queues + shed policies), and the spill/quarantine machinery that keeps
one misbehaving tenant from taking the rest down:

* **Backpressure** — ``submit()`` never blocks and never throws under
  load; it returns a typed outcome the caller branches on.  A 10×
  burst degrades into shed events, not an OOM or a dead process.
* **Poison quarantine** — a tenant whose batch trips the data-health
  monitor (or whose update raises) is rolled back from the
  pre-dispatch state snapshot, its queued work purged, and the tenant
  marked quarantined; a ``QuarantineEvent`` lands on the bus and the
  flight recorder dumps a post-mortem bundle.  Because tenants only
  ever touch their own seat's masked slice, every other tenant's
  results stay bit-identical to a solo run.
* **Idle spill** — past ``max_resident`` seated tenants, the
  least-recently-touched sessions are checkpointed through
  :class:`~torcheval_tpu.resilience.checkpoint.CheckpointManager`
  (per-tenant namespace) and their seats freed; the next touch
  transparently resumes them, possibly on a different seat or group.
* **Graceful drain** — ``drain()`` stops admission, pumps the queue to
  empty under a deadline, and final-checkpoints every resident tenant.

Processing is pull-based: call :meth:`EvalService.pump` from your own
loop, or :meth:`start` a background worker thread (stop it with
:meth:`stop`; :meth:`drain` stops it too).  All hook sites follow the
one-branch zero-cost-when-off contract.
"""

from __future__ import annotations

import math
import os
import threading
import time
import warnings
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Optional, Tuple, Union

from torcheval_tpu import _flags
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.resilience import faults as _faults
from torcheval_tpu.resilience.checkpoint import CheckpointManager
from torcheval_tpu.telemetry import events as _telemetry
from torcheval_tpu.telemetry import flightrec as _flightrec
from torcheval_tpu.telemetry import trace as _trace
from torcheval_tpu.telemetry.health import DataCorruptionError

import torcheval_tpu.serve.metering as _metering
from torcheval_tpu.serve.admission import (
    Admitted,
    AdmissionController,
    QueueItem,
    Rejected,
    Shed,
)
from torcheval_tpu.serve.registry import (
    ACTIVE,
    CLOSED,
    QUARANTINED,
    SPILLED,
    DEFAULT_GROUP_WIDTH,
    Session,
    SessionRegistry,
)

# Worker join budget on stop(); a worker alive past it is reported, not
# silently leaked (mirrors engine/prefetch.py).
_JOIN_TIMEOUT_S = 5.0

# Worker idle poll period: a submit sets the wake event, so this only
# bounds shutdown latency when the queue stays empty.
_IDLE_TICK_S = 0.01

# Host-side admit-wait reservoir for stats()/the bench p99 (the bus
# histogram is the durable record; this keeps stats() telemetry-free).
_WAIT_WINDOW = 4096


def _p99(waits: List[float]) -> float:
    if not waits:
        return 0.0
    ordered = sorted(waits)
    rank = max(0, math.ceil(0.99 * len(ordered)) - 1)
    return ordered[rank]


@dataclass(frozen=True)
class DrainResult:
    """Typed outcome of :meth:`EvalService.drain`.

    ``expired`` means the deadline fired before the queue emptied; the
    service then spilled every undispatched resident session it could
    reach (``spilled``) so their state survives the shutdown, and
    ``unspilled`` names the ones it could not.  ``stuck`` flags the
    pathological case: a dispatch wedged inside the pump still holds
    the service lock at expiry, so the rescue spill could not run at
    all (the pump helper is leaked as a daemon, mirroring ``stop()``'s
    contract).  Indexing (``result["processed"]``) is kept for callers
    of the old dict-shaped summary.
    """

    processed: int
    flushed: bool
    pending: int
    expired: bool = False
    spilled: int = 0
    unspilled: Tuple[str, ...] = ()
    stuck: bool = False

    def __getitem__(self, key: str) -> Any:
        return getattr(self, key)


class EvalService:
    """Multi-tenant metric evaluation with admission control.

    Thread-safety: every public method is safe to call from any thread;
    registry and session mutations serialize on one reentrant lock, and
    the admission controller's internal lock is only ever taken under
    it (fixed lock order: service → admission).
    """

    def __init__(
        self,
        *,
        group_width: int = DEFAULT_GROUP_WIDTH,
        bucket: bool = True,
        admission: Optional[AdmissionController] = None,
        spill_dir: Optional[str] = None,
        max_resident: Optional[int] = None,
        keep: int = 2,
    ) -> None:
        self._registry = SessionRegistry(
            group_width=group_width, bucket=bucket
        )
        self._admission = (
            admission if admission is not None else AdmissionController()
        )
        if spill_dir is None:
            spill_dir = _flags.get("SERVE_SPILL_DIR")
        self._spill_root = (
            CheckpointManager(spill_dir, keep=keep)
            if spill_dir is not None
            else None
        )
        if max_resident is not None and max_resident < 1:
            raise ValueError(
                f"max_resident must be >= 1, got {max_resident}"
            )
        self._max_resident = max_resident
        self._lock = threading.RLock()
        self._draining = False
        self._closed = False
        self._waits: deque = deque(maxlen=_WAIT_WINDOW)
        self._counts: Dict[str, int] = {
            "admitted": 0,
            "shed": 0,
            "rejected": 0,
            "dispatched": 0,
            "quarantined": 0,
            "spills": 0,
            "resumes": 0,
        }
        self._worker: Optional[threading.Thread] = None
        self._stop_flag = threading.Event()
        self._wake = threading.Event()
        # Cold resolver: the unset TENANT_METERING tribool auto-enables
        # the per-tenant ledger exactly when serve is in use.
        _metering.activate_for_serve()

    # ------------------------------------------------------------ sessions
    def open(
        self,
        tenant: str,
        metrics: Mapping[str, Metric],
        *,
        signature: Optional[Tuple[Any, ...]] = None,
    ) -> Session:
        """Register ``tenant``; same-signature tenants coalesce onto a
        shared sliced collection.  The metrics' current states are
        adopted into the tenant's seat."""
        with self._lock:
            if self._closed or self._draining:
                raise RuntimeError(
                    "EvalService is draining/closed; no new sessions"
                )
            session = self._registry.open(
                tenant, metrics, signature=signature
            )
            if _telemetry.ENABLED:
                _telemetry.record_session("open", tenant)
            self._maybe_spill(exclude=session)
            return session

    def close(self, tenant: str) -> None:
        """End ``tenant``'s session: purge its queue, free its seat,
        and delete its spill namespace (siblings untouched)."""
        with self._lock:
            session = self._registry.session(tenant)
            if session is None:
                raise KeyError(f"unknown tenant {tenant!r}")
            self._admission.purge(tenant)
            self._registry.release(session)
            if self._spill_root is not None:
                self._spill_root.namespace(tenant).delete_all()
            session.state = CLOSED
            if _telemetry.ENABLED:
                _telemetry.record_session("close", tenant)

    # ----------------------------------------------------------- admission
    def submit(
        self,
        tenant: str,
        *args: Any,
        deadline_s: Optional[float] = None,
        **kwargs: Any,
    ) -> Union[Admitted, Shed, Rejected]:
        """Offer one batch.  Non-blocking; returns a typed outcome.
        Positional arrays and ``mask=``/``weight=`` keywords flow to
        the metrics' ``update`` unchanged (``slice_ids`` is owned by
        the service and must not be passed)."""
        if "slice_ids" in kwargs:
            raise TypeError(
                "slice_ids= is assigned by the service (the tenant's seat)"
            )
        if _faults.ENABLED:
            _faults.fire(
                "serve.admit",
                tenant=tenant,
                # tpulint: disable=TPU006 -- depth() locks internally; fire stays outside self._lock so injected delays can't stall the pump
                queue_depth=self._admission.depth(),
            )
        with self._lock:
            session = self._registry.session(tenant)
            if session is None or session.state == CLOSED:
                return self._reject(tenant, "unknown-tenant")
            if session.state == QUARANTINED:
                return self._reject(tenant, "quarantined")
            if self._closed:
                return self._reject(tenant, "closed")
            if self._draining:
                return self._reject(tenant, "draining")
            ctx = _trace.capture() if _trace.ENABLED else None
            now = time.monotonic()
            outcome, dropped = self._admission.offer(
                tenant,
                args,
                kwargs,
                now=now,
                deadline_s=deadline_s,
                trace_ctx=ctx,
            )
            for victim in dropped:
                self._counts["shed"] += 1
                if _telemetry.ENABLED:
                    _telemetry.record_admission(
                        victim.tenant,
                        "shed",
                        reason="drop-oldest",
                        policy=self._admission.policy,
                        queue_depth=outcome.queue_depth,
                        wait_s=now - victim.enqueued_at,
                    )
                if _metering.ENABLED:
                    _metering.record_submit(
                        victim.tenant,
                        "shed",
                        queue_depth=self._admission.depth(victim.tenant),
                    )
            if isinstance(outcome, Admitted):
                self._counts["admitted"] += 1
                if _telemetry.ENABLED:
                    _telemetry.record_admission(
                        tenant,
                        "admitted",
                        policy=self._admission.policy,
                        queue_depth=outcome.queue_depth,
                    )
                if _metering.ENABLED:
                    _metering.record_submit(
                        tenant,
                        "admitted",
                        nbytes=_metering.payload_nbytes(args, kwargs),
                        queue_depth=self._admission.depth(tenant),
                    )
            else:
                self._counts["shed"] += 1
                if _telemetry.ENABLED:
                    _telemetry.record_admission(
                        tenant,
                        "shed",
                        reason=outcome.reason,
                        policy=self._admission.policy,
                        queue_depth=outcome.queue_depth,
                    )
                if _metering.ENABLED:
                    _metering.record_submit(
                        tenant,
                        "shed",
                        queue_depth=self._admission.depth(tenant),
                    )
        self._wake.set()
        return outcome

    def _reject(self, tenant: str, reason: str) -> Rejected:
        self._counts["rejected"] += 1
        if _telemetry.ENABLED:
            _telemetry.record_admission(
                tenant,
                "rejected",
                reason=reason,
                policy=self._admission.policy,
                queue_depth=self._admission.depth(),
            )
        if _metering.ENABLED:
            _metering.record_submit(
                tenant,
                "rejected",
                queue_depth=self._admission.depth(tenant),
            )
        return Rejected(tenant=tenant, reason=reason)

    # ---------------------------------------------------------- processing
    def pump(self, max_items: Optional[int] = None) -> int:
        """Process queued batches synchronously; returns how many were
        dispatched.  Deadline-expired items are shed at pop, never
        executed."""
        processed = 0
        while max_items is None or processed < max_items:
            # Same lock order as submit (service, then admission's own
            # lock inside pop) — and the shed accounting must not race
            # submit's counter updates.
            with self._lock:
                now = time.monotonic()
                item, expired = self._admission.pop(now=now)
                for stale in expired:
                    self._counts["shed"] += 1
                    if _telemetry.ENABLED:
                        _telemetry.record_admission(
                            stale.tenant,
                            "shed",
                            reason="deadline",
                            policy=self._admission.policy,
                            queue_depth=self._admission.depth(),
                            # The wait the expired batch actually paid —
                            # exactly the batches that waited longest
                            # must not vanish from the latency record.
                            wait_s=now - stale.enqueued_at,
                        )
                    if _metering.ENABLED:
                        _metering.record_submit(
                            stale.tenant,
                            "shed",
                            queue_depth=self._admission.depth(stale.tenant),
                        )
            if item is None:
                break
            if self._process(item):
                processed += 1
        return processed

    def _process(self, item: QueueItem) -> bool:
        with self._lock:
            session = self._registry.session(item.tenant)
            if session is None or session.state in (QUARANTINED, CLOSED):
                # Quarantined/closed after this item was queued (purge
                # raced the pop): drop it, don't execute it.
                self._counts["shed"] += 1
                if _telemetry.ENABLED:
                    _telemetry.record_admission(
                        item.tenant,
                        "shed",
                        reason="tenant-gone",
                        policy=self._admission.policy,
                        queue_depth=self._admission.depth(),
                        wait_s=time.monotonic() - item.enqueued_at,
                    )
                if _metering.ENABLED:
                    _metering.record_submit(
                        item.tenant,
                        "shed",
                        queue_depth=self._admission.depth(item.tenant),
                    )
                return False
            wait = time.monotonic() - item.enqueued_at
            self._waits.append(wait)
            self._counts["dispatched"] += 1
            if _telemetry.ENABLED:
                _telemetry.record_admission(
                    item.tenant,
                    "dispatched",
                    policy=self._admission.policy,
                    queue_depth=self._admission.depth(),
                    wait_s=wait,
                )
            self._ensure_resident(session)
            col = session.group.collection
            # donate=False keeps these refs alive: the free rollback
            # point the quarantine path restores from (a health
            # escalation fires AFTER the poisoned states installed).
            snapshot = col._read_states()
            t0 = time.monotonic()
            try:
                if _trace.ENABLED and item.trace_ctx is not None:
                    with _trace.activate(item.trace_ctx):
                        with _trace.span("serve.dispatch"):
                            self._registry.dispatch(
                                session, item.args, item.kwargs
                            )
                else:
                    self._registry.dispatch(session, item.args, item.kwargs)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as exc:  # noqa: BLE001 - isolation boundary
                col._install_states(snapshot, guard_deleted=True)
                self._quarantine(session, exc)
                return False
            session.batches += 1
            self._registry.touch(session)
            done = time.monotonic()
            if _telemetry.ENABLED:
                _telemetry.record_span(
                    "update",
                    "EvalService.dispatch",
                    done - t0,
                    0,
                )
            if _metering.ENABLED:
                _metering.record_dispatch(
                    item.tenant,
                    _metering.program_id(
                        (session.signature, session.group.width)
                    ),
                    rows=_metering.batch_rows(item.args),
                    seconds=done - t0,
                    wait_s=wait,
                    e2e_s=done - item.enqueued_at,
                    queue_depth=self._admission.depth(item.tenant),
                )
            self._maybe_spill(exclude=session)
            return True

    def _quarantine(self, session: Session, exc: BaseException) -> None:
        # Caller holds the lock and has already rolled the group's
        # states back to the pre-dispatch snapshot.
        reason = (
            "data-corruption"
            if isinstance(exc, DataCorruptionError)
            else "update-error"
        )
        session.state = QUARANTINED
        session.quarantine_reason = f"{type(exc).__name__}: {exc}"
        self._registry.release(session)
        purged = self._admission.purge(session.tenant)
        self._counts["quarantined"] += 1
        self._counts["shed"] += len(purged)
        if _telemetry.ENABLED:
            _telemetry.record_quarantine(
                session.tenant,
                reason,
                error=session.quarantine_reason,
                batches_dropped=len(purged),
            )
        if _metering.ENABLED:
            # The ledger survives quarantine: the tenant's pre-quarantine
            # device-time and shed history is exactly what a postmortem
            # needs.
            _metering.record_quarantine(session.tenant)
        if _flightrec.ENABLED:
            extra: Dict[str, Any] = {
                "serve": {
                    "tenant": session.tenant,
                    "reason": reason,
                    "error": session.quarantine_reason,
                    "batches_dropped": len(purged),
                    "batches_applied": session.batches,
                }
            }
            if _metering.ENABLED:
                extra["tenants"] = _metering.ledger_rows()
            _flightrec.trigger(
                "tenant_quarantine",
                f"tenant={session.tenant} {reason}",
                extra=extra,
            )

    # ------------------------------------------------------------- results
    def results(self, tenant: str) -> Dict[str, Any]:
        """``compute()`` over the tenant's seat (resuming it first if
        spilled).  Quarantined tenants raise with their reason."""
        with self._lock:
            session = self._registry.session(tenant)
            if session is None:
                raise KeyError(f"unknown tenant {tenant!r}")
            if session.state == QUARANTINED:
                raise RuntimeError(
                    f"tenant {tenant!r} is quarantined: "
                    f"{session.quarantine_reason}"
                )
            if session.state == CLOSED:
                raise RuntimeError(f"tenant {tenant!r} session is closed")
            self._ensure_resident(session)
            self._registry.touch(session)
            out = self._registry.compute(session)
            if _telemetry.ENABLED:
                for name, value in out.items():
                    try:
                        _telemetry.record_quality(
                            name,
                            slice_label=tenant,
                            window="lifetime",
                            value=float(value),
                            step=session.batches,
                        )
                    except (TypeError, ValueError):
                        pass  # non-scalar results don't ride the bus
            return out

    # --------------------------------------------------------------- spill
    def spill(self, tenant: str) -> None:
        """Explicitly checkpoint-and-evict one resident tenant."""
        with self._lock:
            session = self._registry.session(tenant)
            if session is None:
                raise KeyError(f"unknown tenant {tenant!r}")
            if session.state != ACTIVE:
                raise RuntimeError(
                    f"tenant {tenant!r} is not resident (state="
                    f"{session.state})"
                )
            if self._spill_root is None:
                raise RuntimeError(
                    "spill requires spill_dir= (or the "
                    "TORCHEVAL_TPU_SERVE_SPILL_DIR flag)"
                )
            self._spill_one(session)

    def adopt_spilled(
        self,
        tenant: str,
        metrics: Mapping[str, Metric],
        *,
        signature: Optional[Tuple[Any, ...]] = None,
    ) -> Session:
        """Register a tenant whose state already lives in this
        service's spill namespace (cluster failover / migration
        landing): the session is created directly in the SPILLED state
        and the next touch resumes it through the normal checkpoint
        path — bit-exact, via the same ``load_latest`` validation as
        any other resume."""
        with self._lock:
            if self._closed or self._draining:
                raise RuntimeError(
                    "EvalService is draining/closed; no new sessions"
                )
            if self._spill_root is None:
                raise RuntimeError(
                    "adopt_spilled requires spill_dir= (or the "
                    "TORCHEVAL_TPU_SERVE_SPILL_DIR flag)"
                )
            session = self._registry.open(
                tenant, metrics, signature=signature
            )
            self._registry.release(session)
            session.state = SPILLED
            if _telemetry.ENABLED:
                _telemetry.record_session("open", tenant)
            return session

    def resume(self, tenant: str) -> Session:
        """Force a spilled tenant resident now (the cluster needs the
        resumed batch cursor before applying routed batches).  No-op on
        an already-resident tenant."""
        with self._lock:
            session = self._registry.session(tenant)
            if session is None:
                raise KeyError(f"unknown tenant {tenant!r}")
            if session.state in (QUARANTINED, CLOSED):
                raise RuntimeError(
                    f"tenant {tenant!r} cannot resume (state="
                    f"{session.state})"
                )
            self._ensure_resident(session)
            self._registry.touch(session)
            return session

    def evict(self, tenant: str) -> None:
        """Forget a tenant WITHOUT deleting its spill namespace — the
        migration commit: the durable state now belongs to another
        host, so only the local seat and queue are torn down (contrast
        :meth:`close`, which prunes the namespace)."""
        with self._lock:
            session = self._registry.session(tenant)
            if session is None:
                raise KeyError(f"unknown tenant {tenant!r}")
            self._admission.purge(tenant)
            self._registry.forget(session)
            session.state = CLOSED
            if _telemetry.ENABLED:
                _telemetry.record_session("close", tenant)

    def _spill_one(self, session: Session) -> None:
        t0 = time.monotonic()
        flat = self._registry.seat_state_dict(session)
        manager = self._spill_root.namespace(session.tenant)
        path = manager.save(flat, {"batches_seen": session.batches})
        self._registry.release(session)
        session.state = SPILLED
        # tpulint: disable=TPU006 -- caller holds _lock: _spill_one is only reached from locked paths (spill/drain/evict)
        self._counts["spills"] += 1
        if _telemetry.ENABLED:
            _telemetry.record_session(
                "spill",
                session.tenant,
                generation=manager.generations()[-1],
                nbytes=os.path.getsize(path),
                seconds=time.monotonic() - t0,
            )
        if _metering.ENABLED:
            _metering.record_session("spill", session.tenant)

    def _maybe_spill(self, exclude: Optional[Session] = None) -> None:
        if self._spill_root is None or self._max_resident is None:
            return
        lru = self._registry.resident_lru()
        over = len(lru) - self._max_resident
        for session in lru:
            if over <= 0:
                break
            if session is exclude or session.state != ACTIVE:
                continue
            self._spill_one(session)
            over -= 1

    def _ensure_resident(self, session: Session) -> None:
        if session.state != SPILLED:
            return
        t0 = time.monotonic()
        self._registry.attach(session)
        checkpoint = None
        if self._spill_root is not None:
            checkpoint = self._spill_root.namespace(
                session.tenant
            ).load_latest()
        if checkpoint is not None:
            self._registry.load_seat(session, checkpoint.state)
            session.batches = int(
                checkpoint.cursor.get("batches_seen", session.batches)
            )
        elif _telemetry.ENABLED:
            # Spilled state unrecoverable (corrupt/missing generations):
            # the seat restarts from reset — operator-visible data loss.
            _telemetry.record_degraded(
                "serve.resume",
                f"tenant {session.tenant!r}: no valid spill checkpoint; "
                "seat reset",
                "data_loss",
            )
        self._counts["resumes"] += 1
        if _telemetry.ENABLED:
            _telemetry.record_session(
                "resume",
                session.tenant,
                generation=(
                    checkpoint.generation if checkpoint is not None else 0
                ),
                nbytes=checkpoint.nbytes if checkpoint is not None else 0,
                seconds=time.monotonic() - t0,
            )
        if _metering.ENABLED:
            _metering.record_session("resume", session.tenant)

    # --------------------------------------------------------------- drain
    def drain(self, deadline_s: Optional[float] = None) -> DrainResult:
        """Graceful shutdown: stop admission, pump the queue to empty,
        final-checkpoint every resident tenant, and close the service.
        Idempotent.

        ``deadline_s`` is a hard bound against a *stuck pump*: the
        queue is pumped on a helper thread joined against the budget,
        so a wedged dispatch cannot hang the caller.  On expiry the
        undispatched sessions are spilled through the checkpoint path
        (best effort — a dispatch still holding the lock blocks the
        rescue and is reported as ``stuck``) and a typed partial
        :class:`DrainResult` is returned instead of hanging."""
        t0 = time.monotonic()
        with self._lock:
            self._draining = True
        self.stop()
        deadline = None if deadline_s is None else t0 + deadline_s
        expired = False
        stuck = False
        if deadline is None:
            processed = 0
            while self.pump(1):
                processed += 1
        else:
            drained = threading.Event()
            abort = threading.Event()
            counter = {"n": 0}

            def _pump_to_empty() -> None:
                try:
                    while not abort.is_set() and self.pump(1):
                        counter["n"] += 1
                finally:
                    drained.set()

            helper = threading.Thread(
                target=_pump_to_empty,
                name="torcheval-tpu-drain",
                daemon=True,
            )
            helper.start()
            if not drained.wait(
                timeout=max(0.0, deadline - time.monotonic())
            ):
                expired = True
                abort.set()
                # One grace join: a helper BETWEEN items exits at the
                # abort check; one wedged INSIDE a dispatch stays stuck
                # and is leaked as a daemon (stop()'s contract).
                helper.join(timeout=_IDLE_TICK_S)
                stuck = helper.is_alive()
            processed = counter["n"]
        flushed = True
        spilled = 0
        unspilled: List[str] = []
        # The rescue spill must not hang either: a stuck dispatch holds
        # self._lock, so the acquire is bounded and failure is typed.
        locked = self._lock.acquire(timeout=_JOIN_TIMEOUT_S)
        if locked:
            try:
                for session in self._registry.resident_lru():
                    if session.state != ACTIVE:
                        continue
                    if self._spill_root is not None:
                        self._spill_one(session)
                        spilled += 1
                    elif expired:
                        # No checkpoint path configured: the expired
                        # drain can only NAME what it left behind.
                        unspilled.append(session.tenant)
                # tpulint: disable=TPU006 -- lock IS held: acquired via acquire(timeout=) above, released in the finally
                pending = self._admission.depth()
                # tpulint: disable=TPU006 -- lock IS held: acquired via acquire(timeout=) above, released in the finally
                self._closed = True
            finally:
                self._lock.release()
        else:
            stuck = True
            flushed = False
            # tpulint: disable=TPU006 -- gave-up path: the lock is wedged; depth() locks internally
            pending = self._admission.depth()
            # tpulint: disable=TPU006 -- gave-up path: the lock is wedged; a bool store is atomic and monotonic
            self._closed = True
            unspilled = [
                s.tenant
                for s in self._registry.sessions().values()
                if s.state == ACTIVE
            ]
        if stuck and _telemetry.ENABLED:
            _telemetry.record_degraded(
                "serve.drain",
                "drain deadline expired with a dispatch still wedged; "
                "pump helper leaked (daemon)",
                "leaked_thread",
            )
        if _telemetry.ENABLED:
            _telemetry.record_session(
                "drain", "", seconds=time.monotonic() - t0
            )
        return DrainResult(
            processed=processed,
            flushed=flushed and pending == 0 and not expired,
            pending=pending,
            expired=expired,
            spilled=spilled,
            unspilled=tuple(unspilled),
            stuck=stuck,
        )

    # -------------------------------------------------------------- worker
    def start(self) -> "EvalService":
        """Start the background pump thread (idempotent)."""
        with self._lock:
            if self._worker is not None:
                return self
            if self._closed:
                raise RuntimeError("EvalService is closed")
            self._stop_flag.clear()
            # contextvars do not flow into Thread targets; hand the
            # caller's trace context over explicitly (prefetch idiom).
            worker_ctx = _trace.capture() if _trace.ENABLED else None
            self._worker = threading.Thread(
                target=self._run,
                args=(worker_ctx,),
                name="torcheval-tpu-serve",
                daemon=True,
            )
            self._worker.start()
        return self

    def _run(self, worker_ctx: Any) -> None:
        if _trace.ENABLED:
            _trace.adopt(worker_ctx)
        while not self._stop_flag.is_set():
            if self.pump(16) == 0:
                self._wake.wait(timeout=_IDLE_TICK_S)
                self._wake.clear()

    def stop(self) -> None:
        """Stop and join the worker thread (idempotent)."""
        with self._lock:
            worker = self._worker
            self._worker = None
        if worker is None:
            return
        self._stop_flag.set()
        self._wake.set()
        worker.join(timeout=_JOIN_TIMEOUT_S)
        if worker.is_alive():
            # Daemon thread: the process can still exit, but a silent
            # leak would mask a wedged dispatch — report it.
            if _telemetry.ENABLED:
                _telemetry.record_degraded(
                    "serve.stop",
                    f"worker thread still alive after {_JOIN_TIMEOUT_S:g}s "
                    "join",
                    "leaked_thread",
                )
            warnings.warn(
                "EvalService.stop(): worker thread did not exit within "
                f"{_JOIN_TIMEOUT_S:g}s and was leaked (daemon). A metric "
                "dispatch is likely wedged.",
                RuntimeWarning,
                stacklevel=2,
            )

    def session(self, tenant: str) -> Optional[Session]:
        """The tenant's session record, or None (cluster placement and
        tests peek at lifecycle state without reaching into the
        registry)."""
        with self._lock:
            return self._registry.session(tenant)

    # --------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        """Host-side service counters (valid with telemetry off)."""
        with self._lock:
            states: Dict[str, int] = {}
            for session in self._registry.sessions().values():
                states[session.state] = states.get(session.state, 0) + 1
            info = self._registry.program_cache_info()
            return {
                "queue_depth": self._admission.depth(),
                "tenants": states,
                "groups": self._registry.group_count(),
                "programs": {
                    "currsize": info.currsize,
                    "hits": info.hits,
                    "misses": info.misses,
                    "evictions": info.evictions,
                },
                "admit_wait_p99_s": _p99(list(self._waits)),
                "counts": dict(self._counts),
            }
