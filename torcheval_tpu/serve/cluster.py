"""ServeCluster — the distributed serve plane (ISSUE 20 / ROADMAP 3).

One :class:`ServeCluster` per host turns the single-host
:class:`~torcheval_tpu.serve.service.EvalService` into a sharded fleet:

* **Placement** — tenants land on hosts via the consistent-hash ring in
  :mod:`~torcheval_tpu.serve.placement` (deterministic and
  membership-keyed: every host computes the same owner from the same
  alive set + migration overrides, no coordination round).
* **Routing** — ``submit()`` on a non-owner host frames the batch
  (:func:`~torcheval_tpu.distributed.pack_frames`: length-prefixed
  arrays, zero-copy unpack) and ships it to the owner over the
  group's p2p channel under the ``serve/`` tag namespace.  Acks are
  batched per peer and carry the owner's applied/durable cursors plus
  its :class:`~torcheval_tpu.serve.admission.AdmissionController`
  queue-depth/shed signals — the sender sheds locally once its route
  window fills or the owner reports shedding (backpressure, typed, no
  exception).
* **Exactly-once application** — every tenant's batches carry a
  monotone sequence number; the owner applies them in order, and its
  cursor is the session's dispatched-batch count — the SAME number the
  checkpoint manifest stores.  After any handoff the new owner resumes
  at cursor *c* and simply skips re-sent batches below *c*: duplicates
  are impossible by construction, and the applied stream is bit-exact.
* **Live migration** — a two-phase handoff on proven primitives: the
  owner spills through ``CheckpointManager.namespace(tenant)``,
  streams the checkpoint bytes + manifest p2p
  (:meth:`~torcheval_tpu.resilience.checkpoint.CheckpointManager.
  export_latest` / :meth:`import_blob` — a torn transfer is sha256-
  quarantined, never resumed), the target resumes and acks, and the
  placement override (versioned, max-wins) bumps the ring epoch.  A
  stale owner is fenced by the override version and by the cursor in
  the manifest.
* **Failover** — hosts gossip their placement state on every heartbeat
  and ack; a peer silent past the death timeout is excised
  (:class:`~torcheval_tpu.resilience.membership.MembershipView`) and
  the ring repairs around it.  A dead host's tenants resume from their
  durable spill namespace where one validates; sessions never spilled
  are reported ``lost`` — a typed :class:`~torcheval_tpu.serve.
  placement.PlacementOutcome`, never an exception escaping the
  cluster API.
* **Rebalancing** — a rebalancer thread consumes
  :func:`~torcheval_tpu.serve.metering.rebalance_hints` (hot/cold
  skew, shed rate, spill churn) and live-migrates the hottest local
  tenant toward the least-loaded survivor.

Fault sites (``resilience/faults.py``): ``serve.route`` fires per
placement decision (submit and owner-side apply) and ``serve.migrate``
per migration phase (``spill`` / ``stream`` / ``resume``) — an
``action="drop_rank"`` rule makes this host vanish mid-dispatch or
mid-migration, which is exactly what the chaos suite
(``tests/serve/test_cluster.py``) kills hosts with.

Tenant registration is symmetric: every host calls :meth:`open` with
the same metric *factory* (factories never cross the wire — they are
not picklable in general), so any host can resume any tenant after a
migration or repair.  One logical submitter per tenant is assumed (the
sequence numbers are per client stream).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional

from torcheval_tpu import _flags
from torcheval_tpu.distributed import (
    CollectiveGroup,
    PeerTimeoutError,
    pack_frames,
    serve_tag,
    unpack_frames,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.resilience import faults as _faults
from torcheval_tpu.resilience.checkpoint import (
    CheckpointBlob,
    CheckpointManager,
)
from torcheval_tpu.resilience.faults import DroppedRank, InjectedFault
from torcheval_tpu.resilience.membership import MembershipView
from torcheval_tpu.telemetry import events as _telemetry

import torcheval_tpu.serve.metering as _metering
from torcheval_tpu.serve.admission import Admitted, Shed
from torcheval_tpu.serve.placement import Placement, PlacementOutcome
from torcheval_tpu.serve.registry import (
    ACTIVE,
    CLOSED,
    QUARANTINED,
    SPILLED,
)
from torcheval_tpu.serve.service import EvalService

MetricFactory = Callable[[], Mapping[str, Metric]]

# Missed heartbeats before a silent peer is declared dead.
_DEATH_MISSES = 5

# Per-peer non-blocking poll budget (seconds) while draining the inbox.
_POLL_S = 0.0

# Default wait budget for a blocking migration / remote results call.
_DEFAULT_WAIT_S = 10.0


def _note_owner(tenant: str, owner: int) -> None:
    """Record the tenant's owning host in the attribution table (lazy:
    ``telemetry.tenants`` sits in the observe layer above serve; only
    placement changes land here, never the per-batch path)."""
    from torcheval_tpu.telemetry import tenants as _tenants

    _tenants.note_owner(tenant, str(owner))


class _ClientStream:
    """Sender-side state for one tenant routed to a remote owner."""

    __slots__ = (
        "next_seq",
        "frames",
        "applied",
        "durable",
        "owner",
        "remote_depth",
        "remote_shedding",
        "failed",
        "resend",
    )

    def __init__(self, owner: int) -> None:
        self.next_seq = 0
        # seq -> framed payload, retained until the owner reports the
        # state DURABLE past it (an applied-but-unspilled batch must be
        # re-drivable after the owner dies).
        self.frames: Dict[int, bytes] = {}
        self.applied = -1  # owner's applied-through cursor
        self.durable = -1  # owner's spilled-through cursor
        self.owner = owner
        self.remote_depth = 0
        self.remote_shedding = False
        self.failed = ""  # "lost" | "quarantined" | "rejected" | ""
        self.resend = False


class _OwnerStream:
    """Receiver-side state for one tenant this host owns."""

    __slots__ = ("buffer", "clients", "durable", "shedding")

    def __init__(self) -> None:
        # Out-of-order / backpressured arrivals parked until applicable.
        self.buffer: Dict[int, bytes] = {}
        self.clients: set = set()
        self.durable = -1
        self.shedding = False


class ServeCluster:
    """A sharded multi-tenant serve plane over one p2p-capable group.

    Drive it synchronously (:meth:`step` from your own loop — the chaos
    suite's deterministic mode) or with :meth:`start` /:meth:`stop`
    background router + rebalancer threads.  Every public method
    returns a typed :class:`PlacementOutcome`; no exception escapes.
    """

    def __init__(
        self,
        group: CollectiveGroup,
        *,
        spill_dir: str,
        vnodes: Optional[int] = None,
        route_window: Optional[int] = None,
        heartbeat_s: Optional[float] = None,
        death_timeout_s: Optional[float] = None,
        group_width: int = 8,
        admission: Optional[Any] = None,
        max_resident: Optional[int] = None,
    ) -> None:
        if not group.supports_p2p:
            raise ValueError(
                "ServeCluster needs a p2p-capable group "
                "(LocalGroup or JaxProcessGroup)"
            )
        self._group = group
        self._rank = group.rank
        self._world = group.world_size
        self._vnodes = (
            int(vnodes)
            if vnodes is not None
            else _flags.get("SERVE_VNODES")
        )
        self._route_window = (
            int(route_window)
            if route_window is not None
            else _flags.get("SERVE_ROUTE_WINDOW")
        )
        self._heartbeat_s = (
            float(heartbeat_s)
            if heartbeat_s is not None
            else _flags.get("SERVE_HEARTBEAT_MS") / 1e3
        )
        self._death_timeout_s = (
            float(death_timeout_s)
            if death_timeout_s is not None
            else _DEATH_MISSES * self._heartbeat_s
        )
        self._spill_dir = str(spill_dir)
        # The cluster's own handle on the durable tenant store — the
        # same directory the service spills into, reused for p2p
        # export/import and failover recovery checks.
        self._store = CheckpointManager(self._spill_dir)
        self._service = EvalService(
            group_width=group_width,
            admission=admission,
            spill_dir=self._spill_dir,
            max_resident=max_resident,
        )
        self._membership = MembershipView(self._world, self._rank)
        self._placement = Placement(self._world, vnodes=self._vnodes)
        self._lock = threading.RLock()
        self._factories: Dict[str, MetricFactory] = {}
        self._streams: Dict[str, _ClientStream] = {}
        self._apply: Dict[str, _OwnerStream] = {}
        self._lost: set = set()
        self._send_seq = [0] * self._world
        self._recv_seq = [0] * self._world
        self._last_heard: Dict[int, float] = {}
        self._last_hb = 0.0
        self._dead_self = False
        # peer -> {tenant: ack entry}; flushed once per step.
        self._pending_acks: Dict[int, Dict[str, Dict[str, Any]]] = {}
        # tenant -> in-flight migration bookkeeping (this host = source).
        self._migrating: Dict[str, Dict[str, Any]] = {}
        self._migration_s: List[float] = []
        self._results_replies: Dict[int, Dict[str, Any]] = {}
        # rids with a live waiter; replies for any other rid (waiter
        # timed out / redirected away) are dropped on arrival so the
        # reply dict cannot grow without bound.
        self._results_waiting: set = set()
        self._next_rid = 0
        self._counts: Dict[str, int] = {
            "routed": 0,
            "local": 0,
            "shed_window": 0,
            "shed_remote": 0,
            "shed_migrating": 0,
            "migrations": 0,
            "migrations_aborted": 0,
            "repairs": 0,
            "recovered": 0,
            "lost": 0,
            "redirects": 0,
        }
        self._router: Optional[threading.Thread] = None
        self._rebalancer: Optional[threading.Thread] = None
        self._stop_flag = threading.Event()

    # ------------------------------------------------------------ helpers
    @property
    def rank(self) -> int:
        return self._rank

    @property
    def service(self) -> EvalService:
        return self._service

    @property
    def placement(self) -> Placement:
        return self._placement

    @property
    def epoch(self) -> int:
        return self._placement.epoch

    def _outcome(
        self, tenant: str, action: str, owner: int = -1, **kw: Any
    ) -> PlacementOutcome:
        return PlacementOutcome(
            tenant=tenant,
            action=action,
            owner=owner,
            epoch=self._placement.epoch,
            **kw,
        )

    def _send(self, dst: int, msg: Dict[str, Any]) -> None:
        if self._dead_self or dst == self._rank:
            return
        if not self._membership.is_alive(dst):
            return
        seq = self._send_seq[dst]
        self._send_seq[dst] += 1
        # tpulint: disable=TPU007 -- fire-and-forget put (KV store / local mailbox): completes on this host, never waits on the peer
        self._group.send_object(
            msg, dst, serve_tag(f"m/{self._rank}/{dst}/{seq}")
        )

    def _gossip_payload(self) -> Dict[str, Any]:
        snap = self._placement.snapshot()
        return {
            "epoch": self._placement.epoch,
            "dead": snap["dead"],
            "ovr": snap["ovr"],
        }

    def _merge_gossip(self, msg: Mapping[str, Any]) -> None:
        dead = msg.get("dead") or ()
        for rank in dead:
            if int(rank) == self._rank:
                # The fleet thinks we are dead; believe it (a zombie
                # owner double-applying is worse than a clean exit).
                self.kill()
                return
        newly = [
            int(r) for r in dead if self._membership.is_alive(int(r))
        ]
        self._membership.merge_gossip(newly, reason="serve gossip")
        changed = self._placement.merge(dead, msg.get("ovr"))
        for rank in newly:
            self._repair(rank)
        if changed:
            self._reroute_streams()

    # ----------------------------------------------------------- sessions
    def open(
        self, tenant: str, factory: MetricFactory
    ) -> PlacementOutcome:
        """Register ``tenant`` fleet-wide.  Call on EVERY host with the
        same factory; the host the ring assigns opens the session
        locally, the rest just remember the factory so they can resume
        the tenant after a migration or repair."""
        # tpulint: disable=TPU006 -- _dead_self is a monotonic kill flag: the lock-free read is the zombie fence on the no-lock fast path
        if self._dead_self:
            return self._outcome(tenant, "dead")
        with self._lock:
            self._factories[tenant] = factory
            owner = self._placement.owner_of(tenant)
            if owner < 0:
                return self._outcome(
                    tenant, "dead", detail="no live hosts"
                )
            _note_owner(tenant, owner)
            if owner == self._rank:
                try:
                    if self._service.session(tenant) is None:
                        self._service.open(tenant, factory())
                except RuntimeError as exc:
                    return self._outcome(
                        tenant, "rejected", owner, detail=str(exc)
                    )
                return self._outcome(tenant, "local", owner)
            return self._outcome(tenant, "routed", owner)

    def close(self, tenant: str) -> PlacementOutcome:
        """Close ``tenant`` wherever it lives (local close, or a routed
        close message to the owner)."""
        # tpulint: disable=TPU006 -- _dead_self is a monotonic kill flag: the lock-free read is the zombie fence on the no-lock fast path
        if self._dead_self:
            return self._outcome(tenant, "dead")
        with self._lock:
            self._factories.pop(tenant, None)
            owner = self._placement.owner_of(tenant)
            if owner < 0:
                return self._outcome(
                    tenant, "dead", detail="no live hosts"
                )
            if owner == self._rank:
                try:
                    self._service.close(tenant)
                except KeyError:
                    return self._outcome(
                        tenant, "rejected", owner, detail="unknown-tenant"
                    )
                self._apply.pop(tenant, None)
                return self._outcome(tenant, "local", owner)
            self._send(owner, {"type": "cls", "t": tenant})
            self._streams.pop(tenant, None)
            return self._outcome(tenant, "routed", owner)

    # ----------------------------------------------------------- submit
    def submit(
        self, tenant: str, *args: Any, **kwargs: Any
    ) -> PlacementOutcome:
        """Offer one batch.  Local tenants go straight to the service;
        remote tenants are framed and routed to their owner, gated by
        the route window and the owner's backpressure signals."""
        # tpulint: disable=TPU006 -- _dead_self is a monotonic kill flag: the lock-free read is the zombie fence on the no-lock fast path
        if self._dead_self:
            return self._outcome(tenant, "dead")
        try:
            if _faults.ENABLED:
                _faults.fire(
                    "serve.route",
                    tenant=tenant,
                    rank=self._rank,
                    role="submit",
                )
        except DroppedRank:
            self.kill()
            return self._outcome(tenant, "dead", detail="dropped")
        except InjectedFault as exc:
            return self._outcome(tenant, "shed", detail=str(exc))
        with self._lock:
            if tenant in self._lost:
                return self._outcome(
                    tenant, "lost", detail="unspilled on dead host"
                )
            owner = self._placement.owner_of(tenant)
            if owner < 0:
                return self._outcome(
                    tenant, "dead", detail="no live hosts"
                )
            if owner == self._rank:
                if tenant in self._migrating:
                    # A two-phase handoff is in flight: the spill
                    # cursor already streamed to the target, and the
                    # commit evicts this seat WITHOUT re-spilling — a
                    # locally admitted batch would vanish.  Routed
                    # submits survive via client-side frame retention;
                    # local ones have no retention, so shed typed
                    # until the handoff commits or aborts.
                    self._counts["shed_migrating"] += 1
                    return self._outcome(
                        tenant, "shed", owner, detail="migrating"
                    )
                return self._submit_local(tenant, args, kwargs)
            stream = self._streams.get(tenant)
            if stream is None:
                stream = self._streams[tenant] = _ClientStream(owner)
            if stream.failed:
                return self._outcome(
                    tenant, "rejected", owner, detail=stream.failed
                )
            inflight = stream.next_seq - 1 - stream.applied
            if inflight >= self._route_window:
                self._counts["shed_window"] += 1
                return self._outcome(
                    tenant, "shed", owner, detail="route-window"
                )
            if stream.remote_shedding:
                self._counts["shed_remote"] += 1
                # One shot per signal: the next ack refreshes it.
                stream.remote_shedding = False
                return self._outcome(
                    tenant, "shed", owner, detail="remote-shed"
                )
            payload = pack_frames(args, kwargs)
            seq = stream.next_seq
            stream.next_seq += 1
            stream.frames[seq] = payload
            stream.owner = owner
            self._send(
                owner,
                {"type": "sub", "t": tenant, "q": seq, "f": payload},
            )
            self._counts["routed"] += 1
            if _telemetry.ENABLED:
                _telemetry.record_placement(
                    "route",
                    tenant,
                    src=self._rank,
                    dst=owner,
                    epoch=self._placement.epoch,
                )
            return self._outcome(tenant, "routed", owner)

    def _submit_local(
        self, tenant: str, args: tuple, kwargs: Dict[str, Any]
    ) -> PlacementOutcome:
        # Caller holds the lock.
        try:
            out = self._service.submit(tenant, *args, **kwargs)
        except DroppedRank:
            self.kill()
            return self._outcome(tenant, "dead", detail="dropped")
        except InjectedFault as exc:
            return self._outcome(
                tenant, "shed", self._rank, detail=str(exc)
            )
        self._counts["local"] += 1
        if isinstance(out, Admitted):
            return self._outcome(tenant, "local", self._rank, value=out)
        if isinstance(out, Shed):
            return self._outcome(
                tenant, "shed", self._rank, detail=out.reason, value=out
            )
        return self._outcome(
            tenant, "rejected", self._rank, detail=out.reason, value=out
        )

    # ----------------------------------------------------------- results
    def results(
        self, tenant: str, *, timeout_s: float = _DEFAULT_WAIT_S
    ) -> PlacementOutcome:
        """``compute()`` for ``tenant`` wherever it lives.  Remote
        owners are queried over p2p (the call drives :meth:`step` while
        it waits).  ``value`` carries the metric dict on success."""
        # tpulint: disable=TPU006 -- _dead_self is a monotonic kill flag: the lock-free read is the zombie fence on the no-lock fast path
        if self._dead_self:
            return self._outcome(tenant, "dead")
        with self._lock:
            if tenant in self._lost:
                return self._outcome(
                    tenant, "lost", detail="unspilled on dead host"
                )
            owner = self._placement.owner_of(tenant)
            if owner < 0:
                return self._outcome(
                    tenant, "dead", detail="no live hosts"
                )
            if owner == self._rank:
                return self._local_results(tenant, owner)
            rid = self._next_rid
            self._next_rid += 1
            self._results_waiting.add(rid)
            self._send(owner, {"type": "res", "t": tenant, "rid": rid})
        deadline = time.monotonic() + timeout_s
        try:
            while time.monotonic() < deadline:
                self.step()
                with self._lock:
                    reply = self._results_replies.pop(rid, None)
                    if reply is not None:
                        if reply.get("ok"):
                            return self._outcome(
                                tenant, "local", owner, value=reply["val"]
                            )
                        return self._outcome(
                            tenant,
                            reply.get("action", "rejected"),
                            owner,
                            detail=reply.get("detail", ""),
                        )
                    if tenant in self._lost:
                        return self._outcome(
                            tenant, "lost", detail="owner died"
                        )
                    new_owner = self._placement.owner_of(tenant)
                # tpulint: disable=TPU006 -- _dead_self is a monotonic kill flag: the lock-free read is the zombie fence on the no-lock fast path
                if self._dead_self:
                    return self._outcome(tenant, "dead")
                if new_owner != owner:
                    return self.results(
                        tenant,
                        timeout_s=max(0.0, deadline - time.monotonic()),
                    )
                time.sleep(0.001)
            return self._outcome(tenant, "timeout", owner)
        finally:
            # Every exit path (reply consumed, timeout, redirect
            # recursion, host death) retires the rid so a late reply
            # is dropped at the door instead of leaking.
            with self._lock:
                self._results_waiting.discard(rid)
                self._results_replies.pop(rid, None)

    def _local_results(self, tenant: str, owner: int) -> PlacementOutcome:
        try:
            value = self._service.results(tenant)
        except KeyError:
            return self._outcome(
                tenant, "rejected", owner, detail="unknown-tenant"
            )
        except RuntimeError as exc:
            return self._outcome(
                tenant, "rejected", owner, detail=str(exc)
            )
        return self._outcome(tenant, "local", owner, value=value)

    # ---------------------------------------------------------- migration
    def migrate(
        self,
        tenant: str,
        target: int,
        *,
        wait: bool = True,
        timeout_s: float = _DEFAULT_WAIT_S,
    ) -> PlacementOutcome:
        """Two-phase live handoff of ``tenant`` to ``target``: spill →
        stream bytes p2p → target resumes and acks → override commits
        and the epoch bumps.  The source keeps serving until commit;
        an aborted handoff (target died, torn transfer, injected
        fault) leaves the tenant bit-exact at the source."""
        # tpulint: disable=TPU006 -- _dead_self is a monotonic kill flag: the lock-free read is the zombie fence on the no-lock fast path
        if self._dead_self:
            return self._outcome(tenant, "dead")
        t0 = time.monotonic()
        with self._lock:
            if (
                target == self._rank
                or not self._membership.is_alive(target)
            ):
                return self._outcome(
                    tenant, "aborted", detail="bad target"
                )
            if self._placement.owner_of(tenant) != self._rank:
                return self._outcome(
                    tenant,
                    "aborted",
                    self._placement.owner_of(tenant),
                    detail="not owner",
                )
            if tenant in self._migrating:
                return self._outcome(
                    tenant, "aborted", detail="migration in flight"
                )
            session = self._service.session(tenant)
            if session is None or session.state in (QUARANTINED, CLOSED):
                return self._outcome(
                    tenant, "aborted", detail="no migratable session"
                )
            try:
                if _faults.ENABLED:
                    _faults.fire(
                        "serve.migrate",
                        tenant=tenant,
                        phase="spill",
                        rank=self._rank,
                        target=target,
                    )
                # Flush whatever is queued, then checkpoint: the spill
                # cursor IS the handoff cursor.
                self._service.pump()
                if session.state != SPILLED:
                    self._service.spill(tenant)
                if _faults.ENABLED:
                    _faults.fire(
                        "serve.migrate",
                        tenant=tenant,
                        phase="stream",
                        rank=self._rank,
                        target=target,
                    )
            except DroppedRank:
                self.kill()
                return self._outcome(tenant, "dead", detail="dropped")
            except (InjectedFault, RuntimeError) as exc:
                self._counts["migrations_aborted"] += 1
                return self._outcome(tenant, "aborted", detail=str(exc))
            blob = self._store.namespace(tenant).export_latest()
            if blob is None:
                self._counts["migrations_aborted"] += 1
                return self._outcome(
                    tenant, "aborted", detail="nothing durable to stream"
                )
            version = self._placement.override_version(tenant) + 1
            stream = self._apply.get(tenant)
            if stream is not None:
                stream.durable = max(
                    stream.durable,
                    int(blob.manifest["cursor"].get("batches_seen", 0))
                    - 1,
                )
            self._send(
                target,
                {
                    "type": "mig",
                    "t": tenant,
                    "g": blob.generation,
                    "m": blob.manifest,
                    "p": blob.payload,
                    "v": version,
                },
            )
            self._migrating[tenant] = {
                "target": target,
                "version": version,
                "t0": t0,
                "deadline": t0 + timeout_s,
                # The handoff cursor: the target resumes exactly here,
                # so the commit can seed this host's client stream at
                # a sequence the target's duplicate fence accepts.
                "cursor": int(
                    blob.manifest["cursor"].get("batches_seen", 0)
                ),
            }
        if not wait:
            return self._outcome(
                tenant, "routed", target, detail="migration started"
            )
        while True:
            self.step()
            with self._lock:
                if self._dead_self:
                    return self._outcome(tenant, "dead")
                entry = self._migrating.get(tenant)
                if entry is None:
                    if self._placement.owner_of(tenant) == target:
                        return self._outcome(tenant, "migrated", target)
                    return self._outcome(
                        tenant, "aborted", detail="handoff rejected"
                    )
                if time.monotonic() > entry["deadline"]:
                    self._abort_migration(tenant, "timeout")
                    return self._outcome(
                        tenant, "aborted", detail="timeout"
                    )
            time.sleep(0.001)

    def _abort_migration(self, tenant: str, why: str) -> None:
        # Caller holds the lock.  The source spilled before streaming,
        # so the session resumes bit-exact on next touch — nothing to
        # roll back.
        self._migrating.pop(tenant, None)
        self._counts["migrations_aborted"] += 1
        if _telemetry.ENABLED:
            _telemetry.record_degraded(
                "serve.migrate",
                f"tenant {tenant!r} handoff aborted: {why}",
                "migration_aborted",
            )

    # --------------------------------------------------------- rebalancer
    def rebalance_once(
        self, *, min_gap: int = 2
    ) -> List[PlacementOutcome]:
        """One rebalance pass: consume ``serve.rebalance_hints()`` and
        live-migrate the hottest local tenant (device-seconds, then
        queue depth, then shed rate, then spill churn) to the
        least-loaded survivor when the owned-tenant census is skewed by
        at least ``min_gap``."""
        # tpulint: disable=TPU006 -- _dead_self is a monotonic kill flag: the lock-free read is the zombie fence on the no-lock fast path
        if self._dead_self:
            return []
        hints = _metering.rebalance_hints()
        with self._lock:
            alive = self._placement.alive
            if len(alive) < 2:
                return []
            census = {r: 0 for r in alive}
            for tenant in self._factories:
                if tenant in self._lost:
                    continue
                owner = self._placement.owner_of(tenant)
                if owner in census:
                    census[owner] += 1
            coldest = min(
                (r for r in alive if r != self._rank),
                key=lambda r: (census[r], r),
            )
            if census[self._rank] - census[coldest] < min_gap:
                return []
            mine = [
                s
                for s in hints.tenants
                if s.tenant in self._factories
                and s.tenant not in self._lost
                and s.tenant not in self._migrating
                and self._placement.owner_of(s.tenant) == self._rank
                and self._service.session(s.tenant) is not None
            ]
            if not mine:
                return []
            hottest = max(
                mine,
                key=lambda s: (
                    s.device_seconds,
                    s.queue_depth,
                    s.shed_rate,
                    s.spill_churn,
                ),
            )
        return [self.migrate(hottest.tenant, coldest)]

    # -------------------------------------------------------------- step
    def step(self) -> int:
        """Drive the router once: drain the inbox, re-drive parked
        frames, pump the local service, flush batched acks, heartbeat,
        and check for dead peers.  Returns the number of messages
        handled.  Safe from any thread; a ``drop_rank`` fault kills
        this host typed, never raising."""
        # tpulint: disable=TPU006 -- caller holds _lock (documented contract of _poll_inbox)
        if self._dead_self:
            return 0
        try:
            with self._lock:
                handled = self._poll_inbox()
                if self._dead_self:
                    return handled
                self._retry_buffered()
                if self._service.pump():
                    # Local dispatch advanced remote tenants' cursors;
                    # refresh their acks.
                    for tenant, stream in self._apply.items():
                        for client in stream.clients:
                            self._queue_ack(client, tenant)
                self._checkpoint_routed()
                self._flush_acks()
                self._resend_marked()
                now = time.monotonic()
                if now - self._last_hb >= self._heartbeat_s:
                    self._last_hb = now
                    hb = {"type": "hb", **self._gossip_payload()}
                    for peer in self._placement.alive:
                        if peer != self._rank:
                            self._send(peer, hb)
                self._check_deaths(now)
            return handled
        except DroppedRank:
            self.kill()
            return 0

    def _poll_inbox(self) -> int:
        # Caller holds the lock (recv with timeout=0 never blocks, so
        # holding it across the drain is fine and keeps the per-peer
        # receive cursors race-free under router + waiter threads).
        handled = 0
        for peer in range(self._world):
            if peer == self._rank or not self._membership.is_alive(peer):
                continue
            while True:
                tag = serve_tag(
                    f"m/{peer}/{self._rank}/{self._recv_seq[peer]}"
                )
                try:
                    # tpulint: disable=TPU007 -- bounded: timeout=_POLL_S (0.0) makes this a non-blocking poll, never an unbounded wait
                    msg = self._group.recv_object(
                        peer, tag, timeout=_POLL_S
                    )
                except PeerTimeoutError:
                    break
                self._recv_seq[peer] += 1
                self._last_heard[peer] = time.monotonic()
                self._handle(msg, peer)
                handled += 1
                if self._dead_self:
                    return handled
        return handled

    def _handle(self, msg: Dict[str, Any], src: int) -> None:
        kind = msg.get("type")
        if kind == "sub":
            self._handle_submit(msg, src)
        elif kind == "ack":
            self._handle_ack(msg, src)
        elif kind == "hb":
            self._merge_gossip(msg)
        elif kind == "mig":
            self._handle_migrate(msg, src)
        elif kind == "migack":
            self._handle_migrate_ack(msg, src)
        elif kind == "res":
            self._handle_results_request(msg, src)
        elif kind == "resr":
            rid = int(msg["rid"])
            if rid in self._results_waiting:
                self._results_replies[rid] = msg
        elif kind == "cls":
            tenant = msg.get("t", "")
            if self._service.session(tenant) is not None:
                try:
                    self._service.close(tenant)
                except (KeyError, RuntimeError):
                    pass
            self._apply.pop(tenant, None)

    # ------------------------------------------------------ owner side
    def _handle_submit(self, msg: Dict[str, Any], src: int) -> None:
        tenant = msg["t"]
        seq = int(msg["q"])
        if tenant in self._lost:
            self._queue_ack(src, tenant, status="lost")
            return
        owner = self._placement.owner_of(tenant)
        if owner != self._rank:
            self._counts["redirects"] += 1
            self._queue_ack(src, tenant, status="redirect", owner=owner)
            return
        stream = self._apply.get(tenant)
        if stream is None:
            stream = self._apply[tenant] = _OwnerStream()
        stream.clients.add(src)
        stream.buffer[seq] = msg["f"]
        try:
            if _faults.ENABLED:
                # DroppedRank propagates to step(): a host dying
                # mid-dispatch, with batches in its inbox.
                _faults.fire(
                    "serve.route",
                    tenant=tenant,
                    rank=self._rank,
                    role="apply",
                )
        except DroppedRank:
            raise
        except InjectedFault:
            # Frame stays parked; the retry sweep re-drives it.
            return
        self._queue_ack(src, tenant, status=self._apply_buffered(tenant))

    def _apply_buffered(self, tenant: str) -> str:
        """Apply the tenant's parked frames strictly in sequence order
        against the session's batch cursor.  Returns the ack status."""
        stream = self._apply[tenant]
        session = self._service.session(tenant)
        if session is None:
            factory = self._factories.get(tenant)
            if factory is None:
                return "rejected"
            try:
                if (
                    self._store.namespace(tenant).export_latest()
                    is not None
                ):
                    self._service.adopt_spilled(tenant, factory())
                else:
                    self._service.open(tenant, factory())
            except RuntimeError:
                return "rejected"
        if self._service.session(tenant).state == QUARANTINED:
            return "quarantined"
        try:
            session = self._service.resume(tenant)
        except (KeyError, RuntimeError):
            return "rejected"
        # Drop re-sent frames the resumed cursor already covers — the
        # duplicate fence after any handoff or failover.
        for seq in [s for s in stream.buffer if s < session.batches]:
            stream.buffer.pop(seq)
        while session.batches in stream.buffer:
            expected = session.batches
            payload = stream.buffer.pop(expected)
            args, kwargs = unpack_frames(payload)
            try:
                out = self._service.submit(tenant, *args, **kwargs)
            except DroppedRank:
                raise
            except InjectedFault:
                stream.buffer[expected] = payload
                stream.shedding = True
                return "ok"
            if isinstance(out, Admitted):
                self._service.pump()
                if session.state == QUARANTINED:
                    return "quarantined"
                if session.batches != expected + 1:
                    # Not dispatched this round (shed at pop / tenant
                    # gone): park the frame and retry next step.
                    stream.buffer[expected] = payload
                    stream.shedding = True
                    return "ok"
            elif isinstance(out, Shed):
                stream.buffer[expected] = payload
                stream.shedding = True
                return "ok"
            else:  # Rejected
                return "rejected"
        if not stream.buffer:
            stream.shedding = False
        return "ok"

    def _checkpoint_routed(self) -> None:
        # Caller holds the lock.  Senders retain every routed frame
        # until the durable cursor passes it, and the service only
        # spills on idle pressure or drain — a long-lived routed
        # tenant would pin the sender's memory forever.  Bound the
        # retention: once a route window's worth of applied-but-
        # unspilled batches accumulates, checkpoint the tenant so the
        # next ack carries an advanced durable cursor and clients
        # release their frames.  (The next routed frame transparently
        # resumes the session through the normal spill path.)
        for tenant, stream in self._apply.items():
            if not stream.clients or tenant in self._migrating:
                continue
            session = self._service.session(tenant)
            if session is None or session.state != ACTIVE:
                continue
            if session.batches - 1 - stream.durable < self._route_window:
                continue
            try:
                self._service.spill(tenant)
            except (KeyError, RuntimeError):
                continue
            stream.durable = max(stream.durable, session.batches - 1)
            for client in stream.clients:
                self._queue_ack(client, tenant)

    def _retry_buffered(self) -> None:
        # Frames parked by backpressure or injected routing faults get
        # re-driven once per step.
        for tenant in list(self._apply):
            stream = self._apply.get(tenant)
            if stream is None or not stream.buffer:
                continue
            if self._placement.owner_of(tenant) != self._rank:
                continue
            status = self._apply_buffered(tenant)
            for client in list(stream.clients):
                self._queue_ack(client, tenant, status=status)

    def _queue_ack(
        self,
        dst: int,
        tenant: str,
        status: str = "ok",
        owner: int = -1,
    ) -> None:
        entry: Dict[str, Any] = {"t": tenant, "s": status}
        if status == "redirect":
            entry["o"] = owner
        session = self._service.session(tenant)
        if session is not None:
            entry["a"] = session.batches - 1
        stream = self._apply.get(tenant)
        if stream is not None:
            if session is not None and session.state == SPILLED:
                # The service checkpointed this tenant (idle spill,
                # drain, explicit spill): the manifest cursor covers
                # every dispatched batch, so the durable cursor
                # advances and senders can release retained frames.
                stream.durable = max(
                    stream.durable, session.batches - 1
                )
            entry["d"] = stream.durable
            # The owner's AdmissionController backpressure signals ride
            # every ack back to the sender.
            entry["sh"] = stream.shedding
        entry["qd"] = self._service._admission.depth(tenant)
        self._pending_acks.setdefault(dst, {})[tenant] = entry

    def _flush_acks(self) -> None:
        if not self._pending_acks:
            return
        gossip = self._gossip_payload()
        for dst, entries in self._pending_acks.items():
            if not self._membership.is_alive(dst):
                continue
            self._send(
                dst,
                {"type": "ack", "e": list(entries.values()), **gossip},
            )
        self._pending_acks.clear()

    # ------------------------------------------------------ client side
    def _handle_ack(self, msg: Dict[str, Any], src: int) -> None:
        for entry in msg.get("e", ()):
            tenant = entry["t"]
            stream = self._streams.get(tenant)
            if stream is None:
                continue
            status = entry.get("s", "ok")
            if status == "lost":
                stream.failed = "lost"
                self._lost.add(tenant)
                continue
            if status in ("quarantined", "rejected"):
                stream.failed = status
                continue
            if status == "redirect":
                new_owner = int(entry.get("o", -1))
                if new_owner >= 0 and new_owner != stream.owner:
                    if new_owner == self._rank:
                        self._adopt_local_stream(tenant, stream)
                    else:
                        self._redirect_stream(tenant, stream, new_owner)
                continue
            if "a" in entry:
                stream.applied = max(stream.applied, int(entry["a"]))
            if "d" in entry:
                stream.durable = max(stream.durable, int(entry["d"]))
                for seq in [
                    s for s in stream.frames if s <= stream.durable
                ]:
                    stream.frames.pop(seq)
            stream.remote_depth = int(entry.get("qd", 0))
            stream.remote_shedding = bool(entry.get("sh", False))
        self._merge_gossip(msg)

    def _redirect_stream(
        self, tenant: str, stream: _ClientStream, new_owner: int
    ) -> None:
        stream.owner = new_owner
        # Conservative cursor reset: the new owner resumed from the
        # durable spill; everything after it is re-driven from the
        # retained frames (the owner's cursor fence drops what its
        # checkpoint already covers).
        stream.applied = stream.durable
        stream.resend = True

    def _adopt_local_stream(
        self, tenant: str, stream: _ClientStream
    ) -> None:
        """The ring moved a tenant WE were routing onto this host: hand
        the retained frames to the owner-side buffer (same duplicate
        fence) and apply them locally."""
        self._streams.pop(tenant, None)
        if tenant in self._lost or stream.failed:
            return
        ostream = self._apply.setdefault(tenant, _OwnerStream())
        for seq, payload in stream.frames.items():
            ostream.buffer.setdefault(seq, payload)
        self._apply_buffered(tenant)

    def _reroute_streams(self) -> None:
        for tenant, stream in list(self._streams.items()):
            if stream.failed:
                continue
            owner = self._placement.owner_of(tenant)
            if owner == self._rank:
                self._adopt_local_stream(tenant, stream)
            elif owner >= 0 and owner != stream.owner:
                self._redirect_stream(tenant, stream, owner)

    def _resend_marked(self) -> None:
        for tenant, stream in self._streams.items():
            if not stream.resend or stream.failed:
                continue
            stream.resend = False
            for seq in sorted(stream.frames):
                self._send(
                    stream.owner,
                    {
                        "type": "sub",
                        "t": tenant,
                        "q": seq,
                        "f": stream.frames[seq],
                    },
                )

    # ------------------------------------------------- migration (wire)
    def _handle_migrate(self, msg: Dict[str, Any], src: int) -> None:
        tenant = msg["t"]
        version = int(msg["v"])
        reply = {"type": "migack", "t": tenant, "v": version, "ok": False}
        try:
            if _faults.ENABLED:
                # A target dying mid-migration: the blob arrived but
                # the resume never happens — the source aborts and the
                # tenant stays bit-exact at the source.
                _faults.fire(
                    "serve.migrate",
                    tenant=tenant,
                    phase="resume",
                    rank=self._rank,
                    target=self._rank,
                )
        except DroppedRank:
            raise
        except InjectedFault as exc:
            reply["why"] = str(exc)
            self._send(src, reply)
            return
        if self._placement.override_version(tenant) >= version:
            reply["why"] = "stale"
            self._send(src, reply)
            return
        factory = self._factories.get(tenant)
        if factory is None:
            reply["why"] = "unknown tenant"
            self._send(src, reply)
            return
        blob = CheckpointBlob(
            generation=int(msg["g"]),
            manifest=dict(msg["m"]),
            payload=msg["p"],
        )
        t0 = time.monotonic()
        if not self._store.namespace(tenant).import_blob(blob):
            # Torn transfer: quarantined by import_blob; never resumed.
            reply["why"] = "torn transfer"
            self._send(src, reply)
            return
        session = self._service.session(tenant)
        if session is None:
            try:
                self._service.adopt_spilled(tenant, factory())
            except RuntimeError as exc:
                reply["why"] = str(exc)
                self._send(src, reply)
                return
        try:
            session = self._service.resume(tenant)
        except (KeyError, RuntimeError) as exc:
            reply["why"] = str(exc)
            self._send(src, reply)
            return
        self._placement.note_migration(tenant, self._rank, version)
        _note_owner(tenant, self._rank)
        stream = self._apply.setdefault(tenant, _OwnerStream())
        stream.durable = max(stream.durable, session.batches - 1)
        self._streams.pop(tenant, None)
        if _telemetry.ENABLED:
            _telemetry.record_placement(
                "migrate",
                tenant,
                src=src,
                dst=self._rank,
                epoch=self._placement.epoch,
                generation=int(msg["g"]),
                seconds=time.monotonic() - t0,
            )
        reply["ok"] = True
        self._send(src, reply)

    def _handle_migrate_ack(self, msg: Dict[str, Any], src: int) -> None:
        tenant = msg["t"]
        entry = self._migrating.get(tenant)
        if (
            entry is None
            or src != entry["target"]
            or int(msg.get("v", -1)) != entry["version"]
        ):
            # A stale ack (an earlier timed-out attempt, or a peer
            # that was never this migration's target) must not touch
            # the in-flight handoff's bookkeeping.
            return
        self._migrating.pop(tenant, None)
        if not msg.get("ok"):
            self._abort_migration(tenant, msg.get("why", "nack"))
            return
        self._placement.note_migration(
            tenant, entry["target"], entry["version"]
        )
        _note_owner(tenant, entry["target"])
        try:
            self._service.evict(tenant)
        except KeyError:
            pass
        self._apply.pop(tenant, None)
        if tenant not in self._streams:
            # This host's own submits now route to the target.  The
            # sequence numbers must line up with the target's resumed
            # batch cursor (its duplicate fence drops anything below
            # it), and the source knows that cursor exactly — it is
            # the spill cursor it streamed in phase one.
            stream = self._streams[tenant] = _ClientStream(
                entry["target"]
            )
            cursor = int(entry.get("cursor", 0))
            stream.next_seq = cursor
            stream.applied = cursor - 1
            stream.durable = cursor - 1
        self._counts["migrations"] += 1
        self._migration_s.append(time.monotonic() - entry["t0"])

    # ------------------------------------------------------ results wire
    def _handle_results_request(
        self, msg: Dict[str, Any], src: int
    ) -> None:
        tenant = msg["t"]
        rid = int(msg["rid"])
        reply: Dict[str, Any] = {"type": "resr", "rid": rid, "ok": False}
        if tenant in self._lost:
            reply["action"] = "lost"
        elif self._placement.owner_of(tenant) != self._rank:
            reply["action"] = "rejected"
            reply["detail"] = "not owner"
        else:
            out = self._local_results(tenant, self._rank)
            if out.action == "local":
                reply["ok"] = True
                reply["val"] = out.value
            else:
                reply["action"] = out.action
                reply["detail"] = out.detail
        self._send(src, reply)

    # ------------------------------------------------------ failure paths
    def _check_deaths(self, now: float) -> None:
        for peer in range(self._world):
            if peer == self._rank or not self._membership.is_alive(peer):
                continue
            first = self._last_heard.setdefault(peer, now)
            if now - first <= self._death_timeout_s:
                continue
            self._membership.excise(
                peer,
                f"serve heartbeat: silent {now - first:.3f}s",
            )
            self._placement.exclude(peer)
            self._repair(peer)
            self._reroute_streams()

    def _repair(self, dead: int) -> None:
        """Ring repair after ``dead`` was excised: adopt every tenant
        the survivors' ring now assigns HERE, resuming from the durable
        spill namespace when one validates and reporting the rest
        lost.  Surviving tenants' placements are untouched (the
        consistent-hash guarantee)."""
        self._counts["repairs"] += 1
        epoch = self._placement.epoch
        if _telemetry.ENABLED:
            _telemetry.record_placement(
                "repair", "", src=dead, dst=self._rank, epoch=epoch
            )
        # In-flight migrations addressed at the dead host abort (the
        # source spilled first, so the tenant resumes here bit-exact).
        for tenant in [
            t
            for t, e in self._migrating.items()
            if e["target"] == dead
        ]:
            self._abort_migration(tenant, f"target {dead} died")
        for tenant, factory in self._factories.items():
            if tenant in self._lost:
                continue
            if self._placement.owner_of(tenant) != self._rank:
                continue
            if self._service.session(tenant) is not None:
                continue
            _note_owner(tenant, self._rank)
            blob = self._store.namespace(tenant).export_latest()
            if blob is not None:
                try:
                    self._service.adopt_spilled(tenant, factory())
                except RuntimeError:
                    continue
                stream = self._apply.setdefault(tenant, _OwnerStream())
                stream.durable = max(
                    stream.durable,
                    int(blob.manifest["cursor"].get("batches_seen", 0))
                    - 1,
                )
                self._counts["recovered"] += 1
                if _telemetry.ENABLED:
                    _telemetry.record_placement(
                        "recovered",
                        tenant,
                        src=dead,
                        dst=self._rank,
                        epoch=epoch,
                        generation=blob.generation,
                    )
            else:
                # Never spilled before its host died: the only state
                # the repair cannot reconstruct.
                self._lost.add(tenant)
                self._counts["lost"] += 1
                if _telemetry.ENABLED:
                    _telemetry.record_placement(
                        "lost",
                        tenant,
                        src=dead,
                        dst=self._rank,
                        epoch=epoch,
                    )

    def kill(self) -> None:
        """Declare THIS host dead (chaos hook / zombie fencing): stop
        responding entirely.  Peers excise it after the death timeout
        and repair the ring around it."""
        # tpulint: disable=TPU006 -- kill() must never block on the router's lock; a bool store is atomic and monotonic
        self._dead_self = True
        self._stop_flag.set()

    @property
    def is_dead(self) -> bool:
        # tpulint: disable=TPU006 -- single racy bool read, same contract as every hook site's plain attribute read
        return self._dead_self

    # ------------------------------------------------------------ threads
    def start(
        self, *, rebalance_interval_s: Optional[float] = None
    ) -> "ServeCluster":
        """Start the background router thread (and, when an interval is
        given, the rebalancer thread consuming ``rebalance_hints()``).
        Idempotent."""
        with self._lock:
            if self._router is not None:
                return self
            self._stop_flag.clear()
            self._router = threading.Thread(
                target=self._router_loop,
                name=f"torcheval-tpu-serve-router-{self._rank}",
                daemon=True,
            )
            self._router.start()
            if rebalance_interval_s is not None:
                self._rebalancer = threading.Thread(
                    target=self._rebalancer_loop,
                    args=(float(rebalance_interval_s),),
                    name=f"torcheval-tpu-serve-rebalance-{self._rank}",
                    daemon=True,
                )
                self._rebalancer.start()
        return self

    def _router_loop(self) -> None:
        while not self._stop_flag.is_set():
            if self.step() == 0:
                time.sleep(min(0.002, self._heartbeat_s / 4))

    def _rebalancer_loop(self, interval_s: float) -> None:
        while not self._stop_flag.wait(timeout=interval_s):
            self.rebalance_once()

    def stop(self) -> None:
        """Stop and join the background threads (idempotent)."""
        self._stop_flag.set()
        for thread in (self._router, self._rebalancer):
            if thread is not None:
                thread.join(timeout=5.0)
        self._router = None
        self._rebalancer = None

    # -------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        """Host-side cluster counters (valid with telemetry off)."""
        with self._lock:
            lat = sorted(self._migration_s)
            p99 = (
                lat[max(0, int(len(lat) * 0.99) - 1)] if lat else 0.0
            )
            return {
                "rank": self._rank,
                "epoch": self._placement.epoch,
                "fingerprint": self._placement.fingerprint(),
                "alive": list(self._placement.alive),
                "dead": list(self._placement.dead),
                "lost": sorted(self._lost),
                "owned": sorted(
                    t
                    for t in self._factories
                    if self._placement.owner_of(t) == self._rank
                    and t not in self._lost
                ),
                "migration_p99_s": p99,
                "migration_count": len(lat),
                "counts": dict(self._counts),
                "service": self._service.stats(),
            }
