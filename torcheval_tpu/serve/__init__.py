"""Multi-tenant eval serving: coalesced sessions under admission
control.

The serve layer turns the sliced-collection machinery into a
long-running, overload-safe service: many tenants' metric suites
coalesce by signature onto shared fused programs
(:mod:`~torcheval_tpu.serve.registry`), bursts are absorbed by bounded
queues with typed shed outcomes
(:mod:`~torcheval_tpu.serve.admission`), and a poison tenant is
quarantined — rolled back, purged, reported — without perturbing its
neighbours (:mod:`~torcheval_tpu.serve.service`).  Idle sessions spill
to checkpoints and resume transparently.

See ``docs/source/serve.rst`` for the operating model and runbooks.
"""

from torcheval_tpu.serve.admission import (
    POLICIES,
    Admitted,
    AdmissionController,
    Rejected,
    Shed,
)
from torcheval_tpu.serve.registry import (
    DEFAULT_GROUP_WIDTH,
    Session,
    SessionRegistry,
    TenantGroup,
    signature_of,
)
from torcheval_tpu.serve.service import EvalService

__all__ = [
    "Admitted",
    "AdmissionController",
    "DEFAULT_GROUP_WIDTH",
    "EvalService",
    "POLICIES",
    "Rejected",
    "Session",
    "SessionRegistry",
    "Shed",
    "TenantGroup",
    "signature_of",
]
