"""Multi-tenant eval serving: coalesced sessions under admission
control.

The serve layer turns the sliced-collection machinery into a
long-running, overload-safe service: many tenants' metric suites
coalesce by signature onto shared fused programs
(:mod:`~torcheval_tpu.serve.registry`), bursts are absorbed by bounded
queues with typed shed outcomes
(:mod:`~torcheval_tpu.serve.admission`), and a poison tenant is
quarantined — rolled back, purged, reported — without perturbing its
neighbours (:mod:`~torcheval_tpu.serve.service`).  Idle sessions spill
to checkpoints and resume transparently.  When the per-tenant ledger is
on (:mod:`~torcheval_tpu.serve.metering`), :func:`rebalance_hints`
reads it back as typed placement signals — queue depth, shed rate,
spill churn, attributed device-seconds — plus a noisy-neighbour
verdict.

The distributed tier (:mod:`~torcheval_tpu.serve.cluster` +
:mod:`~torcheval_tpu.serve.placement`) shards tenants across hosts on
a consistent-hash ring, routes batches p2p with backpressure, migrates
sessions live through the checkpoint path, and repairs the ring around
dead hosts — every action a typed :class:`PlacementOutcome`.

See ``docs/source/serve.rst`` for the operating model and runbooks.
"""

from torcheval_tpu.serve import metering
from torcheval_tpu.serve.admission import (
    POLICIES,
    Admitted,
    AdmissionController,
    Rejected,
    Shed,
)
from torcheval_tpu.serve.cluster import ServeCluster
from torcheval_tpu.serve.metering import (
    RebalanceHints,
    TenantSignal,
    rebalance_hints,
)
from torcheval_tpu.serve.placement import (
    HashRing,
    Placement,
    PlacementOutcome,
)
from torcheval_tpu.serve.registry import (
    DEFAULT_GROUP_WIDTH,
    Session,
    SessionRegistry,
    TenantGroup,
    signature_of,
)
from torcheval_tpu.serve.service import DrainResult, EvalService

__all__ = [
    "Admitted",
    "AdmissionController",
    "DEFAULT_GROUP_WIDTH",
    "DrainResult",
    "EvalService",
    "HashRing",
    "POLICIES",
    "Placement",
    "PlacementOutcome",
    "RebalanceHints",
    "Rejected",
    "ServeCluster",
    "Session",
    "SessionRegistry",
    "Shed",
    "TenantGroup",
    "TenantSignal",
    "metering",
    "rebalance_hints",
    "signature_of",
]
